"""Brute-force top-K over an embedding corpus — the compute half of
retrieval serving.

`TopKIndex` stages one (immutable) corpus's lane-row table on device and
answers masked dot/cosine top-K through jitted bucket-padded programs:
one compiled program per (query-bucket, k) pair, reused across requests,
with the score kernel behind the `paged_topk_score` impl discipline
(ops/pallas_kernels.py — 'xla' jitted reference is the `auto` fallback
and A/B oracle, the Pallas form is interpret-validated).

Bit-determinism contract (PARITY.md "Retrieval scoring"):

  * scoring operands are significand-truncated to 12 bits (corpus.py
    `quantize_sig12` — corpus rows at build time, queries here), so
    every q*x product is EXACT in f32 and FMA contraction cannot
    perturb it;
  * scores accumulate strictly left-to-right in f32 (the kernel's
    contract), so they are bit-identical across impls and vs NumPy;
  * ties break (score desc, id asc): corpus rows are sorted by id
    ascending and `lax.top_k` prefers the lower index on equal values;
  * filtered retrieval masks scores to -inf BEFORE selection, so a
    filter can only remove candidates, never perturb surviving scores.

`numpy_topk_oracle` is the independent pure-NumPy implementation of the
same spec (its own normalization loop, scoring loop, and lexsort
selection — no JAX, no shared code path) and `merge_topk` is the
canonical-order heap merge the router uses to fuse per-shard answers;
fleet == single shard == oracle bitwise is pinned in
tests/test_retrieval.py.
"""

from __future__ import annotations

import heapq

import numpy as np

from euler_tpu.retrieval.corpus import (
    INVALID_ID,
    EmbeddingCorpus,
    normalize_rows,
    quantize_sig12,
)

# query-batch buckets: requests pad up to the smallest fitting bucket so
# a steady mix of batch sizes compiles a handful of programs, not one
# per distinct B; beyond the largest bucket, pad to its next multiple
BUCKETS = (1, 4, 16, 64)


def bucket_for(b: int, buckets=BUCKETS) -> int:
    for cand in buckets:
        if b <= cand:
            return cand
    top = buckets[-1]
    return -(-b // top) * top


class TopKIndex:
    """Jitted bucket-padded top-K over one staged EmbeddingCorpus."""

    def __init__(self, corpus: EmbeddingCorpus, impl: str = "auto",
                 buckets=BUCKETS):
        import jax.numpy as jnp

        self.corpus = corpus
        self.impl = impl
        self.buckets = tuple(buckets)
        self._n = corpus.num_rows
        self._dp = corpus.dim_padded
        # the paged HBM table: staged once per corpus version, shared by
        # every program (the hot-swap unit is the whole TopKIndex)
        self._table2d = jnp.asarray(corpus.lane_rows()) if self._n else None
        self._all_rows = np.ones(max(self._n, 1), dtype=bool)
        self._programs: dict[tuple[int, int], object] = {}

    def _program(self, bp: int, keff: int):
        key = (bp, keff)
        fn = self._programs.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            from euler_tpu.ops.pallas_kernels import paged_topk_score

            n, dp, impl = self._n, self._dp, self.impl

            @jax.jit
            def run(table2d, q, mask):
                scores = paged_topk_score(table2d, q, n, dp, impl=impl)
                scores = jnp.where(mask[None, :], scores, -jnp.inf)
                return jax.lax.top_k(scores, keff)

            self._programs[key] = fn = run
        return fn

    def warmup(self, k: int, buckets=None) -> int:
        """Compile the (bucket, k) programs off the serving path — the
        hot-swap discipline builds + warms the NEW index here before the
        engine reference flips. Returns programs compiled."""
        before = len(self._programs)
        if self._n:
            keff = min(int(k), self._n)
            probe = np.zeros((1, self.corpus.dim), np.float32)
            for b in buckets or self.buckets:
                self.search(np.repeat(probe, b, axis=0), keff)
        return len(self._programs) - before

    def search(self, q: np.ndarray, k: int, mask: np.ndarray | None = None):
        """(ids u64[B, k], scores f32[B, k], valid bool[B, k]) — the
        top-k rows per query in canonical (score desc, id asc) order;
        under-filled slots carry INVALID_ID / -inf / False."""
        import jax.numpy as jnp

        q = np.ascontiguousarray(q, dtype=np.float32)
        if q.ndim != 2 or q.shape[1] != self.corpus.dim:
            raise ValueError(
                f"queries must be [B, {self.corpus.dim}], got {q.shape}"
            )
        b, k = q.shape[0], int(k)
        if k <= 0:
            raise ValueError(f"k must be positive, got {k}")
        ids = np.full((b, k), INVALID_ID, dtype=np.uint64)
        scores = np.full((b, k), -np.inf, dtype=np.float32)
        valid = np.zeros((b, k), dtype=bool)
        if b == 0 or self._n == 0:
            return ids, scores, valid
        if self.corpus.metric == "cosine":
            q = normalize_rows(q)
        q = quantize_sig12(q)  # exact-product scoring canon (corpus.py)
        if self._dp != q.shape[1]:
            q = np.pad(q, ((0, 0), (0, self._dp - q.shape[1])))
        bp = bucket_for(b, self.buckets)
        if bp != b:
            q = np.pad(q, ((0, bp - b), (0, 0)))
        keff = min(k, self._n)
        m = self._all_rows if mask is None else np.asarray(mask, dtype=bool)
        vals, idx = self._program(bp, keff)(
            self._table2d, jnp.asarray(q), jnp.asarray(m)
        )
        vals = np.asarray(vals)[:b]
        idx = np.asarray(idx)[:b]
        ok = vals > -np.inf
        ids[:, :keff] = np.where(
            ok, self.corpus.ids[np.clip(idx, 0, self._n - 1)], INVALID_ID
        )
        scores[:, :keff] = vals
        valid[:, :keff] = ok
        return ids, scores, valid


def numpy_topk_oracle(ids, vectors, q, k, metric="dot", mask=None):
    """INDEPENDENT reference: the PARITY.md retrieval-scoring spec in
    pure NumPy (no JAX, no shared scoring code) — left-to-right f32
    score accumulation, canonical cosine normalization, lexsort
    (score desc, id asc) selection. `mask` (optional bool) is aligned
    with the input row order. Returns the same (ids, scores, valid)
    triple as TopKIndex.search; bitwise equality against the served
    path is the retrieval parity claim."""
    ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
    x = np.ascontiguousarray(vectors, dtype=np.float32)
    # private copy: the cosine branch normalizes in place
    q = np.array(q, dtype=np.float32, order="C", copy=True)
    keep = np.ones(len(ids), dtype=bool) if mask is None else (
        np.asarray(mask, dtype=bool).copy()
    )
    order = np.argsort(ids, kind="stable")
    ids, x, keep = ids[order], x[order], keep[order]
    if metric == "cosine":
        for arr in (x, q):
            nrm2 = np.zeros(arr.shape[0], dtype=np.float32)
            for d in range(arr.shape[1]):
                nrm2 = nrm2 + arr[:, d] * arr[:, d]
            inv = np.ones_like(nrm2)
            ok = nrm2 > 0
            inv[ok] = np.float32(1.0) / np.sqrt(nrm2[ok])
            arr *= inv[:, None]
    elif metric != "dot":
        raise ValueError(f"unknown metric {metric!r}")
    # exact-product canon: truncate significands to 12 bits (own bit
    # expression of the corpus.py spec constant) so every product below
    # is exact in f32 and the sum order is the only rounding story
    x = (x.view(np.uint32) & np.uint32(0xFFFFF000)).view(np.float32)
    q = (
        np.ascontiguousarray(q).view(np.uint32) & np.uint32(0xFFFFF000)
    ).view(np.float32)
    b, n, k = q.shape[0], len(ids), int(k)
    out_ids = np.full((b, k), INVALID_ID, dtype=np.uint64)
    out_scores = np.full((b, k), -np.inf, dtype=np.float32)
    out_valid = np.zeros((b, k), dtype=bool)
    if n == 0:
        return out_ids, out_scores, out_valid
    scores = np.zeros((b, n), dtype=np.float32)
    for d in range(x.shape[1]):
        scores = scores + q[:, d][:, None] * x[:, d][None, :]
    scores = np.where(keep[None, :], scores, np.float32(-np.inf))
    take = min(k, n)
    for i in range(b):
        top = np.lexsort((ids, -scores[i]))[:take]
        s = scores[i][top]
        ok = s > -np.inf
        out_ids[i, :take] = np.where(ok, ids[top], INVALID_ID)
        out_scores[i, :take] = s
        out_valid[i, :take] = ok
    return out_ids, out_scores, out_valid


def merge_topk(parts, k: int):
    """Fuse per-shard top-k answers into the global top-k, per query.

    `parts` is a list of (ids, scores, valid) triples, each [B, k_s]
    and already in canonical (score desc, id asc) order — exactly what
    TopKIndex.search returns. A k-way heap merge in the same canonical
    order makes the fleet answer bit-identical to a single-shard search
    over the union corpus: shard scores are per-row (independent of
    co-resident rows), shards partition the rows, and each shard
    returning its own top k means the global top k is always inside the
    merged candidate set."""
    if not parts:
        raise ValueError("merge_topk needs at least one shard answer")
    b = parts[0][0].shape[0]
    k = int(k)
    out_ids = np.full((b, k), INVALID_ID, dtype=np.uint64)
    out_scores = np.full((b, k), -np.inf, dtype=np.float32)
    out_valid = np.zeros((b, k), dtype=bool)
    def _stream(ids_row, scores_row, valid_row):
        # a def, not a genexp: lazy genexps close over the part-loop
        # variables by reference and would all read the LAST shard
        for j, s in enumerate(scores_row):
            if valid_row[j]:
                yield (float(-s), int(ids_row[j]))

    for i in range(b):
        streams = [
            _stream(ids_p[i], scores_p[i], valid_p[i])
            for ids_p, scores_p, valid_p in parts
        ]
        for slot, (neg, nid) in enumerate(heapq.merge(*streams)):
            if slot >= k:
                break
            out_ids[i, slot] = np.uint64(nid)
            out_scores[i, slot] = np.float32(-neg)
            out_valid[i, slot] = True
    return out_ids, out_scores, out_valid
