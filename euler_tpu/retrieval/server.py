"""RetrievalServer — `retrieve`/`corpus_stats`/`reload_corpus` wire
verbs over the pooled-TCP stack.

One server owns ONE row shard of an embedding corpus (id % num_parts ==
part) behind the graph service's `_PoolServer` (selector thread + bounded
worker pool, no coordinator threads — a retrieval shard never fans out).
Scoring runs through a `_CorpusEngine`: an immutable (corpus shard,
staged TopKIndex, bounded DNF-mask cache) unit, published by a single
reference assignment — the serving hot-swap discipline (PR 7 `swap()`):

  * `reload_corpus` builds + warms the NEW engine in the one pool worker
    running the verb while every other worker keeps answering from the
    old engine, then flips `self._engine`. The outgoing engine is
    RETAINED as `self._prev`, so during a rolling fleet reload a router
    that pins a version (trailing `version` arg on `retrieve`) can still
    be answered consistently by shards that already swapped — the fix
    for mixed-version merges, not a cache.
  * canary queries ride the LIVE retrieve path pre/post swap; the
    reported `canary_parity` is a bit-level proof (True iff the corpus
    version did not actually change).

Verbs:
  retrieve      [q f32[B, D], k, dnf_json|None, tenant|None, version|None]
                                  → [ids u64[B,k], scores f32[B,k],
                                     valid u8[B,k], version str]
  corpus_stats  []                → [json]
  ping          []                → [0]
  reload_corpus [source_json|None, canary_q f32[C, D]|None, k|None]
                                  → [json report]

Deadline/overload rejections ride the typed err-frame vocabulary
(distributed/errors.py): already-expired work is rejected before
dispatch by `_PoolServer`, per-tenant admission raises `OverloadError`
naming the tenant, and a pinned `version` neither engine holds raises a
deterministic "corpus version skew" error the router resolves by
re-pinning (never a transport retry).
"""

from __future__ import annotations

import collections
import json
import threading
import time

import numpy as np

from euler_tpu.distributed.service import _PoolServer
from euler_tpu.retrieval.corpus import EmbeddingCorpus
from euler_tpu.retrieval.topk import TopKIndex
from euler_tpu.serving.batcher import TenantQuota


class _CorpusEngine:
    """Immutable serving unit: one corpus shard, its staged top-K
    programs, and a bounded cache of compiled DNF candidate masks
    (deterministic per corpus version, so caching is pure memoization)."""

    MASK_CACHE = 64

    def __init__(self, corpus: EmbeddingCorpus, impl: str = "auto"):
        self.corpus = corpus
        self.index = TopKIndex(corpus, impl=impl)
        self._masks: collections.OrderedDict = collections.OrderedDict()
        self._mask_lock = threading.Lock()

    def warm(self, k: int):
        self.index.warmup(k)
        return self

    def mask_for(self, dnf_json: str | None):
        if not dnf_json:
            return None
        with self._mask_lock:
            mask = self._masks.get(dnf_json)
            if mask is not None:
                self._masks.move_to_end(dnf_json)
                return mask
        mask = self.corpus.condition_mask(json.loads(dnf_json))
        with self._mask_lock:
            self._masks[dnf_json] = mask
            while len(self._masks) > self.MASK_CACHE:
                self._masks.popitem(last=False)
        return mask

    def retrieve(self, q: np.ndarray, k: int, dnf_json: str | None):
        return self.index.search(q, k, self.mask_for(dnf_json))


class RetrievalServer:
    """Serves one corpus row shard over the wire protocol."""

    def __init__(
        self,
        corpus: EmbeddingCorpus | None = None,
        loader=None,
        part: int = 0,
        num_parts: int = 1,
        host: str = "127.0.0.1",
        port: int = 0,
        workers: int | None = None,
        registry=None,
        impl: str = "auto",
        warm_k: int = 16,
        tenant_quota: TenantQuota | None = None,
    ):
        """`loader(source: dict | None) -> EmbeddingCorpus` produces the
        FULL corpus (reload calls it again with the wire `source`); the
        server keeps only its row shard. A prebuilt `corpus` (already
        full — it is sharded here) serves without a loader, but then
        `reload_corpus` needs a loader to have been given too."""
        if corpus is None and loader is None:
            raise ValueError("need a corpus or a loader")
        self._loader = loader
        self.part, self.num_parts = int(part), int(num_parts)
        self.impl = impl
        self.warm_k = int(warm_k)
        full = corpus if corpus is not None else loader(None)
        self._engine = self._build_engine(full)
        self._prev: _CorpusEngine | None = None
        self._swap_lock = threading.Lock()
        self.reloads = 0
        self.may_coordinate = False  # _PoolServer: no coordinator threads
        if tenant_quota is None:  # graftlint: disable=lock-racy-init -- __init__ local, pre-publication
            tenant_quota = TenantQuota.from_env()
        self.tenant_quota = tenant_quota
        if workers is None:  # graftlint: disable=lock-racy-init -- __init__ local, pre-publication
            import os

            # like the model server: workers park on device compute, so
            # size for concurrency, not cores
            workers = min(64, max(8, (os.cpu_count() or 1) * 2))
        self.server = _PoolServer((host, port), self, workers)
        self.host, self.port = self.server.server_address
        self.registry = registry
        self._beat = None
        self._started = time.monotonic()
        self.retrieves = 0
        # per-verb wire byte counters (filled by _PoolServer at the
        # socket seam, same telemetry stance as the other services)
        self.wire_bytes_in: collections.Counter = collections.Counter()
        self.wire_bytes_out: collections.Counter = collections.Counter()

    def _build_engine(self, full: EmbeddingCorpus) -> _CorpusEngine:
        shard = (
            full.shard(self.part, self.num_parts)
            if self.num_parts > 1
            else full
        )
        return _CorpusEngine(shard, impl=self.impl).warm(self.warm_k)

    # -- lifecycle -------------------------------------------------------

    def start(self):
        self.server.start()
        if self.registry is not None:
            self._beat = self.registry.register(
                self.part, self.host, self.port
            )
        return self

    def stop(self, drain_s: float | None = None):
        if self._beat is not None:
            self._beat.set()
        if drain_s:
            self.server.drain(drain_s)
        self.server.shutdown()
        self.server.server_close()

    # -- _PoolServer service surface -------------------------------------

    # Load-bearing: dispatch() gates on it, graftlint's wire-protocol
    # checker diffs it against the `op ==` arms and the retrieval
    # client/router WIRE_VERBS, and tests/test_wire_parity.py asserts
    # parity at runtime.
    HANDLED_VERBS = frozenset(
        {"retrieve", "corpus_stats", "ping", "reload_corpus"}
    )

    def is_coordinator(self, op: str) -> bool:
        return False

    def dispatch(self, op: str, a: list) -> list:
        if op not in self.HANDLED_VERBS:
            raise ValueError(f"unknown op {op!r}")
        if op == "retrieve":
            return self._retrieve(a)
        if op == "corpus_stats":
            return [json.dumps(self._stats())]
        if op == "ping":
            return [0]
        if op == "reload_corpus":
            return [json.dumps(self._reload(a))]
        raise RuntimeError(
            f"op {op!r} is in HANDLED_VERBS but has no dispatch arm"
        )

    def _engine_for(self, version: str | None) -> _CorpusEngine:
        eng = self._engine  # ONE read: request-coherent snapshot
        if version is None or eng.corpus.version == version:
            return eng
        prev = self._prev
        if prev is not None and prev.corpus.version == version:
            return prev
        raise ValueError(
            "corpus version skew: "
            f"want {version} have {eng.corpus.version}"
            + (f" prev {prev.corpus.version}" if prev is not None else "")
        )

    def _retrieve(self, a: list) -> list:
        q = np.asarray(a[0], dtype=np.float32)
        k = int(a[1])
        dnf_json = a[2] if len(a) > 2 else None
        tenant = a[3] if len(a) > 3 else None
        version = a[4] if len(a) > 4 else None
        if tenant is not None and self.tenant_quota is not None:
            self.tenant_quota.admit(tenant)  # raises typed OverloadError
        try:
            eng = self._engine_for(version)
            ids, scores, valid = eng.retrieve(q, k, dnf_json)
            self.retrieves += 1
            return [ids, scores, valid.astype(np.uint8), eng.corpus.version]
        finally:
            if tenant is not None and self.tenant_quota is not None:
                self.tenant_quota.release(tenant)

    def _stats(self) -> dict:
        eng = self._engine
        prev = self._prev
        out = {
            "shard": self.part,
            "num_parts": self.num_parts,
            "retrieves": self.retrieves,
            "reloads": self.reloads,
            "uptime_s": round(time.monotonic() - self._started, 3),
            "programs": len(eng.index._programs),
            "prev_version": prev.corpus.version if prev else None,
            "wire_bytes_in": dict(self.wire_bytes_in),
            "wire_bytes_out": dict(self.wire_bytes_out),
        }
        if self.tenant_quota is not None:
            out["tenants"] = self.tenant_quota.stats()
        out.update(eng.corpus.stats())
        return out

    def _reload(self, a: list) -> dict:
        """Hot-swap to a freshly loaded corpus version with a canary
        bit-parity proof through the live retrieve path."""
        source = json.loads(a[0]) if a and a[0] else None
        canary = a[1] if len(a) > 1 else None
        canary_k = int(a[2]) if len(a) > 2 and a[2] is not None else 4
        if self._loader is None:
            raise ValueError("reload_corpus: server was built without a loader")
        pre = None
        if canary is not None and len(canary):
            canary = np.asarray(canary, np.float32)
            pre = self._engine.retrieve(canary, canary_k, None)
        with self._swap_lock:
            old = self._engine
            t0 = time.monotonic()
            # build + warm OFF the dispatch path: every other worker keeps
            # serving `old` until the single reference flip below
            new = self._build_engine(self._loader(source))
            build_s = time.monotonic() - t0
            self._prev = old
            self._engine = new  # atomic publish
            self.reloads += 1
        report = {
            "from_version": old.corpus.version,
            "to_version": new.corpus.version,
            "rows": new.corpus.num_rows,
            "build_s": round(build_s, 4),
            "swapped": new.corpus.version != old.corpus.version,
        }
        if pre is not None:
            # canary through `new` — the engine THIS reload published —
            # not self._engine, which a concurrent reload may have flipped
            # to a third version between our publish and this read (the
            # parity verdict must describe our swap, not someone else's)
            post = new.retrieve(canary, canary_k, None)
            report["canary_n"] = int(len(canary))
            report["canary_parity"] = bool(
                all(np.array_equal(x, y) for x, y in zip(pre, post))
            )
        return report
