"""Fast synthetic graph construction (array-direct, no JSON round-trip).

Used by bench.py and scale tests: builds the columnar shard arrays directly
so multi-million-edge graphs materialize in seconds.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.graph.meta import FeatureSpec, GraphMeta
from euler_tpu.graph.store import Graph, GraphStore


def synthetic_meta(
    feat_dim: int, label_dim: int, num_partitions: int
) -> GraphMeta:
    return GraphMeta(
        name="synthetic",
        num_partitions=num_partitions,
        num_node_types=1,
        num_edge_types=1,
        node_features={
            "feat": FeatureSpec("feat", "dense", 0, feat_dim),
            "label": FeatureSpec("label", "dense", 1, label_dim),
        },
        edge_features={},
    )


def shard_arrays(
    p: int,
    num_nodes: int,
    out_degree: int,
    feat_dim: int,
    label_dim: int,
    num_partitions: int,
    rng: np.random.Generator,
    centers: np.ndarray | None = None,
    weighted: bool = False,
) -> dict:
    """Columnar arrays for shard p of the random regular digraph.

    Nodes 1..N owned by `id % num_partitions`; node i belongs to cluster
    (i % label_dim); features are a noisy cluster signature so supervised
    heads have signal to learn. Exposed separately from `random_graph` so
    scale tooling can build/write one shard at a time without holding the
    whole graph in memory. `centers` [label_dim, feat_dim] must be shared
    across every shard of one graph (random_graph derives it from the
    seed); None spawns an independent child stream off `rng` so the
    cluster signatures stay seed-controlled without perturbing the main
    draw sequence.
    """
    all_ids = np.arange(1, num_nodes + 1, dtype=np.uint64)
    ids = all_ids[all_ids % num_partitions == p]
    n = len(ids)
    e = n * out_degree
    dst = rng.integers(1, num_nodes + 1, size=e).astype(np.uint64)
    cluster = (ids.astype(np.int64) % label_dim).astype(np.int64)
    if centers is None:
        centers = rng.spawn(1)[0].normal(0.0, 4.0, (label_dim, feat_dim))
    feat = centers[cluster] + rng.normal(0.0, 1.0, size=(n, feat_dim))
    label = np.eye(label_dim, dtype=np.float32)[cluster]
    # weighted=True: non-unit edge weights in [0.5, 2.0) — exercises the
    # weighted-lean wire and weighted alias sampling (a uniform-weight
    # graph silently skips both)
    ew = (
        rng.uniform(0.5, 2.0, size=e).astype(np.float32)
        if weighted
        else np.ones(e, dtype=np.float32)
    )

    arrays = {
        "node_ids": ids,
        "node_types": np.zeros(n, dtype=np.int32),
        "node_weights": np.ones(n, dtype=np.float32),
        "edge_src": np.repeat(ids, out_degree),
        "edge_dst": dst,
        "edge_types": np.zeros(e, dtype=np.int32),
        "edge_weights": ew,
        "adj_0_indptr": np.arange(0, e + 1, out_degree, dtype=np.int64),
        "adj_0_dst": dst,
        "adj_0_w": ew,
        "adj_0_eidx": np.arange(e, dtype=np.int64),
        "nf_dense_0": feat.astype(np.float32),
        "nf_dense_1": label,
        "glabel_indptr": np.zeros(1, dtype=np.int64),
        "glabel_nodes": np.zeros(0, dtype=np.uint64),
    }
    # in-adjacency: only edges whose dst lands in this shard
    in_sel = (dst % num_partitions) == p if num_partitions > 1 else slice(None)
    in_dst = dst[in_sel]
    in_src = arrays["edge_src"][in_sel]
    rows = np.searchsorted(ids, in_dst)
    rows = np.clip(rows, 0, max(n - 1, 0))
    ok = (n > 0) & (ids[rows] == in_dst) if n else np.zeros(0, bool)
    rows, in_src = rows[ok], in_src[ok]
    order = np.argsort(rows, kind="stable")
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.add.at(indptr, rows + 1, 1)
    arrays["inadj_0_indptr"] = np.cumsum(indptr)
    arrays["inadj_0_dst"] = in_src[order]
    arrays["inadj_0_w"] = ew[in_sel][ok][order] if weighted else np.ones(
        len(rows), dtype=np.float32
    )
    arrays["inadj_0_eidx"] = np.full(len(rows), -1, dtype=np.int64)
    return arrays


def random_graph(
    num_nodes: int = 10000,
    out_degree: int = 15,
    feat_dim: int = 32,
    label_dim: int = 2,
    num_partitions: int = 1,
    seed: int = 0,
    weighted: bool = False,
) -> Graph:
    """Uniform random regular digraph with cluster-separable features."""
    rng = np.random.default_rng(seed)
    meta = synthetic_meta(feat_dim, label_dim, num_partitions)
    centers = rng.normal(0.0, 4.0, (label_dim, feat_dim))
    shards = []
    for p in range(num_partitions):
        arrays = shard_arrays(
            p, num_nodes, out_degree, feat_dim, label_dim, num_partitions,
            rng, centers, weighted=weighted,
        )
        n = len(arrays["node_ids"])
        meta.node_weight_sums.append([float(n)])
        meta.edge_weight_sums.append(
            [float(arrays["edge_weights"].sum())]
        )
        shards.append(GraphStore(meta, arrays, part=p))
    return Graph(meta, shards)
