from euler_tpu.datasets.base import Dataset, cache_dir  # noqa: F401
from euler_tpu.datasets.catalog import (  # noqa: F401
    DATASETS,
    KGDataset,
    PlanetoidDataset,
    SageDataset,
    TUDataset,
    get_dataset,
)
from euler_tpu.datasets.synthetic import random_graph  # noqa: F401
