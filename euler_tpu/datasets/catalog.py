"""Dataset catalog (tf_euler/python/dataset/* parity): cora, citeseer,
pubmed (Planetoid), ppi, reddit (GraphSAGE json/npy), mutag (TU graph
classification), fb15k / fb15k237 / wn18 (KG triples)."""

from __future__ import annotations

import json
import os

import numpy as np

from euler_tpu.datasets.base import Dataset, _planted_partition_json


class PlanetoidDataset(Dataset):
    """cora / citeseer / pubmed from the classic Planetoid pickles."""

    sizes = {
        "cora": (2708, 1433, 7),
        "citeseer": (3327, 3703, 6),
        "pubmed": (19717, 500, 3),
    }

    def __init__(self, name: str, **kw):
        self.name = name
        n, f, c = self.sizes[name]
        self.num_nodes, self.feature_dim, self.num_classes = n, f, c
        super().__init__(**kw)

    def raw_files(self):
        parts = ["x", "y", "tx", "ty", "allx", "ally", "graph", "test.index"]
        return [f"ind.{self.name}.{p}" for p in parts]

    def build_json(self) -> dict:
        import pickle

        def load(part):
            path = os.path.join(self.root, f"ind.{self.name}.{part}")
            if part == "test.index":
                return np.loadtxt(path, dtype=np.int64)
            with open(path, "rb") as f:
                return pickle.load(f, encoding="latin1")

        x, y, tx, ty, allx, ally = (
            load(p) for p in ("x", "y", "tx", "ty", "allx", "ally")
        )
        graph = load("graph")
        test_idx = load("test.index")
        tx_dense = np.asarray(tx.todense())
        ty_dense = np.asarray(ty)
        sorted_test = np.sort(test_idx)
        lo, hi = int(test_idx.min()), int(test_idx.max())
        if hi - lo + 1 > len(test_idx):
            # citeseer: test.index has gaps (isolated nodes) — extend the
            # test block over the full contiguous range, zero-filling
            tx_ext = np.zeros((hi - lo + 1, tx_dense.shape[1]))
            ty_ext = np.zeros((hi - lo + 1, ty_dense.shape[1]))
            tx_ext[sorted_test - lo] = tx_dense
            ty_ext[sorted_test - lo] = ty_dense
            tx_dense, ty_dense = tx_ext, ty_ext
        feats = np.vstack([np.asarray(allx.todense()), tx_dense])
        labels = np.vstack([np.asarray(ally), ty_dense])
        # standard fixup: the test block arrives permuted by test.index
        feats[test_idx] = feats[sorted_test]
        labels[test_idx] = labels[sorted_test]
        n = feats.shape[0]
        train_n = len(np.asarray(y))
        val_n = 500
        types = np.full(n, 2)
        types[:train_n] = 0
        types[train_n : train_n + val_n] = 1
        nodes = [
            {
                "id": i + 1,
                "type": int(types[i]),
                "weight": 1.0,
                "features": [
                    {"name": "feature", "type": "dense", "value": feats[i].tolist()},
                    {"name": "label", "type": "dense", "value": labels[i].tolist()},
                ],
            }
            for i in range(n)
        ]
        edges = [
            {"src": i + 1, "dst": j + 1, "type": 0, "weight": 1.0, "features": []}
            for i, nbrs in graph.items()
            for j in nbrs
            if i < n and j < n
        ]
        return {"nodes": nodes, "edges": edges}

    def synthetic_json(self, seed: int = 0) -> dict:
        return _planted_partition_json(
            min(self.num_nodes, 600),
            min(self.feature_dim, 64),
            self.num_classes,
            seed=seed,
        )


class SageDataset(Dataset):
    """ppi / reddit in the GraphSAGE release layout
    (<name>-G.json, -feats.npy, -class_map.json, -id_map.json)."""

    sizes = {"ppi": (50, 121, True), "reddit": (602, 41, False)}

    def __init__(self, name: str, **kw):
        self.name = name
        f, c, multi = self.sizes[name]
        self.feature_dim, self.num_classes, self.multilabel = f, c, multi
        super().__init__(**kw)

    def raw_files(self):
        return [
            f"{self.name}-G.json",
            f"{self.name}-feats.npy",
            f"{self.name}-class_map.json",
            f"{self.name}-id_map.json",
        ]

    def build_json(self) -> dict:
        with open(os.path.join(self.root, f"{self.name}-G.json")) as f:
            g = json.load(f)
        feats = np.load(os.path.join(self.root, f"{self.name}-feats.npy"))
        with open(os.path.join(self.root, f"{self.name}-class_map.json")) as f:
            class_map = json.load(f)
        with open(os.path.join(self.root, f"{self.name}-id_map.json")) as f:
            id_map = json.load(f)
        nodes = []
        for nd in g["nodes"]:
            nid = id_map[str(nd["id"])]
            t = 1 if nd.get("val") else (2 if nd.get("test") else 0)
            y = class_map[str(nd["id"])]
            label = (
                np.asarray(y, dtype=np.float32)
                if isinstance(y, list)
                else np.eye(self.num_classes, dtype=np.float32)[int(y)]
            )
            nodes.append(
                {
                    "id": nid + 1,
                    "type": t,
                    "weight": 1.0,
                    "features": [
                        {"name": "feature", "type": "dense", "value": feats[nid].tolist()},
                        {"name": "label", "type": "dense", "value": label.tolist()},
                    ],
                }
            )
        edges = [
            {
                "src": id_map[str(e["source"])] + 1,
                "dst": id_map[str(e["target"])] + 1,
                "type": 0,
                "weight": 1.0,
                "features": [],
            }
            for e in g["links"]
        ]
        return {"nodes": nodes, "edges": edges}

    def synthetic_json(self, seed: int = 0) -> dict:
        return _planted_partition_json(
            400, min(self.feature_dim, 64), min(self.num_classes, 16), seed=seed
        )


class TUDataset(Dataset):
    """mutag-style graph classification (TU DS_A / DS_graph_indicator /
    DS_graph_labels / DS_node_labels files)."""

    def __init__(self, name: str = "mutag", **kw):
        self.name = name
        self.feature_dim = 8
        self.num_classes = 2
        super().__init__(**kw)

    def raw_files(self):
        up = self.name.upper()
        return [
            f"{up}_A.txt",
            f"{up}_graph_indicator.txt",
            f"{up}_graph_labels.txt",
            f"{up}_node_labels.txt",
        ]

    def build_json(self) -> dict:
        up = self.name.upper()
        edges_raw = np.loadtxt(
            os.path.join(self.root, f"{up}_A.txt"), delimiter=",", dtype=np.int64
        )
        gi = np.loadtxt(
            os.path.join(self.root, f"{up}_graph_indicator.txt"), dtype=np.int64
        )
        gl = np.loadtxt(
            os.path.join(self.root, f"{up}_graph_labels.txt"), dtype=np.int64
        )
        nl = np.loadtxt(
            os.path.join(self.root, f"{up}_node_labels.txt"), dtype=np.int64
        )
        num_nl = int(nl.max()) + 1
        nodes = [
            {
                "id": i + 1,
                "type": 0,
                "weight": 1.0,
                "features": [
                    {
                        "name": "feature",
                        "type": "dense",
                        "value": np.eye(num_nl)[nl[i]].tolist(),
                    },
                    {
                        "name": "graph_label",
                        "type": "binary",
                        "value": f"g{gi[i]}_c{gl[gi[i] - 1]}",
                    },
                ],
            }
            for i in range(len(gi))
        ]
        edges = [
            {"src": int(s), "dst": int(d), "type": 0, "weight": 1.0, "features": []}
            for s, d in edges_raw
        ]
        return {"nodes": nodes, "edges": edges}

    def synthetic_json(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        nodes, edges = [], []
        nid = 1
        for gidx in range(24):
            cls = gidx % 2
            size = int(rng.integers(5, 9))
            ids = list(range(nid, nid + size))
            nid += size
            for i in ids:
                nodes.append(
                    {
                        "id": i,
                        "type": 0,
                        "weight": 1.0,
                        "features": [
                            {
                                "name": "feature",
                                "type": "dense",
                                "value": rng.normal(2.0 * (1 - 2 * cls), 1, 8).tolist(),
                            },
                            {
                                "name": "graph_label",
                                "type": "binary",
                                "value": f"g{gidx}_c{cls}",
                            },
                        ],
                    }
                )
            for i in ids:
                for j in ids:
                    if i != j and (cls == 0 or abs(i - j) <= 1):
                        edges.append(
                            {"src": i, "dst": j, "type": 0, "weight": 1.0, "features": []}
                        )
        return {"nodes": nodes, "edges": edges}


class KGDataset(Dataset):
    """fb15k / fb15k237 / wn18 triples (train/valid/test .txt TSV)."""

    def __init__(self, name: str = "fb15k", **kw):
        self.name = name
        super().__init__(**kw)
        self.entity_map: dict[str, int] = {}
        self.relation_map: dict[str, int] = {}

    def raw_files(self):
        return ["train.txt", "valid.txt", "test.txt"]

    def _triples(self, split: str):
        path = os.path.join(self.root, f"{split}.txt")
        out = []
        with open(path) as f:
            for line in f:
                h, r, t = line.rstrip("\n").split("\t")
                out.append((h, r, t))
        return out

    def _build_maps(self):
        """Deterministic entity/relation id maps derived from train.txt."""
        ents, rels = {}, {}
        for h, r, t in self._triples("train"):
            ents.setdefault(h, len(ents) + 1)
            ents.setdefault(t, len(ents) + 1)
            rels.setdefault(r, len(rels))
        self.entity_map, self.relation_map = ents, rels

    def build_json(self) -> dict:
        self._build_maps()
        ents, rels = self.entity_map, self.relation_map
        train = self._triples("train")
        nodes = [
            {"id": i, "type": 0, "weight": 1.0, "features": []}
            for i in ents.values()
        ]
        edges = [
            {
                "src": ents[h],
                "dst": ents[t],
                "type": rels[r],
                "weight": 1.0,
                "features": [],
            }
            for h, r, t in train
        ]
        return {"nodes": nodes, "edges": edges}

    def eval_triples(self, split: str = "test") -> np.ndarray:
        """int32 [M, 3] (h, r, t) restricted to known entities/relations."""
        if not self.entity_map:
            self._build_maps()
        out = []
        for h, r, t in self._triples(split):
            if h in self.entity_map and t in self.entity_map and r in self.relation_map:
                out.append(
                    (self.entity_map[h], self.relation_map[r], self.entity_map[t])
                )
        return np.asarray(out, dtype=np.int32)

    def synthetic_json(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        n_ent, n_rel, n_tri = 200, 6, 2000
        nodes = [
            {"id": i + 1, "type": 0, "weight": 1.0, "features": []}
            for i in range(n_ent)
        ]
        edges = [
            {
                "src": int(rng.integers(1, n_ent + 1)),
                "dst": int(rng.integers(1, n_ent + 1)),
                "type": int(rng.integers(0, n_rel)),
                "weight": 1.0,
                "features": [],
            }
            for _ in range(n_tri)
        ]
        return {"nodes": nodes, "edges": edges}


class MovieLensDataset(Dataset):
    """ml_1m bipartite user↔movie ratings graph (dataset/ml_1m.py parity).

    Node ids: movies keep their MovieLens id (1..3952); users are offset by
    3952. Movie nodes (type 0) carry a sparse `genre` feature; user nodes
    (type 1) carry sparse `gender`/`age`/`occupation` and binary `zip_code`;
    `rate` edges (type 0, user→movie) carry sparse `rating` and binary
    `timestamp`.
    """

    GENRES = [
        "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
        "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
        "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
    ]
    AGES = ["1", "18", "25", "35", "45", "50", "56"]
    MOVIE_LEN = 3952

    def __init__(self, name: str = "ml_1m", **kw):
        self.name = name
        self.feature_dim = len(self.GENRES)
        self.num_classes = 5
        super().__init__(**kw)

    def raw_files(self):
        return ["movies.dat", "ratings.dat", "users.dat"]

    def _rows(self, fname: str):
        path = os.path.join(self.root, fname)
        with open(path, encoding="latin1") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line.split("::")

    def build_json(self) -> dict:
        genre_id = {g: i for i, g in enumerate(self.GENRES)}
        age_id = {a: i for i, a in enumerate(self.AGES)}
        nodes = []
        for mid, _title, genres in self._rows("movies.dat"):
            nodes.append(
                {
                    "id": int(mid),
                    "type": 0,
                    "weight": 1.0,
                    "features": [
                        {
                            "name": "genre",
                            "type": "sparse",
                            "value": [genre_id[g] for g in genres.split("|")],
                        }
                    ],
                }
            )
        for uid, gender, age, occupation, zip_code in self._rows("users.dat"):
            nodes.append(
                {
                    "id": int(uid) + self.MOVIE_LEN,
                    "type": 1,
                    "weight": 1.0,
                    "features": [
                        {"name": "gender", "type": "sparse",
                         "value": [0 if gender == "M" else 1]},
                        {"name": "age", "type": "sparse",
                         "value": [age_id[age]]},
                        {"name": "occupation", "type": "sparse",
                         "value": [int(occupation)]},
                        {"name": "zip_code", "type": "binary",
                         "value": str(zip_code)},
                    ],
                }
            )
        edges = [
            {
                "src": int(uid) + self.MOVIE_LEN,
                "dst": int(mid),
                "type": 0,
                "weight": float(rating),
                "features": [
                    {"name": "rating", "type": "sparse", "value": [int(rating)]},
                    {"name": "timestamp", "type": "binary", "value": str(ts)},
                ],
            }
            for uid, mid, rating, ts in self._rows("ratings.dat")
        ]
        return {"nodes": nodes, "edges": edges}

    def synthetic_json(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        n_movies, n_users, n_ratings = 120, 80, 1500
        nodes = [
            {
                "id": m + 1,
                "type": 0,
                "weight": 1.0,
                "features": [
                    {
                        "name": "genre",
                        "type": "sparse",
                        "value": sorted(
                            rng.choice(
                                len(self.GENRES),
                                size=int(rng.integers(1, 4)),
                                replace=False,
                            ).tolist()
                        ),
                    }
                ],
            }
            for m in range(n_movies)
        ]
        nodes += [
            {
                "id": self.MOVIE_LEN + u + 1,
                "type": 1,
                "weight": 1.0,
                "features": [
                    {"name": "gender", "type": "sparse",
                     "value": [int(rng.integers(0, 2))]},
                    {"name": "age", "type": "sparse",
                     "value": [int(rng.integers(0, len(self.AGES)))]},
                    {"name": "occupation", "type": "sparse",
                     "value": [int(rng.integers(0, 21))]},
                    {"name": "zip_code", "type": "binary",
                     "value": f"{rng.integers(10000, 99999)}"},
                ],
            }
            for u in range(n_users)
        ]
        edges = [
            {
                "src": self.MOVIE_LEN + int(rng.integers(1, n_users + 1)),
                "dst": int(rng.integers(1, n_movies + 1)),
                "type": 0,
                "weight": float(rng.integers(1, 6)),
                "features": [
                    {"name": "rating", "type": "sparse",
                     "value": [int(rng.integers(1, 6))]},
                    {"name": "timestamp", "type": "binary",
                     "value": f"{rng.integers(9e8, 1e9)}"},
                ],
            }
            for _ in range(n_ratings)
        ]
        return {"nodes": nodes, "edges": edges}


DATASETS = {
    "cora": lambda **kw: PlanetoidDataset("cora", **kw),
    "citeseer": lambda **kw: PlanetoidDataset("citeseer", **kw),
    "pubmed": lambda **kw: PlanetoidDataset("pubmed", **kw),
    "ppi": lambda **kw: SageDataset("ppi", **kw),
    "reddit": lambda **kw: SageDataset("reddit", **kw),
    "mutag": lambda **kw: TUDataset("mutag", **kw),
    "fb15k": lambda **kw: KGDataset("fb15k", **kw),
    "fb15k237": lambda **kw: KGDataset("fb15k237", **kw),
    "wn18": lambda **kw: KGDataset("wn18", **kw),
    "ml_1m": lambda **kw: MovieLensDataset("ml_1m", **kw),
}


def get_dataset(name: str, **kw) -> Dataset:
    """Factory (tf_euler/python/dataset get_dataset parity)."""
    if name not in DATASETS:
        raise KeyError(f"unknown dataset {name!r}; have {sorted(DATASETS)}")
    return DATASETS[name](**kw)
