"""Dataset catalog (tf_euler/python/dataset/* parity): cora, citeseer,
pubmed (Planetoid), ppi, reddit (GraphSAGE json/npy), mutag (TU graph
classification), fb15k / fb15k237 / wn18 (KG triples)."""

from __future__ import annotations

import json
import os

import numpy as np

from euler_tpu.datasets.base import Dataset, _planted_partition_json


class PlanetoidDataset(Dataset):
    """cora / citeseer / pubmed from the classic Planetoid pickles."""

    sizes = {
        "cora": (2708, 1433, 7),
        "citeseer": (3327, 3703, 6),
        "pubmed": (19717, 500, 3),
    }

    def __init__(self, name: str, **kw):
        self.name = name
        n, f, c = self.sizes[name]
        self.num_nodes, self.feature_dim, self.num_classes = n, f, c
        super().__init__(**kw)

    def raw_files(self):
        parts = ["x", "y", "tx", "ty", "allx", "ally", "graph", "test.index"]
        return [f"ind.{self.name}.{p}" for p in parts]

    def build_json(self) -> dict:
        import pickle

        def load(part):
            path = os.path.join(self.root, f"ind.{self.name}.{part}")
            if part == "test.index":
                return np.loadtxt(path, dtype=np.int64)
            with open(path, "rb") as f:
                return pickle.load(f, encoding="latin1")

        x, y, tx, ty, allx, ally = (
            load(p) for p in ("x", "y", "tx", "ty", "allx", "ally")
        )
        graph = load("graph")
        test_idx = load("test.index")
        tx_dense = np.asarray(tx.todense())
        ty_dense = np.asarray(ty)
        sorted_test = np.sort(test_idx)
        lo, hi = int(test_idx.min()), int(test_idx.max())
        if hi - lo + 1 > len(test_idx):
            # citeseer: test.index has gaps (isolated nodes) — extend the
            # test block over the full contiguous range, zero-filling
            tx_ext = np.zeros((hi - lo + 1, tx_dense.shape[1]))
            ty_ext = np.zeros((hi - lo + 1, ty_dense.shape[1]))
            tx_ext[sorted_test - lo] = tx_dense
            ty_ext[sorted_test - lo] = ty_dense
            tx_dense, ty_dense = tx_ext, ty_ext
        feats = np.vstack([np.asarray(allx.todense()), tx_dense])
        labels = np.vstack([np.asarray(ally), ty_dense])
        # standard fixup: the test block arrives permuted by test.index
        feats[test_idx] = feats[sorted_test]
        labels[test_idx] = labels[sorted_test]
        n = feats.shape[0]
        train_n = len(np.asarray(y))
        val_n = 500
        types = np.full(n, 2)
        types[:train_n] = 0
        types[train_n : train_n + val_n] = 1
        nodes = [
            {
                "id": i + 1,
                "type": int(types[i]),
                "weight": 1.0,
                "features": [
                    {"name": "feature", "type": "dense", "value": feats[i].tolist()},
                    {"name": "label", "type": "dense", "value": labels[i].tolist()},
                ],
            }
            for i in range(n)
        ]
        edges = [
            {"src": i + 1, "dst": j + 1, "type": 0, "weight": 1.0, "features": []}
            for i, nbrs in graph.items()
            for j in nbrs
            if i < n and j < n
        ]
        return {"nodes": nodes, "edges": edges}

    def synthetic_json(self, seed: int = 0) -> dict:
        return _planted_partition_json(
            min(self.num_nodes, 600),
            min(self.feature_dim, 64),
            self.num_classes,
            seed=seed,
        )


class SageDataset(Dataset):
    """ppi / reddit in the GraphSAGE release layout
    (<name>-G.json, -feats.npy, -class_map.json, -id_map.json)."""

    sizes = {"ppi": (50, 121, True), "reddit": (602, 41, False)}

    def __init__(self, name: str, **kw):
        self.name = name
        f, c, multi = self.sizes[name]
        self.feature_dim, self.num_classes, self.multilabel = f, c, multi
        super().__init__(**kw)

    def raw_files(self):
        return [
            f"{self.name}-G.json",
            f"{self.name}-feats.npy",
            f"{self.name}-class_map.json",
            f"{self.name}-id_map.json",
        ]

    def build_json(self) -> dict:
        with open(os.path.join(self.root, f"{self.name}-G.json")) as f:
            g = json.load(f)
        feats = np.load(os.path.join(self.root, f"{self.name}-feats.npy"))
        with open(os.path.join(self.root, f"{self.name}-class_map.json")) as f:
            class_map = json.load(f)
        with open(os.path.join(self.root, f"{self.name}-id_map.json")) as f:
            id_map = json.load(f)
        nodes = []
        for nd in g["nodes"]:
            nid = id_map[str(nd["id"])]
            t = 1 if nd.get("val") else (2 if nd.get("test") else 0)
            y = class_map[str(nd["id"])]
            label = (
                np.asarray(y, dtype=np.float32)
                if isinstance(y, list)
                else np.eye(self.num_classes, dtype=np.float32)[int(y)]
            )
            nodes.append(
                {
                    "id": nid + 1,
                    "type": t,
                    "weight": 1.0,
                    "features": [
                        {"name": "feature", "type": "dense", "value": feats[nid].tolist()},
                        {"name": "label", "type": "dense", "value": label.tolist()},
                    ],
                }
            )
        edges = [
            {
                "src": id_map[str(e["source"])] + 1,
                "dst": id_map[str(e["target"])] + 1,
                "type": 0,
                "weight": 1.0,
                "features": [],
            }
            for e in g["links"]
        ]
        return {"nodes": nodes, "edges": edges}

    def synthetic_json(self, seed: int = 0) -> dict:
        return _planted_partition_json(
            400, min(self.feature_dim, 64), min(self.num_classes, 16), seed=seed
        )


class TUDataset(Dataset):
    """mutag-style graph classification (TU DS_A / DS_graph_indicator /
    DS_graph_labels / DS_node_labels files)."""

    def __init__(self, name: str = "mutag", **kw):
        self.name = name
        self.feature_dim = 8
        self.num_classes = 2
        super().__init__(**kw)

    def raw_files(self):
        up = self.name.upper()
        return [
            f"{up}_A.txt",
            f"{up}_graph_indicator.txt",
            f"{up}_graph_labels.txt",
            f"{up}_node_labels.txt",
        ]

    def build_json(self) -> dict:
        up = self.name.upper()
        edges_raw = np.loadtxt(
            os.path.join(self.root, f"{up}_A.txt"), delimiter=",", dtype=np.int64
        )
        gi = np.loadtxt(
            os.path.join(self.root, f"{up}_graph_indicator.txt"), dtype=np.int64
        )
        gl = np.loadtxt(
            os.path.join(self.root, f"{up}_graph_labels.txt"), dtype=np.int64
        )
        nl = np.loadtxt(
            os.path.join(self.root, f"{up}_node_labels.txt"), dtype=np.int64
        )
        num_nl = int(nl.max()) + 1
        nodes = [
            {
                "id": i + 1,
                "type": 0,
                "weight": 1.0,
                "features": [
                    {
                        "name": "feature",
                        "type": "dense",
                        "value": np.eye(num_nl)[nl[i]].tolist(),
                    },
                    {
                        "name": "graph_label",
                        "type": "binary",
                        "value": f"g{gi[i]}_c{gl[gi[i] - 1]}",
                    },
                ],
            }
            for i in range(len(gi))
        ]
        edges = [
            {"src": int(s), "dst": int(d), "type": 0, "weight": 1.0, "features": []}
            for s, d in edges_raw
        ]
        return {"nodes": nodes, "edges": edges}

    def synthetic_json(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        nodes, edges = [], []
        nid = 1
        for gidx in range(24):
            cls = gidx % 2
            size = int(rng.integers(5, 9))
            ids = list(range(nid, nid + size))
            nid += size
            for i in ids:
                nodes.append(
                    {
                        "id": i,
                        "type": 0,
                        "weight": 1.0,
                        "features": [
                            {
                                "name": "feature",
                                "type": "dense",
                                "value": rng.normal(2.0 * (1 - 2 * cls), 1, 8).tolist(),
                            },
                            {
                                "name": "graph_label",
                                "type": "binary",
                                "value": f"g{gidx}_c{cls}",
                            },
                        ],
                    }
                )
            for i in ids:
                for j in ids:
                    if i != j and (cls == 0 or abs(i - j) <= 1):
                        edges.append(
                            {"src": i, "dst": j, "type": 0, "weight": 1.0, "features": []}
                        )
        return {"nodes": nodes, "edges": edges}


class KGDataset(Dataset):
    """fb15k / fb15k237 / wn18 triples (train/valid/test .txt TSV)."""

    def __init__(self, name: str = "fb15k", **kw):
        self.name = name
        super().__init__(**kw)
        self.entity_map: dict[str, int] = {}
        self.relation_map: dict[str, int] = {}

    def raw_files(self):
        return ["train.txt", "valid.txt", "test.txt"]

    def _triples(self, split: str):
        path = os.path.join(self.root, f"{split}.txt")
        out = []
        with open(path) as f:
            for line in f:
                h, r, t = line.rstrip("\n").split("\t")
                out.append((h, r, t))
        return out

    def _build_maps(self):
        """Deterministic entity/relation id maps derived from train.txt."""
        ents, rels = {}, {}
        for h, r, t in self._triples("train"):
            ents.setdefault(h, len(ents) + 1)
            ents.setdefault(t, len(ents) + 1)
            rels.setdefault(r, len(rels))
        self.entity_map, self.relation_map = ents, rels

    def build_json(self) -> dict:
        self._build_maps()
        ents, rels = self.entity_map, self.relation_map
        train = self._triples("train")
        nodes = [
            {"id": i, "type": 0, "weight": 1.0, "features": []}
            for i in ents.values()
        ]
        edges = [
            {
                "src": ents[h],
                "dst": ents[t],
                "type": rels[r],
                "weight": 1.0,
                "features": [],
            }
            for h, r, t in train
        ]
        return {"nodes": nodes, "edges": edges}

    def eval_triples(self, split: str = "test") -> np.ndarray:
        """int32 [M, 3] (h, r, t) restricted to known entities/relations."""
        if not self.entity_map:
            self._build_maps()
        out = []
        for h, r, t in self._triples(split):
            if h in self.entity_map and t in self.entity_map and r in self.relation_map:
                out.append(
                    (self.entity_map[h], self.relation_map[r], self.entity_map[t])
                )
        return np.asarray(out, dtype=np.int32)

    def synthetic_json(self, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        n_ent, n_rel, n_tri = 200, 6, 2000
        nodes = [
            {"id": i + 1, "type": 0, "weight": 1.0, "features": []}
            for i in range(n_ent)
        ]
        edges = [
            {
                "src": int(rng.integers(1, n_ent + 1)),
                "dst": int(rng.integers(1, n_ent + 1)),
                "type": int(rng.integers(0, n_rel)),
                "weight": 1.0,
                "features": [],
            }
            for _ in range(n_tri)
        ]
        return {"nodes": nodes, "edges": edges}


DATASETS = {
    "cora": lambda **kw: PlanetoidDataset("cora", **kw),
    "citeseer": lambda **kw: PlanetoidDataset("citeseer", **kw),
    "pubmed": lambda **kw: PlanetoidDataset("pubmed", **kw),
    "ppi": lambda **kw: SageDataset("ppi", **kw),
    "reddit": lambda **kw: SageDataset("reddit", **kw),
    "mutag": lambda **kw: TUDataset("mutag", **kw),
    "fb15k": lambda **kw: KGDataset("fb15k", **kw),
    "fb15k237": lambda **kw: KGDataset("fb15k237", **kw),
    "wn18": lambda **kw: KGDataset("wn18", **kw),
}


def get_dataset(name: str, **kw) -> Dataset:
    """Factory (tf_euler/python/dataset get_dataset parity)."""
    if name not in DATASETS:
        raise KeyError(f"unknown dataset {name!r}; have {sorted(DATASETS)}")
    return DATASETS[name](**kw)
