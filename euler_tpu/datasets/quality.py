"""Calibrated quality-parity benchmark graphs.

The reference's example READMEs publish model-quality tables (GCN cora F1
0.822, examples/gcn/README.md — copied into BASELINE.md) but the classic
datasets auto-download at runtime (tf_euler/python/dataset/cora.py), which a
zero-egress environment cannot do. This module generates *calibrated*
synthetic stand-ins whose statistics match the real dataset closely enough
that the published score separates working models from broken ones:

`cora_like_json` mirrors cora's shape — 2708 nodes, 7 classes, 1433-dim
sparse bag-of-words features, ~9k undirected citation edges, 140/500/1000
train/val/test split (20 per class) — with feature noise (word_sigma) and
edge homophily tuned jointly so that, measured on seed 0:
  - features alone (logistic regression)   0.552 acc (cora LR ~0.55)
  - 2-layer GCN, true-degree symmetric norm 0.824 micro-F1 (cora GCN 0.822)
(homophily lands at 0.68 rather than cora's raw 0.81 because the
synthetic's independent-noise edges carry more signal per edge than real
correlated citations — the calibration target is the score pair, not each
raw statistic.) The LR→GCN gap is the graph signal a GCN must exploit;
hitting the GCN number requires correct normalization, masking, training.
"""

from __future__ import annotations

import numpy as np


def pubmed_like_json(seed: int = 0) -> dict:
    """Pubmed-shaped stand-in: 19717 nodes, 3 classes, 500-dim sparse
    features, ~45k edges, 60/500/1000 split. Calibrated (seed 0) to the
    published pubmed pair the same way cora_like is to cora's:
      - logistic regression on raw features  0.720 (pubmed LR ~0.72)
      - 2-layer true-degree GCN              0.882 (pubmed GCN 0.871,
        examples/gcn/README.md)
    The knobs: word_sigma 0.45 (3-class topic overlap), homophily 0.50 —
    pubmed's GCN-over-LR gap is smaller than cora's, so the stand-in's
    edges carry proportionally less signal."""
    return cora_like_json(
        num_nodes=19717,
        num_classes=3,
        feature_dim=500,
        avg_degree=4.5,
        homophily=0.50,
        features_on=35,
        word_sigma=0.45,
        train_per_class=20,
        val_n=500,
        test_n=1000,
        seed=seed,
    )


def products_like_graph(
    num_nodes: int = 50_000,
    num_classes: int = 47,
    feature_dim: int = 100,
    avg_degree: int = 16,
    homophily: float = 0.57,
    noise: float = 3.45,
    train_frac: float = 0.08,
    val_frac: float = 0.02,
    seed: int = 0,
    num_partitions: int = 1,
):
    """ogbn-products-shaped stand-in for the NORTH-STAR quality config
    (BASELINE.json: GraphSAGE node-classification on ogbn-products).

    ogbn-products itself (2.45M nodes / 61.9M edges / PCA-100 features /
    47 classes, sales-rank split 8%/2%/90%) cannot be downloaded here;
    this plants the same learning problem at 1/50 scale: skewed class
    sizes (Zipf-like, as product categories are), 100-dim Gaussian
    class-center features whose `noise` is tuned so a feature-only
    model lands at the published MLP baseline (0.6106 accuracy), and
    homophilous co-purchase edges tuned so sampled-fanout GraphSAGE
    lands at the published leaderboard score (0.7849 ± 0.004). Measured
    at the defaults (seed 0): feature-only LR 0.6180, SAGE [10,5]
    fanout 0.7780 — both within a point of the published pair.
    Generation is fully vectorized/columnar (≈1M edge triples — a
    per-edge json dict would dominate runtime).

    Returns (Graph, types int64[N]) with types 0/1/2 = train/val/test.
    """
    from euler_tpu.graph import Graph
    from euler_tpu.graph.store import GraphStore

    rng = np.random.default_rng(seed)
    # Zipf-ish class masses like product categories
    mass = 1.0 / np.arange(1, num_classes + 1) ** 0.7
    mass /= mass.sum()
    classes = rng.choice(num_classes, size=num_nodes, p=mass)
    by_class = [np.nonzero(classes == c)[0] for c in range(num_classes)]
    if min(len(p_) for p_ in by_class) == 0:
        # an empty class would make the homophilous index below collapse
        # into the NEXT class's pool (or run off the end) — refuse loudly
        raise ValueError(
            "products_like_graph: a class drew zero members; increase "
            "num_nodes or decrease num_classes"
        )

    # heavy-tailed out-degrees, co-purchase style
    deg = np.clip(
        rng.lognormal(np.log(avg_degree * 0.7), 0.8, num_nodes), 2, 120
    ).astype(np.int64)
    e = int(deg.sum())
    src = np.repeat(np.arange(num_nodes), deg)
    same = rng.random(e) < homophily
    # homophilous endpoints: uniform within the src's class (vectorized
    # via per-class cumulative pools), drawn only where needed
    pool_offsets = np.r_[0, np.cumsum([len(p) for p in by_class])]
    pools = np.concatenate(by_class)
    dst = rng.integers(0, num_nodes, e)
    cls_of_src = classes[src[same]]
    lo = pool_offsets[cls_of_src]
    hi = pool_offsets[cls_of_src + 1]
    dst[same] = pools[
        lo + (rng.random(int(same.sum())) * (hi - lo)).astype(np.int64)
    ]

    centers = rng.normal(0.0, 1.0, (num_classes, feature_dim))
    feat = centers[classes] + noise * rng.normal(
        0.0, 1.0, (num_nodes, feature_dim)
    )
    labels = np.zeros((num_nodes, num_classes), np.float32)
    labels[np.arange(num_nodes), classes] = 1.0

    types = np.full(num_nodes, 2, np.int64)
    perm = rng.permutation(num_nodes)
    n_tr = int(train_frac * num_nodes)
    n_val = int(val_frac * num_nodes)
    types[perm[:n_tr]] = 0
    types[perm[n_tr : n_tr + n_val]] = 1

    ids = np.arange(1, num_nodes + 1, dtype=np.uint64)
    # src is sorted by construction (repeat of arange): CSR directly
    src_s, dst_s = src, dst
    indptr = np.r_[0, np.cumsum(deg)]
    from euler_tpu.graph.meta import FeatureSpec, GraphMeta

    P = int(num_partitions)
    meta = GraphMeta(
        num_node_types=3,
        num_edge_types=1,
        node_features={
            "feature": FeatureSpec("feature", "dense", 0, feature_dim),
            "label": FeatureSpec("label", "dense", 1, num_classes),
        },
        edge_features={},
        num_partitions=P,
    )
    feat32 = feat.astype(np.float32)
    stores = []
    meta.node_weight_sums = []
    meta.edge_weight_sums = []
    for p in range(P):
        own = np.nonzero(ids % np.uint64(P) == p)[0]  # id%P ownership
        # per-partition CSR: rows of the (src-sorted) global CSR, sliced
        # and re-packed with the standard repeat-offset trick
        lens = deg[own]
        starts = indptr[own]
        total = int(lens.sum())
        row0 = np.repeat(np.cumsum(lens) - lens, lens)
        idx = np.repeat(starts, lens) + (np.arange(total) - row0)
        meta.node_weight_sums.append(
            [float((types[own] == t).sum()) for t in range(3)]
        )
        meta.edge_weight_sums.append([float(total)])
        arrays = {
            "node_ids": ids[own],
            "node_types": types[own].astype(np.int32),
            "node_weights": np.ones(len(own), np.float32),
            "edge_src": ids[src_s[idx]],
            "edge_dst": ids[dst_s[idx]],
            "edge_types": np.zeros(total, np.int32),
            "edge_weights": np.ones(total, np.float32),
            "adj_0_indptr": np.r_[0, np.cumsum(lens)],
            "adj_0_dst": ids[dst_s[idx]],
            "adj_0_w": np.ones(total, np.float32),
            "adj_0_eidx": np.arange(total, dtype=np.int64),
            "nf_dense_0": feat32[own],
            "nf_dense_1": labels[own],
            "glabel_indptr": np.zeros(1, np.int64),
            "glabel_nodes": np.zeros(0, np.uint64),
        }
        stores.append(GraphStore(meta, arrays, part=p))
    return Graph(meta, stores), types


def citeseer_like_json(seed: int = 0) -> dict:
    """Citeseer-shaped stand-in: 3327 nodes, 6 classes, 3703-dim sparse
    features, sparse citation graph (avg degree 2.8), 20-per-class split.
    Calibrated (seed 0) to the published citeseer pair the same way
    cora_like/pubmed_like are:
      - logistic regression on raw features  0.592 (citeseer LR ~0.60)
      - 2-layer true-degree GCN              0.744 (published 0.752,
        examples/gcn/README.md)
    The knobs: word_sigma 0.75 (6-class topic overlap over the wide
    3703-word vocabulary), homophily 0.78 (citeseer's raw homophily
    ~0.74; the sparse degree-2.8 graph needs most edges informative for
    the small published GCN-over-LR gap to appear at all — at
    homophily 0.5 the noisy edges of a degree-2.8 graph make GCN WORSE
    than the feature baseline)."""
    return cora_like_json(
        num_nodes=3327,
        num_classes=6,
        feature_dim=3703,
        avg_degree=2.8,
        homophily=0.78,
        features_on=32,
        word_sigma=0.75,
        train_per_class=20,
        val_n=500,
        test_n=1000,
        seed=seed,
    )


def fb15k_like(
    n_ent: int = 2000,
    n_rel: int = 40,
    dim: int = 16,
    n_train: int = 30000,
    n_test: int = 1000,
    tail_cands: int = 4,
    noise_frac: float = 0.25,
    seed: int = 0,
    projective: bool = False,
) -> tuple[dict, np.ndarray]:
    """Calibrated KG stand-in for the TransX quality bands.

    FB15k itself (14951 entities, 483k triples) cannot be downloaded here;
    this plants real translational structure instead: ground-truth entity
    points E and relation offsets R, each triple's tail drawn from the
    `tail_cands` nearest entities to E[h]+R[r] (1-to-N ambiguity, like
    FB15k's multi-valued relations) with a `noise_frac` of uniform-random
    tails (unlearnable mass). The knobs are tuned so a correct TransE
    lands near FB15k's published *relative* numbers (examples/TransX/
    README.md:43-49: MeanRank 197 = 1.3% of the entity count, Hit@10
    39.7%) while untrained embeddings stay at MeanRank ≈ n_ent/2 — the
    control that separates "learned the structure" from "easy dataset".

    projective=True plants PER-RELATION SUBSPACE structure instead:
    each relation owns an orthogonal map P_r and tails sit near
    P_r·E[h] + R[r]. A pure translation (TransE) underfits this geometry
    while projection variants (TransR/TransD) can represent it exactly —
    the discriminating control for the projection machinery, mirroring
    how TransR out-Hit@10s TransE on real FB15k
    (examples/TransX/README.md:43-48).

    Returns (graph_json, test_triples int32 [n_test, 3] of (h, r, t)).
    """
    rng = np.random.default_rng(seed)
    E = rng.uniform(-1.0, 1.0, (n_ent, dim))
    R = rng.uniform(-0.6, 0.6, (n_rel, dim))
    if projective:
        # per-relation linear map: an equal blend of identity and a
        # random orthogonal matrix (QR of a gaussian) — NOT itself
        # orthogonal; the identity component keeps tails correlated with
        # heads so the structure stays learnable, the orthogonal
        # component rotates each relation into its own subspace
        P = np.empty((n_rel, dim, dim))
        for k in range(n_rel):
            q, _ = np.linalg.qr(rng.normal(size=(dim, dim)))
            P[k] = 0.5 * np.eye(dim) + 0.5 * q
    else:
        P = None

    def make_triples(count):
        h = rng.integers(0, n_ent, count)
        r = rng.integers(0, n_rel, count)
        t = np.empty(count, dtype=np.int64)
        # nearest-entity tails in chunks (count × n_ent distance matrix)
        for lo in range(0, count, 4096):
            hi = min(lo + 4096, count)
            if P is not None:
                target = (
                    np.einsum("bd,bde->be", E[h[lo:hi]], P[r[lo:hi]])
                    + R[r[lo:hi]]
                )
            else:
                target = E[h[lo:hi]] + R[r[lo:hi]]
            d2 = ((target[:, None, :] - E[None, :, :]) ** 2).sum(-1)
            near = np.argpartition(d2, tail_cands, axis=1)[:, :tail_cands]
            pick = rng.integers(0, tail_cands, hi - lo)
            t[lo:hi] = near[np.arange(hi - lo), pick]
        noise = rng.random(count) < noise_frac
        t[noise] = rng.integers(0, n_ent, int(noise.sum()))
        return np.stack([h, r, t], axis=1)

    train = make_triples(n_train)
    test = make_triples(n_test)
    nodes = [
        {"id": i + 1, "type": 0, "weight": 1.0, "features": []}
        for i in range(n_ent)
    ]
    edges = [
        {
            "src": int(h) + 1,
            "dst": int(t) + 1,
            "type": int(r),
            "weight": 1.0,
            "features": [],
        }
        for h, r, t in train
    ]
    test32 = np.stack(
        [test[:, 0] + 1, test[:, 1], test[:, 2] + 1], axis=1
    ).astype(np.int32)
    return {"nodes": nodes, "edges": edges}, test32


def mutag_like_json(
    n_graphs: int = 188,
    n_node_labels: int = 7,
    n_pendants: int = 10,
    label_noise: float = 0.05,
    seed: int = 0,
) -> dict:
    """Graph-classification stand-in for the GIN quality band.

    MUTAG (188 molecules, accuracy 0.923, examples/gin/README.md) can't be
    fetched; the stand-in makes class membership PURELY relational: both
    classes are 6-cycles over the same node-label multiset and degree
    sequence, differing only in which label pairs share an edge — so a
    label-histogram readout is exactly chance and one message-passing
    round is necessary and sufficient to see the signal (the same shape
    as mutag's bond-environment classes). Pendant nodes with random
    labels are noise; `label_noise` flips a fraction of graph labels to
    cap the ceiling near the published 0.92.
    """
    rng = np.random.default_rng(seed)
    nodes, edges = [], []
    nid = 1
    for gi in range(n_graphs):
        cls = gi % 2
        shown = cls if rng.random() >= label_noise else 1 - cls
        core = list(range(nid, nid + 6))
        nid += 6
        # both classes are a 6-cycle over the SAME label multiset
        # {0,0,1,1,2,2} — identical degree sequence and label histogram —
        # but the labels are ORDERED differently around the ring, so the
        # classes differ only in which label pairs share an edge:
        #   class 0: 0,1,2,0,1,2 → every edge joins two DIFFERENT labels
        #   class 1: 0,0,1,1,2,2 → half the edges join two EQUAL labels
        # One message-passing round sees the neighbor-label profile (the
        # mutag-style signal); a label-histogram readout is exactly chance.
        core_pairs = [(core[k], core[(k + 1) % 6]) for k in range(6)]
        core_labels = (
            [0, 1, 2, 0, 1, 2] if cls == 0 else [0, 0, 1, 1, 2, 2]
        )
        n_pend = int(rng.integers(max(1, n_pendants - 3), n_pendants + 4))
        pend = list(range(nid, nid + n_pend))
        nid += n_pend
        pend_labels = rng.integers(0, n_node_labels, n_pend).tolist()
        ids = core + pend
        labels = core_labels + pend_labels
        for i, lab in zip(ids, labels):
            feat = np.zeros(n_node_labels, dtype=np.float32)
            feat[lab] = 1.0
            nodes.append(
                {
                    "id": i,
                    "type": 0,
                    "weight": 1.0,
                    "features": [
                        {"name": "feature", "type": "dense",
                         "value": feat.tolist()},
                        {"name": "graph_label", "type": "binary",
                         "value": f"g{gi}_c{shown}"},
                    ],
                }
            )
        pairs = list(core_pairs)
        for p in pend:  # each pendant hangs off a random core node
            pairs.append((p, core[int(rng.integers(6))]))
        for a, b in pairs:
            for s, d in ((a, b), (b, a)):
                edges.append(
                    {"src": s, "dst": d, "type": 0, "weight": 1.0,
                     "features": []}
                )
    return {"nodes": nodes, "edges": edges}


def cora_like_json(
    num_nodes: int = 2708,
    num_classes: int = 7,
    feature_dim: int = 1433,
    avg_degree: float = 3.9,
    homophily: float = 0.68,
    features_on: int = 18,
    word_sigma: float = 0.8,
    train_per_class: int = 20,
    val_n: int = 500,
    test_n: int = 1000,
    seed: int = 0,
) -> dict:
    """Citation-network stand-in calibrated to cora's GCN score.

    Each node's bag-of-words draws from its class's word distribution
    softmax(word_sigma * G[c]) over the shared vocabulary (G ~ N(0,1)), so
    classes overlap like real topics. word_sigma is the calibration knob:
    lower → more shared words → weaker features → bigger GCN-over-LR gap.
    """
    rng = np.random.default_rng(seed)
    classes = rng.integers(0, num_classes, num_nodes)

    # citation-style degree heavy tail, truncated
    deg = np.clip(
        rng.lognormal(mean=np.log(avg_degree * 0.75), sigma=0.75, size=num_nodes),
        1,
        30,
    ).astype(np.int64)
    by_class = [np.nonzero(classes == c)[0] for c in range(num_classes)]
    seen = set()
    pairs = []
    for i in range(num_nodes):
        for _ in range(int(deg[i])):
            if rng.random() < homophily:
                j = int(rng.choice(by_class[classes[i]]))
            else:
                j = int(rng.integers(num_nodes))
            if j == i:
                continue
            key = (min(i, j), max(i, j))
            if key in seen:
                continue
            seen.add(key)
            pairs.append(key)

    # sparse bag-of-words from overlapping per-class word distributions
    logits = word_sigma * rng.normal(0, 1, (num_classes, feature_dim))
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs /= probs.sum(axis=1, keepdims=True)
    feat_rows = []
    for i in range(num_nodes):
        k = 1 + rng.poisson(features_on - 1)
        idx = rng.choice(feature_dim, size=k, p=probs[classes[i]])
        feat_rows.append(np.unique(idx))

    # split: 20/class train, then val/test from the remainder (shuffled)
    types = np.full(num_nodes, 3, dtype=np.int64)  # 3 = unused pool
    for c in range(num_classes):
        types[rng.permutation(by_class[c])[:train_per_class]] = 0
    rest = rng.permutation(np.nonzero(types == 3)[0])
    types[rest[:val_n]] = 1
    types[rest[val_n : val_n + test_n]] = 2

    feats = np.zeros((num_nodes, feature_dim), np.float32)
    for i in range(num_nodes):
        feats[i, feat_rows[i]] = 1.0
    labels = np.zeros((num_nodes, num_classes), np.float32)
    labels[np.arange(num_nodes), classes] = 1.0
    return _emit_node_class_json(feats, labels, types, pairs)


def _emit_node_class_json(feats, labels, types, pairs) -> dict:
    """Shared JSON emission for node-classification stand-ins: one dense
    `feature` + one dense `label` per node, 1-based ids, each dedup'd
    undirected pair emitted in both directions."""
    nodes = [
        {
            "id": i + 1,
            "type": int(types[i]),
            "weight": 1.0,
            "features": [
                {"name": "feature", "type": "dense",
                 "value": np.asarray(feats[i]).tolist()},
                {"name": "label", "type": "dense",
                 "value": np.asarray(labels[i]).tolist()},
            ],
        }
        for i in range(len(types))
    ]
    edges = [
        {"src": s + 1, "dst": d + 1, "type": 0, "weight": 1.0,
         "features": []}
        for i, j in pairs
        for s, d in ((i, j), (j, i))
    ]
    return {"nodes": nodes, "edges": edges}


def attention_like_json(
    num_signal: int = 2100,
    num_classes: int = 7,
    feature_dim: int = 64,
    rel_degree: int = 4,
    noise_degree: int = 4,
    signal_scale: float = 0.2,
    noise_sigma: float = 1.0,
    distractor_sigma: float = 0.5,
    marker_scale: float = 0.6,
    train_per_class: int = 20,
    test_n: int = 1000,
    seed: int = 0,
) -> dict:
    """Planted-attention stand-in: a probe where attention PROVABLY beats
    mean aggregation (VERDICT r4 #4; gat_conv.py / examples/gat).

    Signal nodes carry x = mu_class + noise and are partitioned into
    (class c, confuser class c') groups. Relevant edges connect nodes
    within the SAME group; every signal node additionally gets
    `noise_degree` private leaf DISTRACTOR neighbors whose features are
    mu_c' + a class-independent marker direction. Construction notes —
    each ingredient defeats a specific escape hatch mean aggregation
    would otherwise use:
      - the confuser class is coherent across a node's whole 2-hop
        neighborhood (group-homophilous relevant edges), so the planted
        c-vs-c' ambiguity does NOT average out at depth 2 the way
        per-node random garbage does;
      - distractors are leaves (degree 1), so their raw mu_c' survives
        GCN's self-loop normalization instead of being diluted by a hub
        neighborhood;
      - the marker direction makes distractors identifiable from their
        OWN features — exactly what GAT-style static attention
        (a_src . W h_j, per-node importance) can learn to suppress —
        while contributing nothing to classification.
    Result: feature-only LR is mediocre (signal/noise calibrated), mean
    aggregation (GCN) is capped by the ambiguity (per-neighbor gating is
    outside its hypothesis class), attention recovers the clean
    same-group neighborhood. A conv with broken attention (uniform
    alpha) degenerates to the GCN score and fails the GAT band — the
    probe discriminates 'conv right' from 'conv subtly wrong', which the
    plain cora-like stand-in cannot.

    Measured at the defaults (seeds 0-2, 2-layer [64,64], 200 steps):
    feature-only LR 0.36, GCN 0.39-0.42 (symmetric norm upweights the
    degree-1 distractors 3x — mean aggregation is actively harmed), GAT
    4-head improved 0.920-0.927, uniform-attention GAT (broken softmax)
    0.753, ARMA 0.938-0.948, ARMA with GCN's symmetric norm (the
    plausible porting bug) 0.510-0.547.
    """
    rng = np.random.default_rng(seed)
    classes = rng.integers(0, num_classes, num_signal)
    # confuser class per node, shared within (c, c') groups via draw
    confuser = (
        classes + 1 + rng.integers(0, num_classes - 1, num_signal)
    ) % num_classes

    mu = rng.normal(0.0, 1.0, (num_classes, feature_dim))
    mu *= signal_scale / np.linalg.norm(mu, axis=1, keepdims=True) * np.sqrt(
        feature_dim
    )
    marker = rng.normal(0.0, 1.0, feature_dim)
    marker *= marker_scale / np.linalg.norm(marker) * np.sqrt(feature_dim)

    feats_sig = (
        mu[classes]
        + noise_sigma * rng.normal(0.0, 1.0, (num_signal, feature_dim))
    ).astype(np.float32)

    by_group: dict[tuple[int, int], np.ndarray] = {}
    for c in range(num_classes):
        for cc in range(num_classes):
            if c != cc:
                m = (classes == c) & (confuser == cc)
                if m.any():
                    by_group[(c, cc)] = np.nonzero(m)[0]

    seen = set()
    pairs = []
    dis_feats = []

    def add(i, j):
        if i == j:
            return
        key = (min(i, j), max(i, j))
        if key not in seen:
            seen.add(key)
            pairs.append(key)

    next_id = num_signal
    for i in range(num_signal):
        grp = by_group[(int(classes[i]), int(confuser[i]))]
        for _ in range(rel_degree):
            add(i, int(rng.choice(grp)))
        for _ in range(noise_degree):  # private leaf distractors
            dis_feats.append(
                mu[confuser[i]]
                + distractor_sigma * rng.normal(0.0, 1.0, feature_dim)
                + marker
            )
            add(i, next_id)
            next_id += 1

    n = next_id
    feats = np.concatenate(
        [
            feats_sig,
            np.asarray(dis_feats, np.float32).reshape(-1, feature_dim),
        ],
        axis=0,
    )

    types = np.full(n, 3, dtype=np.int64)  # 3 = unused/distractor pool
    for c in range(num_classes):
        idx = np.nonzero(classes == c)[0]
        types[rng.permutation(idx)[:train_per_class]] = 0
    rest = rng.permutation(
        np.nonzero((types == 3) & (np.arange(n) < num_signal))[0]
    )
    types[rest[:test_n]] = 2

    all_labels = np.zeros((n, num_classes), np.float32)
    all_labels[np.arange(num_signal), classes] = 1.0
    return _emit_node_class_json(feats, all_labels, types, pairs)
