"""Calibrated quality-parity benchmark graphs.

The reference's example READMEs publish model-quality tables (GCN cora F1
0.822, examples/gcn/README.md — copied into BASELINE.md) but the classic
datasets auto-download at runtime (tf_euler/python/dataset/cora.py), which a
zero-egress environment cannot do. This module generates *calibrated*
synthetic stand-ins whose statistics match the real dataset closely enough
that the published score separates working models from broken ones:

`cora_like_json` mirrors cora's shape — 2708 nodes, 7 classes, 1433-dim
sparse bag-of-words features, ~9k undirected citation edges, 140/500/1000
train/val/test split (20 per class) — with feature noise (word_sigma) and
edge homophily tuned jointly so that, measured on seed 0:
  - features alone (logistic regression)   0.552 acc (cora LR ~0.55)
  - 2-layer GCN, true-degree symmetric norm 0.824 micro-F1 (cora GCN 0.822)
(homophily lands at 0.68 rather than cora's raw 0.81 because the
synthetic's independent-noise edges carry more signal per edge than real
correlated citations — the calibration target is the score pair, not each
raw statistic.) The LR→GCN gap is the graph signal a GCN must exploit;
hitting the GCN number requires correct normalization, masking, training.
"""

from __future__ import annotations

import numpy as np


def cora_like_json(
    num_nodes: int = 2708,
    num_classes: int = 7,
    feature_dim: int = 1433,
    avg_degree: float = 3.9,
    homophily: float = 0.68,
    features_on: int = 18,
    word_sigma: float = 0.8,
    train_per_class: int = 20,
    val_n: int = 500,
    test_n: int = 1000,
    seed: int = 0,
) -> dict:
    """Citation-network stand-in calibrated to cora's GCN score.

    Each node's bag-of-words draws from its class's word distribution
    softmax(word_sigma * G[c]) over the shared vocabulary (G ~ N(0,1)), so
    classes overlap like real topics. word_sigma is the calibration knob:
    lower → more shared words → weaker features → bigger GCN-over-LR gap.
    """
    rng = np.random.default_rng(seed)
    classes = rng.integers(0, num_classes, num_nodes)

    # citation-style degree heavy tail, truncated
    deg = np.clip(
        rng.lognormal(mean=np.log(avg_degree * 0.75), sigma=0.75, size=num_nodes),
        1,
        30,
    ).astype(np.int64)
    by_class = [np.nonzero(classes == c)[0] for c in range(num_classes)]
    seen = set()
    pairs = []
    for i in range(num_nodes):
        for _ in range(int(deg[i])):
            if rng.random() < homophily:
                j = int(rng.choice(by_class[classes[i]]))
            else:
                j = int(rng.integers(num_nodes))
            if j == i:
                continue
            key = (min(i, j), max(i, j))
            if key in seen:
                continue
            seen.add(key)
            pairs.append(key)

    # sparse bag-of-words from overlapping per-class word distributions
    logits = word_sigma * rng.normal(0, 1, (num_classes, feature_dim))
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs /= probs.sum(axis=1, keepdims=True)
    feat_rows = []
    for i in range(num_nodes):
        k = 1 + rng.poisson(features_on - 1)
        idx = rng.choice(feature_dim, size=k, p=probs[classes[i]])
        feat_rows.append(np.unique(idx))

    # split: 20/class train, then val/test from the remainder (shuffled)
    types = np.full(num_nodes, 3, dtype=np.int64)  # 3 = unused pool
    for c in range(num_classes):
        types[rng.permutation(by_class[c])[:train_per_class]] = 0
    rest = rng.permutation(np.nonzero(types == 3)[0])
    types[rest[:val_n]] = 1
    types[rest[val_n : val_n + test_n]] = 2

    nodes = []
    for i in range(num_nodes):
        feat = np.zeros(feature_dim, dtype=np.float32)
        feat[feat_rows[i]] = 1.0
        label = np.zeros(num_classes, dtype=np.float32)
        label[classes[i]] = 1.0
        nodes.append(
            {
                "id": i + 1,
                "type": int(types[i]),
                "weight": 1.0,
                "features": [
                    {"name": "feature", "type": "dense", "value": feat.tolist()},
                    {"name": "label", "type": "dense", "value": label.tolist()},
                ],
            }
        )
    edges = []
    for i, j in pairs:
        for s, d in ((i, j), (j, i)):
            edges.append(
                {
                    "src": s + 1,
                    "dst": d + 1,
                    "type": 0,
                    "weight": 1.0,
                    "features": [],
                }
            )
    return {"nodes": nodes, "edges": edges}
