"""Dataset pipeline (tf_euler/python/dataset parity, base_dataset.py:49-95).

Each dataset resolves through three stages:
  raw files (downloaded or pre-placed in the cache dir)
    → graph.json dict (the converter input schema)
    → converted tensor-dir shards (cached) → Graph.

This environment has zero egress, so `download()` only checks the cache and
raises with instructions when raw files are missing; `synthetic=True`
generates a statistically similar stand-in so every pipeline stays runnable
offline (splits, shapes, and training code paths are identical).
"""

from __future__ import annotations

import os

import numpy as np

from euler_tpu.graph import Graph
from euler_tpu.graph.builder import convert_json

CACHE_ENV = "EULER_TPU_DATA"


def cache_dir() -> str:
    return os.environ.get(
        CACHE_ENV, os.path.expanduser("~/.cache/euler_tpu_data")
    )


class Dataset:
    name: str = "base"
    urls: list[str] = []
    num_classes: int = 2
    feature_dim: int = 8
    node_type_train = 0  # convention: type 0 = train, 1 = val, 2 = test

    def __init__(self, root: str | None = None, num_partitions: int = 1):
        self.root = root or os.path.join(cache_dir(), self.name)
        self.num_partitions = num_partitions

    # -- to be implemented per dataset -----------------------------------

    def raw_files(self) -> list[str]:
        return []

    def build_json(self) -> dict:
        """Parse raw files → graph.json dict."""
        raise NotImplementedError

    def synthetic_json(self, seed: int = 0) -> dict:
        """Offline stand-in with the same schema/feature dims."""
        raise NotImplementedError

    # -- pipeline ---------------------------------------------------------

    def raw_present(self) -> bool:
        files = self.raw_files()
        return bool(files) and all(
            os.path.exists(os.path.join(self.root, f)) for f in files
        )

    def download(self):
        if self.raw_present():
            return
        raise FileNotFoundError(
            f"dataset {self.name!r}: raw files missing under {self.root} "
            f"(no network egress here). Place {self.raw_files()} there, or "
            f"load with synthetic=True for an offline stand-in."
        )

    def load_graph(self, synthetic: bool = False) -> Graph:
        tag = "synthetic" if synthetic else "real"
        out = os.path.join(self.root, f"converted_{tag}_p{self.num_partitions}")
        if not os.path.exists(os.path.join(out, "euler.meta.json")):
            if synthetic:
                data = self.synthetic_json()
            else:
                self.download()
                data = self.build_json()
            os.makedirs(out, exist_ok=True)
            convert_json(data, out, self.num_partitions, name=self.name)
        return Graph.load(out)

    def splits(self, graph: Graph) -> dict[str, np.ndarray]:
        """train/val/test node ids by node type (0/1/2 convention)."""
        out = {}
        for name, t in (("train", 0), ("val", 1), ("test", 2)):
            ids = []
            for sh in graph.shards:
                sel = np.asarray(sh.node_types) == t
                ids.append(np.asarray(sh.node_ids)[sel])
            out[name] = np.sort(np.concatenate(ids))
        return out


def _planted_partition_json(
    num_nodes: int,
    feature_dim: int,
    num_classes: int,
    avg_degree: int = 4,
    seed: int = 0,
    label_name: str = "label",
    feat_name: str = "feature",
    train_frac: float = 0.6,
    val_frac: float = 0.2,
) -> dict:
    """Cluster-separable citation-style stand-in graph."""
    rng = np.random.default_rng(seed)
    classes = rng.integers(0, num_classes, num_nodes)
    centers = rng.normal(0, 2.0, (num_classes, feature_dim))
    split = rng.random(num_nodes)
    nodes = []
    for i in range(num_nodes):
        t = 0 if split[i] < train_frac else (1 if split[i] < train_frac + val_frac else 2)
        feat = centers[classes[i]] + rng.normal(0, 1.0, feature_dim)
        label = np.zeros(num_classes)
        label[classes[i]] = 1.0
        nodes.append(
            {
                "id": i + 1,
                "type": t,
                "weight": 1.0,
                "features": [
                    {"name": feat_name, "type": "dense", "value": feat.tolist()},
                    {"name": label_name, "type": "dense", "value": label.tolist()},
                ],
            }
        )
    edges = []
    for i in range(num_nodes):
        same = np.nonzero(classes == classes[i])[0]
        for j in rng.choice(same, size=min(avg_degree, len(same)), replace=False):
            if j != i:
                edges.append(
                    {
                        "src": i + 1,
                        "dst": int(j) + 1,
                        "type": 0,
                        "weight": 1.0,
                        "features": [],
                    }
                )
    return {"nodes": nodes, "edges": edges}
