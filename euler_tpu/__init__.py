"""euler_tpu — a TPU-native graph learning framework.

A brand-new JAX/XLA/Pallas implementation with the capabilities of Euler 2.0
(reference: /root/reference — see SURVEY.md). The host side is a columnar,
shardable property-graph store with weighted sampling and batch query APIs
(reference parity surface: euler/core/api/api.h:44-92 plus the tf_euler op set);
the device side is static-shape padded subgraph batches consumed by jitted
message-passing programs over `jax.sharding` meshes.

Public surface (mirrors tf_euler/python/euler_ops + model libs):

    euler_tpu.graph      — graph store, binary format, converter
    euler_tpu.ops        — device message-passing primitives (gather/segment_*)
    euler_tpu.dataflow   — padded subgraph batch builders (sage/gcn/layerwise/...)
    euler_tpu.layers     — convolution layers (GCN/SAGE/GAT/GIN/...)
    euler_tpu.nn         — GNN nets, heads, encoders, aggregators, metrics
    euler_tpu.estimator  — train/evaluate/infer drivers
    euler_tpu.serving    — online model server (micro-batched predict RPCs)
    euler_tpu.parallel   — mesh/sharding helpers, sharded embedding tables
    euler_tpu.datasets   — auto-download dataset pipelines
"""

__version__ = "0.1.0"

from euler_tpu.graph import (  # noqa: F401
    Graph,
    GraphMeta,
    GraphStore,
    build_from_json,
    convert_json,
)
