"""Whole-graph analytics console: the operational face of ISSUE 12.

Runs the offline workload class — PageRank, label propagation,
connected components, KG-embedding sweeps — against a local graph
directory or a live cluster, with the same guarantees the library
makes: one pinned epoch per run, bit-deterministic results, durable
state through the retained checkpoint store.

    python -m euler_tpu.tools.analytics --algo pagerank --data DIR
    python -m euler_tpu.tools.analytics --algo cc \
        --registry REG --num-shards N --state-dir STATE
    python -m euler_tpu.tools.analytics --algo pagerank --data DIR \
        --state-dir STATE --incremental
    python -m euler_tpu.tools.analytics --algo kg-sweep --data DIR \
        --state-dir STATE --steps 40
    python -m euler_tpu.tools.analytics --selftest

Each invocation prints one JSON line. ``--state-dir`` persists the run
(values, trajectory, per-row adjacency signatures) via the PR-10
retained checkpoint store; a later ``--incremental`` run diffs the
saved signatures against the current epoch and reseeds only the rows
whose adjacency actually changed — converging to bit-exactly the
from-scratch answer (tests/test_analytics.py pins this).

``--epoch-pin E0,E1,...`` asserts the engine pinned exactly those
per-shard epochs (exit 3 otherwise) — the operational guard that a run
scheduled "after last night's publish" really is reading that epoch.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _mix(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — a cheap, stable per-element hash."""
    x = np.asarray(x, np.uint64).copy()
    x += np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def row_signatures(engine) -> np.ndarray:
    """One u64 per global row summarizing its out-adjacency — the
    change detector behind ``--incremental``. Commutative (wrapping sum
    of per-edge hashes), so it is independent of edge order AND of
    shard count; two epochs disagree on a row iff its signature moved
    (up to hash collisions)."""
    n = engine.num_rows
    sig = np.zeros(n, np.uint64)
    if engine.num_edges:
        dst_id = engine.node_ids[engine.edge_dst]
        h = _mix(
            _mix(dst_id)
            ^ _mix(engine.edge_w.view(np.uint64))
            ^ _mix(engine.edge_tt.astype(np.uint64))
        )
        np.add.at(sig, engine.edge_src, h)
    deg = np.zeros(n, np.int64)
    if engine.num_edges:
        np.add.at(deg, engine.edge_src, 1)
    return sig ^ _mix(deg.astype(np.uint64))


# ---------------------------------------------------------------------------
# durable run state (retained checkpoints)
# ---------------------------------------------------------------------------


def save_state(state_dir: str, algo: str, result, sigs: np.ndarray) -> str:
    from euler_tpu.training.checkpoint import CheckpointStore

    store = CheckpointStore(state_dir, keep=2)
    traj = result.trajectory or [result.values]
    return store.save_leaves(
        result.iterations,
        list(traj),
        [result.node_ids, np.asarray(result.offsets, np.int64), sigs],
        extra_meta={
            "algo": algo,
            "analytics": {
                "params": {
                    k: v for k, v in result.params.items()
                    if not isinstance(v, np.ndarray)
                },
                "epoch_pin": list(result.epoch_pin),
                "iterations": int(result.iterations),
                "converged": bool(result.converged),
            },
        },
    )


def load_state(state_dir: str, algo: str):
    """Saved run → (AnalyticsResult, signatures u64) or None."""
    from euler_tpu.analytics import AnalyticsResult
    from euler_tpu.training.checkpoint import CheckpointStore

    store = CheckpointStore(state_dir, keep=2)
    if store.latest_step() is None:
        return None
    snap = store.load()
    meta = snap["meta"].get("analytics")
    if snap["meta"].get("algo") != algo or not meta:
        return None
    traj = [np.asarray(v, np.float64) for v in snap["params"]]
    node_ids, offsets, sigs = snap["opt_state"]
    prev = AnalyticsResult(
        algo=algo,
        values=traj[-1],
        node_ids=np.asarray(node_ids, np.uint64),
        offsets=np.asarray(offsets, np.int64),
        epoch_pin=tuple(meta["epoch_pin"]),
        iterations=int(meta["iterations"]),
        converged=bool(meta["converged"]),
        trajectory=traj,
        params=dict(meta["params"]),
    )
    return prev, np.asarray(sigs, np.uint64)


def mutated_rows_from_signatures(engine, prev, prev_sigs, cur_sigs):
    """Rows whose out-adjacency signature moved between the saved run
    and the current epoch, compared BY NODE ID (row spaces may be
    ordered differently); None = incomparable → full recompute."""
    if len(prev.node_ids) != engine.num_rows:
        return None
    po = np.argsort(prev.node_ids, kind="stable")
    co = np.argsort(engine.node_ids, kind="stable")
    if not np.array_equal(prev.node_ids[po], engine.node_ids[co]):
        return None
    diff = prev_sigs[po] != cur_sigs[co]
    return np.asarray(co[diff], np.int64)


# ---------------------------------------------------------------------------
# the independent single-shard oracle (--selftest)
# ---------------------------------------------------------------------------


def _oracle(data: dict, algo: str, damping=0.85, tol=1e-10, iters=100):
    """~20-line single-partition NumPy reference using the SAME
    canonical order the engine buys determinism with — (dst, src_id,
    type, weight_bits) — but none of its code. Returns (ids, values)."""
    ids = np.array(sorted(n["id"] for n in data["nodes"]), np.uint64)
    rank = {int(i): r for r, i in enumerate(ids)}
    src = np.array([rank[e["src"]] for e in data["edges"]], np.int64)
    dst = np.array([rank[e["dst"]] for e in data["edges"]], np.int64)
    w = np.array([e["weight"] for e in data["edges"]], np.float64)
    tt = np.array([e["type"] for e in data["edges"]], np.int64)
    n = len(ids)
    if algo == "cc":
        src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
        cur = np.arange(n, dtype=np.float64)
        for _ in range(iters):
            new = cur.copy()
            np.minimum.at(new, dst, cur[src])
            if np.array_equal(new, cur):
                break
            cur = new
        return ids, cur
    wb = w.view(np.uint64)
    if algo == "lp":
        cur = np.arange(n, dtype=np.float64)
        for _ in range(iters):
            new, k, r = cur.copy(), cur[src].astype(np.int64), dst
            o = np.lexsort((wb, k, r))
            r2, k2, v2 = r[o], k[o], w[o]
            st = np.concatenate(
                [[0], np.flatnonzero(np.diff(r2) | np.diff(k2)) + 1]
            )
            gs = np.add.reduceat(v2, st)
            pick = np.lexsort((k2[st], -gs, r2[st]))
            rr, first = np.unique(r2[st][pick], return_index=True)
            new[rr] = k2[st][pick][first].astype(np.float64)
            if np.array_equal(new, cur):
                break
            cur = new
        return ids, cur
    o = np.lexsort((wb, tt, ids[dst], src))  # out-weight sums, canon order
    out_w = np.bincount(src[o], weights=w[o], minlength=n)
    wn = np.divide(w, out_w[src], out=np.zeros_like(w), where=out_w[src] > 0)
    o = np.lexsort((wb, tt, ids[src], dst))  # per-dst reduction order
    cur = np.full(n, 1.0 / n)
    for _ in range(iters):
        new = np.full(n, (1.0 - damping) / n)
        new += damping * np.bincount(
            dst[o], weights=(wn * cur[src])[o], minlength=n
        )
        if np.max(np.abs(new - cur)) <= tol:
            cur = new
            break
        cur = new
    return ids, cur


def _selftest() -> int:
    """2-shard engine vs the independent oracle, bit-compared by id,
    for all three algorithms."""
    from euler_tpu.analytics import (
        WholeGraphEngine,
        connected_components,
        label_propagation,
        pagerank,
    )
    from euler_tpu.graph import Graph

    n = 48
    data = {
        "nodes": [
            {"id": i, "type": i % 2, "weight": 1.0, "features": []}
            for i in range(1, n + 1)
        ],
        "edges": [
            {"src": s, "dst": (s + off) % n + 1, "type": off % 2,
             "weight": float(1 + (s + off) % 4), "features": []}
            for s in range(1, n + 1)
            for off in (1, 3, 7)
        ],
    }
    graph = Graph.from_json(data, num_partitions=2)
    runs = {
        "pagerank": pagerank(graph, max_iters=100, tol=1e-10),
        "lp": label_propagation(graph),
        "cc": connected_components(graph),
    }
    for algo, res in runs.items():
        ids, want = _oracle(data, algo)
        got_ids, got = res.by_id()
        if not np.array_equal(got_ids, ids) or not np.array_equal(
            got.view(np.uint64), want.view(np.uint64)
        ):
            print(f"selftest FAILED: {algo} diverged from the oracle",
                  file=sys.stderr)
            return 1
    eng = WholeGraphEngine(graph)
    sigs = row_signatures(eng)
    if len(np.unique(sigs)) < 2:
        print("selftest FAILED: degenerate row signatures", file=sys.stderr)
        return 1
    print(json.dumps({"selftest": "ok", "algos": sorted(runs)}))
    return 0


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def _load_graph(args, ap):
    if args.data:
        from euler_tpu.graph import Graph

        return Graph.load(args.data, native=False)
    if args.registry:
        from euler_tpu.distributed import connect

        return connect(
            registry_path=args.registry, num_shards=args.num_shards
        )
    ap.error("need --data or --registry (or --selftest)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--algo", choices=["pagerank", "lp", "cc", "kg-sweep"],
        default="pagerank",
    )
    ap.add_argument("--data", default=None, help="local graph directory")
    ap.add_argument("--registry", default=None)
    ap.add_argument("--num-shards", type=int, default=None)
    ap.add_argument("--damping", type=float, default=0.85)
    ap.add_argument("--tol", type=float, default=1e-10)
    ap.add_argument("--max-iters", type=int, default=100)
    ap.add_argument("--device", action="store_true",
                    help="stage frontier math on the accelerator")
    ap.add_argument("--exchange", choices=["auto", "local", "remote"],
                    default="auto")
    ap.add_argument("--state-dir", default=None,
                    help="persist/load run state (retained checkpoints)")
    ap.add_argument("--incremental", action="store_true",
                    help="diff saved signatures; recompute only mutated rows")
    ap.add_argument("--epoch-pin", default=None,
                    help="comma-separated per-shard epochs the run MUST pin")
    ap.add_argument("--steps", type=int, default=40, help="kg-sweep steps")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    graph = _load_graph(args, ap)

    if args.algo == "kg-sweep":
        from euler_tpu.analytics import run_kg_sweep

        if not args.state_dir:
            ap.error("kg-sweep needs --state-dir for its checkpoints")
        out = run_kg_sweep(
            graph, args.state_dir, steps=args.steps,
            batch_size=args.batch, seed=args.seed,
        )
        if args.epoch_pin is not None:
            want = [int(x) for x in args.epoch_pin.split(",")]
            if list(out["epoch_pin"]) != want:
                print(json.dumps({
                    "error": "epoch-pin mismatch",
                    "pinned": list(out["epoch_pin"]), "want": want,
                }))
                return 3
        out["leaderboard"] = [
            {k: e[k] for k in ("name", "metrics", "final_loss", "resumed")}
            for e in out["leaderboard"]
        ]
        print(json.dumps(out))
        return 0

    from euler_tpu.analytics import (
        WholeGraphEngine,
        connected_components,
        label_propagation,
        pagerank,
        rerun_incremental,
    )

    engine = WholeGraphEngine(
        graph,
        device=args.device,
        exchange=args.exchange,
        symmetric=args.algo == "cc",
    )
    if args.epoch_pin is not None:
        want = tuple(int(x) for x in args.epoch_pin.split(","))
        if tuple(engine.epoch_pin) != want:
            print(json.dumps({
                "error": "epoch-pin mismatch",
                "pinned": list(engine.epoch_pin), "want": list(want),
            }))
            return 3
    cur_sigs = row_signatures(engine)
    saved = (
        load_state(args.state_dir, args.algo) if args.state_dir else None
    )
    incremental = False
    if args.incremental and saved is not None:
        prev, prev_sigs = saved
        rows = mutated_rows_from_signatures(engine, prev, prev_sigs, cur_sigs)
        result = rerun_incremental(
            graph, prev, mutated_rows=rows, engine=engine
        )
        incremental = rows is not None
    elif args.algo == "pagerank":
        result = pagerank(
            graph, damping=args.damping, tol=args.tol,
            max_iters=args.max_iters, engine=engine,
        )
    elif args.algo == "lp":
        result = label_propagation(
            graph, max_iters=args.max_iters, engine=engine
        )
    else:
        result = connected_components(
            graph, max_iters=args.max_iters, engine=engine
        )
    if args.state_dir:
        save_state(args.state_dir, args.algo, result, cur_sigs)
    print(json.dumps({
        "algo": args.algo,
        "epoch_pin": list(result.epoch_pin),
        "iterations": result.iterations,
        "converged": result.converged,
        "incremental": incremental,
        "rows_recomputed": int(result.stats.get("rows_recomputed", 0)),
        "num_rows": int(result.stats.get("num_rows", 0)),
        "num_edges": int(result.stats.get("num_edges", 0)),
        "exchange_bytes": int(result.stats.get("exchange_bytes", 0)),
        "value_digest": hex(int(
            np.sum(_mix(result.values.view(np.uint64)), dtype=np.uint64)
        )) if len(result.values) else "0x0",
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
