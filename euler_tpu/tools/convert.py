"""CLI: graph.json → partitioned tensor-dir shards.

Replaces the reference's `python euler/tools/generate_euler_data.py
graph.json out_dir num_partitions meta` entry point
(euler/tools/generate_euler_data.py:28-51). Index metadata is not needed:
the columnar store builds its samplers/indexes at load time.

Usage: python -m euler_tpu.tools.convert graph.json out_dir [num_partitions]
"""

import sys

from euler_tpu.graph.builder import convert_json


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print(__doc__)
        return 2
    graph_json, out_dir = argv[0], argv[1]
    parts = int(argv[2]) if len(argv) > 2 else 1
    meta = convert_json(graph_json, out_dir, parts)
    print(
        f"wrote {meta.num_partitions} partition(s) to {out_dir}: "
        f"{meta.num_node_types} node type(s), {meta.num_edge_types} edge type(s)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
