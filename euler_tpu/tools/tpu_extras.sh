#!/usr/bin/env bash
# Round-5 chip-gated measurements beyond tpu_suite.sh, run when the
# tunnel is alive:
#   1. weighted-lean remote leg (EULER_BENCH_WEIGHTED=1 --remote-only) —
#      the one remote variant VERDICT r4 #1 lists with no on-chip number
#   2. device-flow headline (new default path: on-device sampling from
#      HBM adjacency, zero per-step wire bytes)
#   3. host-path headline rerun (EULER_BENCH_DEVICE_FLOW=0) — variance
#      band around the 5.12M host-sampling number from tpu_suite.sh; the
#      pin keeps the comparison apples-to-apples after the default flip
#   4. scan-depth sweep on the device-flow path (per-dispatch RTT
#      amortization, k=32/64/128) + a batch-4096 max-throughput row (the
#      batch-1024 headline config is dispatch/gather-overhead dominated)
#   5. remote in-flight depth sweep (pipelined-client overlap, d=1/8)
#
#   bash euler_tpu/tools/tpu_extras.sh [outdir]
set -u
cd "$(dirname "$0")/../.."
OUT="${1:-/tmp/etpu_tpu_extras}"
mkdir -p "$OUT"

probe=$(timeout 120 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
echo "# platform probe: ${probe:-unreachable}"
if [ "${probe:-}" != "tpu" ] && [ "${probe:-}" != "axon" ]; then
  echo "# no chip — nothing measured" && exit 1
fi

echo "# 1/5 weighted-lean remote leg (remote-only)"
EULER_BENCH_WEIGHTED=1 timeout 900 python bench.py --remote-only \
  | tee "$OUT/bench_weighted.json"

echo "# 2/5 device-flow headline (2 runs)"
for i in 1 2; do
  EULER_BENCH_REMOTE=0 timeout 600 python bench.py \
    | tee "$OUT/devflow_$i.json"
done

echo "# 3/5 host-path headline rerun (variance band for the 5.12M row)"
EULER_BENCH_REMOTE=0 EULER_BENCH_DEVICE_FLOW=0 timeout 600 python bench.py \
  | tee "$OUT/hostflow_rerun.json"

echo "# 4/5 scan-depth sweep (device flow, k=32/64/128)"
for k in 32 64 128; do
  EULER_BENCH_REMOTE=0 EULER_BENCH_STEPS_PER_CALL=$k \
    timeout 600 python bench.py | tee "$OUT/devflow_k$k.json"
done

echo "# 4b/5 max-throughput row (device flow, batch 4096)"
EULER_BENCH_REMOTE=0 EULER_BENCH_BATCH=4096 timeout 600 python bench.py \
  | tee "$OUT/devflow_b4096.json"

echo "# 5/5 remote in-flight depth sweep (pipelined client overlap)"
for d in 1 8; do
  EULER_BENCH_INFLIGHT=$d timeout 900 python bench.py --remote-only \
    | tee "$OUT/remote_inflight$d.json"
done
echo "# done → $OUT"
