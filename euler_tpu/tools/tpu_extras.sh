#!/usr/bin/env bash
# Round-5 leftover chip-gated measurements, run when the tunnel is alive
# (tpu_suite.sh already captured headline/KG/wide-F this round):
#   1. weighted-lean remote leg (EULER_BENCH_WEIGHTED=1) — the one
#      remote variant VERDICT r4 #1 lists that has no on-chip number
#   2. two extra headline local runs — variance band for the 5.12M
#      number (r2 measured 7.55M; the tunnel-proxied chip fluctuates)
#
#   bash euler_tpu/tools/tpu_extras.sh [outdir]
set -u
cd "$(dirname "$0")/../.."
OUT="${1:-/tmp/etpu_tpu_extras}"
mkdir -p "$OUT"

probe=$(timeout 120 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
echo "# platform probe: ${probe:-unreachable}"
if [ "${probe:-}" != "tpu" ] && [ "${probe:-}" != "axon" ]; then
  echo "# no chip — nothing measured" && exit 1
fi

echo "# 1/2 weighted-lean remote leg"
EULER_BENCH_WEIGHTED=1 timeout 1200 python bench.py | tee "$OUT/bench_weighted.json"

echo "# 2/3 headline variance (2 local-only runs)"
for i in 1 2; do
  EULER_BENCH_REMOTE=0 timeout 600 python bench.py | tee "$OUT/local_rerun_$i.json"
done

echo "# 3/3 scan-depth sweep (amortize tunnel RTT)"
for k in 32 64; do
  EULER_BENCH_REMOTE=0 EULER_BENCH_STEPS_PER_CALL=$k \
    timeout 600 python bench.py | tee "$OUT/local_k$k.json"
done
echo "# done → $OUT"
