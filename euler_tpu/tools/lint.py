"""graftlint CLI — the standalone lint lane.

    python -m euler_tpu.tools.lint                # human-readable findings
    python -m euler_tpu.tools.lint --json         # one JSON line (lane
                                                  # contract: counts per
                                                  # checker + findings)
    python -m euler_tpu.tools.lint --baseline P   # alternate baseline file
    python -m euler_tpu.tools.lint --write-baseline  # absorb current
                                                  # findings (each entry
                                                  # needs a reason edited in)
    python -m euler_tpu.tools.lint path/a.py dir/ # explicit targets
    python -m euler_tpu.tools.lint --changed-only # full analysis, but only
                                                  # report findings in files
                                                  # changed vs git HEAD

Exit codes: 0 = clean (no non-baselined findings), 1 = findings,
2 = usage/internal error. Stale baseline entries (matching nothing) are
reported but do not fail the run — they fail the tier-1 gate instead
(tests/test_lint.py), where a human is already looking.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def changed_files(root: str) -> set[str]:
    """Repo-relative paths changed vs HEAD: tracked modifications
    (staged or not) plus untracked files. Raises OSError when git is
    unavailable or `root` is not a work tree — --changed-only is a
    git-backed mode, silently linting nothing would read as "clean"."""
    out: set[str] = set()
    diff = subprocess.run(
        ["git", "-C", root, "diff", "--name-only", "HEAD"],
        capture_output=True,
        text=True,
    )
    if diff.returncode != 0:
        raise OSError(
            f"git diff failed under {root}: {diff.stderr.strip()}"
        )
    out.update(ln.strip() for ln in diff.stdout.splitlines() if ln.strip())
    status = subprocess.run(
        ["git", "-C", root, "status", "--porcelain"],
        capture_output=True,
        text=True,
    )
    if status.returncode != 0:
        raise OSError(
            f"git status failed under {root}: {status.stderr.strip()}"
        )
    for ln in status.stdout.splitlines():
        if len(ln) < 4:
            continue
        path = ln[3:].strip()
        # renames print "old -> new"; the new path is the one on disk
        if " -> " in path:
            path = path.split(" -> ", 1)[1]
        out.add(path.strip('"'))
    return {os.path.normpath(p) for p in out}


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m euler_tpu.tools.lint", description=__doc__
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: euler_tpu/ + bench.py)",
    )
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: euler_tpu/analysis/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (show everything)",
    )
    ap.add_argument(
        "--checks",
        default=None,
        help="comma-separated checker names (default: all)",
    )
    ap.add_argument(
        "--changed-only",
        action="store_true",
        help="analyze the whole repo (cross-module facts need every file)"
        " but report only findings in files changed vs git HEAD",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings into the baseline file (reasons are"
        " stamped TODO — edit them before committing)",
    )
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from euler_tpu import analysis

    try:
        project = analysis.load_project(args.paths or None)
        baseline = (
            []
            if args.no_baseline
            else analysis.load_baseline(args.baseline)
        )
        checks = (
            [c.strip() for c in args.checks.split(",") if c.strip()]
            if args.checks
            else None
        )
        report = analysis.run(project, checks=checks, baseline=baseline)
        if args.changed_only:
            # The project is always loaded and analyzed WHOLE — the
            # interprocedural facts (call graph, executor ownership,
            # swap-name sets) are wrong on a partial view. Scoping is a
            # reporting filter only: exit code reflects changed files.
            changed = changed_files(project.root)
            report.findings = [
                f for f in report.findings
                if os.path.normpath(f.path) in changed
            ]
    except (ValueError, SyntaxError, OSError) as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        from euler_tpu.analysis.core import save_baseline

        entries = list(baseline)
        known = {(e["check"], e["path"], e["symbol"]) for e in entries}
        for f in report.findings:
            if f.key() not in known:
                known.add(f.key())
                entries.append(
                    {
                        "check": f.check,
                        "path": f.path,
                        "symbol": f.symbol,
                        "reason": f"TODO: justify — {f.message[:80]}",
                    }
                )
        entries.sort(key=lambda e: (e["path"], e["check"], e["symbol"]))
        save_baseline(entries, args.baseline)
        print(f"baseline: {len(entries)} entries written")
        return 0

    if args.json:
        print(json.dumps(report.to_json(), sort_keys=True))
        return 0 if report.ok else 1

    for f in report.findings:
        print(f.render())
    if report.stale_baseline:
        print(
            f"warning: {len(report.stale_baseline)} stale baseline entries"
            " match no current finding:",
            file=sys.stderr,
        )
        for e in report.stale_baseline:
            print(
                f"  {e['path']} [{e['check']}] {e['symbol']}",
                file=sys.stderr,
            )
    counts = report.counts()
    summary = ", ".join(f"{k}={v}" for k, v in counts.items())
    print(
        f"graftlint: {len(report.findings)} finding(s) over {report.files}"
        f" files ({summary}; {len(report.baselined)} baselined,"
        f" {len(report.suppressed)} suppressed)"
    )
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
