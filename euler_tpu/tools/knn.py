"""KNN retrieval over inferred embeddings (knn/knn.py parity).

The reference wraps faiss IVFFlat (knn.py:36-53); on TPU brute-force
matmul + top-k IS the fast path (MXU does the distance matrix), so this is a
jitted exact search with chunking — no index build, no approximation.

Usage:
    python -m euler_tpu.tools.knn --model-dir DIR --k 10 [--query-ids 1 2 3]
reads embedding_{w}.npy / ids_{w}.npy written by Estimator.infer.
"""

from __future__ import annotations

import argparse
import glob
import os

import numpy as np


def knn_search(
    embeddings: np.ndarray,
    queries: np.ndarray,
    k: int = 10,
    metric: str = "ip",  # ip | l2 | cosine
    chunk: int = 1024,
):
    """Exact top-k: returns (indices [Q, k], scores [Q, k])."""
    import jax
    import jax.numpy as jnp

    base = jnp.asarray(embeddings, jnp.float32)
    if metric == "cosine":
        base = base / jnp.maximum(
            jnp.linalg.norm(base, axis=1, keepdims=True), 1e-9
        )
    base_sq = jnp.sum(base * base, axis=1)

    @jax.jit
    def search(q):
        if metric == "cosine":
            q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True), 1e-9)
        sims = q @ base.T
        if metric == "l2":
            qsq = jnp.sum(q * q, axis=1, keepdims=True)
            sims = -(qsq - 2 * sims + base_sq[None, :])
        return jax.lax.top_k(sims, k)

    idxs, scores = [], []
    queries = np.asarray(queries, np.float32)
    for i in range(0, len(queries), chunk):
        q = queries[i : i + chunk]
        pad = chunk - len(q)
        if pad:
            q = np.pad(q, ((0, pad), (0, 0)))
        s, ix = search(jnp.asarray(q))
        idxs.append(np.asarray(ix)[: len(queries[i : i + chunk])])
        scores.append(np.asarray(s)[: len(queries[i : i + chunk])])
    return np.concatenate(idxs), np.concatenate(scores)


def load_inferred(model_dir: str) -> tuple[np.ndarray, np.ndarray]:
    """Concatenate embedding_{w}.npy / ids_{w}.npy across workers."""
    embs, ids = [], []
    for path in sorted(glob.glob(os.path.join(model_dir, "embedding_*.npy"))):
        w = os.path.basename(path)[len("embedding_") : -len(".npy")]
        embs.append(np.load(path))
        ids.append(np.load(os.path.join(model_dir, f"ids_{w}.npy")))
    if not embs:
        raise FileNotFoundError(f"no embedding_*.npy under {model_dir}")
    return np.concatenate(ids), np.concatenate(embs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--metric", default="ip", choices=["ip", "l2", "cosine"])
    ap.add_argument("--query-ids", type=int, nargs="*", default=None)
    args = ap.parse_args(argv)
    ids, embs = load_inferred(args.model_dir)
    if args.query_ids:
        pos = {int(i): r for r, i in enumerate(ids)}
        rows = [pos[q] for q in args.query_ids]
        queries = embs[rows]
    else:
        queries = embs[:5]
    idx, score = knn_search(embs, queries, args.k, args.metric)
    for qi, (row, sc) in enumerate(zip(idx, score)):
        pairs = ", ".join(f"{int(ids[r])}({s:.3f})" for r, s in zip(row, sc))
        print(f"query {qi}: {pairs}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
