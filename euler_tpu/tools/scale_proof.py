"""Scale proof: build + load + sample a 100M+-edge sharded graph.

VERDICT r2 #3: the mmap format and the C++ engine claim billion-edge
headroom; this tool produces the evidence at the largest size this host
fits — builds an N-shard synthetic graph on disk one shard at a time,
loads every shard through the native engine, and measures:

  - per-shard and total load wall time,
  - resident-set growth over the mmapped bytes (the in-RAM cost of
    engine-side structures: i32 dst_row [4 B/edge]; cum and alias tables
    are elided entirely for uniform weights — graph_engine.cc),
  - fused-fanout sampling throughput on the loaded graph.

Writes one JSON line to stdout (and optionally SCALE.md) for PARITY.md's
1B-edge projection. Reference bulk load for comparison:
euler/core/graph/graph_builder.cc:57-120 (8 threads x 64 jobs).

Usage:
  python -m euler_tpu.tools.scale_proof [--nodes 10000000] [--degree 12]
      [--shards 4] [--feat-dim 16] [--dir /tmp/etpu_scale] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import shutil
import time

import numpy as np


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def current_rss_mb() -> float:
    """Anonymous RSS only: mmapped graph files are file-backed and
    reclaimable, so the engine's true RAM cost is the anon delta
    (dst_row + any cum/alias tables), not touched page-cache bytes."""
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("RssAnon:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def build(directory, num_nodes, out_degree, feat_dim, shards) -> dict:
    from euler_tpu.datasets.synthetic import shard_arrays, synthetic_meta
    from euler_tpu.graph import format as tformat

    meta = synthetic_meta(feat_dim, 2, shards)
    rng = np.random.default_rng(0)
    centers = rng.normal(0.0, 4.0, (2, feat_dim))  # shared across shards
    t0 = time.time()
    total_bytes = 0
    for p in range(shards):
        arrays = shard_arrays(
            p, num_nodes, out_degree, feat_dim, 2, shards, rng, centers
        )
        meta.node_weight_sums.append([float(len(arrays["node_ids"]))])
        meta.edge_weight_sums.append([float(len(arrays["edge_dst"]))])
        part = os.path.join(directory, f"part_{p}")
        tformat.write_arrays(part, arrays)
        total_bytes += sum(a.nbytes for a in arrays.values())
        del arrays
    meta.save(directory)
    return {"build_s": round(time.time() - t0, 1),
            "disk_bytes": total_bytes}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=10_000_000)
    ap.add_argument("--degree", type=int, default=12)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--feat-dim", type=int, default=16)
    ap.add_argument("--dir", default="/tmp/etpu_scale")
    ap.add_argument("--keep", action="store_true",
                    help="keep the on-disk graph for re-runs")
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--fanouts", type=int, nargs="+", default=[10, 10])
    ap.add_argument("--sample-secs", type=float, default=10.0)
    args = ap.parse_args(argv)

    rec: dict = {
        "metric": "scale_proof",
        "edges_total": args.nodes * args.degree,
        "nodes_total": args.nodes,
        "shards": args.shards,
    }
    fresh = not os.path.exists(os.path.join(args.dir, "euler.meta.json"))
    if fresh:
        os.makedirs(args.dir, exist_ok=True)
        rec.update(build(args.dir, args.nodes, args.degree,
                         args.feat_dim, args.shards))

    from euler_tpu.graph import Graph

    rss0 = current_rss_mb()
    t0 = time.time()
    g = Graph.load(args.dir, native=True)
    rec["load_s"] = round(time.time() - t0, 1)
    rec["engine_rss_mb"] = round(current_rss_mb() - rss0, 1)
    rec["rss_bytes_per_edge"] = round(
        (current_rss_mb() - rss0) * 1024 * 1024 / rec["edges_total"], 2
    )

    # fused-fanout throughput (single process, all shards in-process)
    rng = np.random.default_rng(1)
    edges_per_call = 0
    width = args.batch
    for k in args.fanouts:
        edges_per_call += width * k
        width *= k
    # warm
    roots = g.sample_node(args.batch, rng=rng)
    g.fanout_with_rows(roots, None, args.fanouts, rng=rng)
    calls = 0
    t0 = time.time()
    while time.time() - t0 < args.sample_secs:
        roots = g.sample_node(args.batch, rng=rng)
        g.fanout_with_rows(roots, None, args.fanouts, rng=rng)
        calls += 1
    dt = time.time() - t0
    rec["fanout_edges_per_sec"] = round(calls * edges_per_call / dt, 1)
    rec["sample_calls"] = calls
    print(json.dumps(rec))
    if not args.keep and fresh:
        shutil.rmtree(args.dir, ignore_errors=True)
    return rec


if __name__ == "__main__":
    main()
