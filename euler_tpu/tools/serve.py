"""Model-serving CLI (`euler.start` parity for the online path).

Boots a ModelServer over a graph dir + Orbax checkpoint:

    python -m euler_tpu.tools.serve --data DIR --model-dir CKPT \
        --dims 128,128 --label-dim 2 --port 9200

Graph queries run in-process against the local shard files (native
engine when available); model config must match the checkpoint. With
`--registry REG` the server heartbeats into the same registry the graph
services use, so clients discover model replicas the way they discover
shards.

`--selftest` is the smoke mode: builds a tiny synthetic graph + trains a
2-step checkpoint in a temp dir, boots server + client in-process,
asserts served predictions match direct inference bit-for-bit, prints a
JSON summary, and exits 0 — wired into the fast test gate.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading


def build_runtime(args):
    import numpy as np

    from euler_tpu.dataflow import FullNeighborDataFlow, SageDataFlow
    from euler_tpu.estimator import EstimatorConfig
    from euler_tpu.graph import Graph
    from euler_tpu.models import GraphSAGESupervised
    from euler_tpu.serving import InferenceRuntime

    graph = Graph.load(args.data, native=None if args.native else False)
    features = args.features.split(",") if args.features else []
    dims = [int(x) for x in args.dims.split(",")]
    if args.full_neighbor:
        flow = FullNeighborDataFlow(
            graph,
            features,
            num_hops=len(dims),
            max_degree=args.max_degree,
            label_feature=args.label_feature,
        )
    else:
        flow = SageDataFlow(
            graph,
            features,
            fanouts=[int(x) for x in args.fanouts.split(",")],
            label_feature=args.label_feature,
            rng=np.random.default_rng(args.seed),
        )
    model = GraphSAGESupervised(
        dims=dims, label_dim=args.label_dim, conv=args.conv
    )
    return InferenceRuntime(
        model,
        flow,
        EstimatorConfig(model_dir=args.model_dir),
        buckets=tuple(int(b) for b in args.buckets.split(",")),
    )


def serve_model(runtime, args):
    from euler_tpu.distributed.rendezvous import make_registry
    from euler_tpu.serving import ModelServer

    registry = make_registry(args.registry) if args.registry else None
    server = ModelServer(
        runtime,
        host=args.host,
        port=args.port,
        max_batch=args.max_batch,
        max_wait_us=args.max_wait_us,
        max_queue=args.max_queue,
        registry=registry,
        shard=args.replica,
    )
    runtime.warmup()
    return server.start()


def selftest() -> int:
    """In-process boot: synthetic graph → 2-step checkpoint → server +
    concurrent clients → bit-parity vs direct inference. Exit 0 = the
    serving path works end to end on this host."""
    import tempfile

    import numpy as np

    from euler_tpu.dataflow import FullNeighborDataFlow
    from euler_tpu.estimator import (
        Estimator,
        EstimatorConfig,
        id_batches,
        node_batches,
    )
    from euler_tpu.graph import Graph
    from euler_tpu.models import GraphSAGESupervised
    from euler_tpu.serving import (
        InferenceRuntime,
        ModelServer,
        ServingClient,
    )

    rng = np.random.default_rng(0)
    n = 48
    nodes = [
        {
            "id": i + 1,
            "type": 0,
            "weight": 1.0,
            "features": [
                {"name": "feat", "type": "dense",
                 "value": rng.normal(size=4).tolist()},
                {"name": "label", "type": "dense", "value": [1.0, 0.0]},
            ],
        }
        for i in range(n)
    ]
    edges = [
        {"src": i + 1, "dst": (i + d) % n + 1, "type": 0, "weight": 1.0,
         "features": []}
        for i in range(n)
        for d in (1, 2, 3)
    ]
    graph = Graph.from_json({"nodes": nodes, "edges": edges})
    flow = FullNeighborDataFlow(
        graph, ["feat"], num_hops=2, max_degree=4, label_feature="label"
    )
    model = GraphSAGESupervised(dims=[8, 8], label_dim=2)
    cfg = EstimatorConfig(
        model_dir=tempfile.mkdtemp(prefix="etpu_serve_selftest_"),
        total_steps=2,
        log_steps=10**9,
    )
    est = Estimator(
        model, node_batches(graph, flow, 16, rng=np.random.default_rng(1)),
        cfg,
    )
    est.train(log=False)

    runtime = InferenceRuntime(model, flow, cfg, buckets=(16,))
    runtime.warmup()
    all_ids = np.arange(1, n + 1, dtype=np.uint64)
    batches, chunks = id_batches(flow, all_ids, 16)
    _, direct = est.infer(batches, chunks)

    server = ModelServer(runtime, max_wait_us=5000).start()
    results: dict = {}

    def worker(k: int):
        client = ServingClient((server.host, server.port))
        try:
            ids = all_ids[k * 6 : (k + 1) * 6]
            results[k] = (ids, client.predict(ids))
        finally:
            client.close()

    threads = [
        threading.Thread(target=worker, args=(k,)) for k in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ok = len(results) == 8 and all(
        np.array_equal(emb, direct[ids.astype(np.int64) - 1])
        for ids, emb in results.values()
    )
    stats_client = ServingClient((server.host, server.port))
    stats = stats_client.stats()
    stats_client.close()
    server.stop()
    print(json.dumps({
        "selftest": "ok" if ok else "MISMATCH",
        "requests": stats["requests"],
        "batches": stats["batches"],
        "coalesced": stats["batches"] < stats["requests"],
    }))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--selftest", action="store_true",
                    help="in-process server+client smoke; exit 0 on parity")
    ap.add_argument("--data", help="graph directory (Graph.load)")
    ap.add_argument("--model-dir", help="EstimatorConfig.model_dir (ckpt)")
    ap.add_argument("--features", default="feat")
    ap.add_argument("--label-feature", default=None)
    ap.add_argument("--dims", default="128,128")
    ap.add_argument("--label-dim", type=int, default=2)
    ap.add_argument("--conv", default="sage")
    ap.add_argument("--fanouts", default="10,10")
    ap.add_argument("--full-neighbor", action="store_true",
                    help="deterministic full-neighbor flow (replayable)")
    ap.add_argument("--max-degree", type=int, default=32)
    ap.add_argument("--buckets", default="8,32,128",
                    help="padded batch-size buckets, comma-separated")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-us", type=int, default=2000)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--registry", default=None)
    ap.add_argument("--replica", type=int, default=0)
    ap.add_argument("--native", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.data or not args.model_dir:
        ap.error("--data and --model-dir are required (or --selftest)")
    server = serve_model(build_runtime(args), args)
    print(
        f"serving model on {server.host}:{server.port} "
        f"(buckets {server.runtime.buckets}, max_batch "
        f"{server.batcher.max_batch}, max_wait "
        f"{int(server.batcher.max_wait_s * 1e6)}us)",
        flush=True,
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
