"""Model-serving CLI (`euler.start` parity for the online path).

Boots one ModelServer — or a replicated fleet — over a graph dir + Orbax
checkpoint:

    python -m euler_tpu.tools.serve --data DIR --model-dir CKPT \
        --dims 128,128 --label-dim 2 --port 9200 --replicas 4

Graph queries run in-process against the local shard files (native
engine when available); model config must match the checkpoint. With
`--registry REG` the servers heartbeat into the same registry the graph
services use, so clients discover model replicas the way they discover
shards. `--replicas N` boots N servers (consecutive ports when --port is
pinned, ephemeral otherwise), each with its own runtime + batcher —
clients front them with a ServingRouter (`ServingClient(addrs,
routing="consistent_hash")`). `--hedge MS` is the fleet's recommended
hedge delay, printed with the topology (and exercised by the fleet
selftest). `--reload` watches the checkpoint path and hot-swaps every
replica — zero downtime — when a new checkpoint lands.

`--selftest` is the smoke mode: builds a tiny synthetic graph + trains a
2-step checkpoint in a temp dir, boots server + client in-process,
asserts served predictions match direct inference bit-for-bit, prints a
JSON summary, and exits 0 — wired into the fast test gate. With
`--replicas N` the selftest boots the whole fleet and additionally
proves routed parity, per-replica fleet stats, and hot-reload canary
parity.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading


def build_runtime(args, graph=None):
    import numpy as np

    from euler_tpu.dataflow import FullNeighborDataFlow, SageDataFlow
    from euler_tpu.estimator import EstimatorConfig
    from euler_tpu.graph import Graph
    from euler_tpu.models import GraphSAGESupervised
    from euler_tpu.serving import InferenceRuntime

    if graph is None:
        graph = Graph.load(args.data, native=None if args.native else False)
    features = args.features.split(",") if args.features else []
    dims = [int(x) for x in args.dims.split(",")]
    # each replica gets its OWN flow over the shared graph: a flow is
    # only ever queried from its replica's single batcher thread
    if args.full_neighbor:
        flow = FullNeighborDataFlow(
            graph,
            features,
            num_hops=len(dims),
            max_degree=args.max_degree,
            label_feature=args.label_feature,
        )
    else:
        flow = SageDataFlow(
            graph,
            features,
            fanouts=[int(x) for x in args.fanouts.split(",")],
            label_feature=args.label_feature,
            rng=np.random.default_rng(args.seed),
        )
    model = GraphSAGESupervised(
        dims=dims, label_dim=args.label_dim, conv=args.conv
    )
    return InferenceRuntime(
        model,
        flow,
        EstimatorConfig(model_dir=args.model_dir),
        buckets=tuple(int(b) for b in args.buckets.split(",")),
    )


def serve_fleet(args) -> list:
    """Boot args.replicas ModelServers over one shared graph."""
    from euler_tpu.distributed.rendezvous import make_registry
    from euler_tpu.graph import Graph
    from euler_tpu.serving import ModelServer

    registry = make_registry(args.registry) if args.registry else None
    graph = Graph.load(args.data, native=None if args.native else False)
    servers = []
    for i in range(args.replicas):
        runtime = build_runtime(args, graph=graph)
        port = args.port + i if args.port else 0
        server = ModelServer(
            runtime,
            host=args.host,
            port=port,
            max_batch=args.max_batch,
            max_wait_us=args.max_wait_us,
            max_queue=args.max_queue,
            registry=registry,
            shard=args.replica + i,
        )
        runtime.warmup()
        servers.append(server.start())
    return servers


def _ckpt_signature(model_dir: str) -> tuple:
    """Change token for the reload watcher: moves ONLY when a new
    COMPLETE checkpoint commits (training/checkpoint.py COMMIT marker),
    so a poll landing mid-write — a trainer still fsync'ing a
    `ckpt_*.tmp-*` dir, or a torn dir left by a kill -9 — can never
    trigger a swap onto a torn checkpoint. Legacy single-path Orbax
    dirs keep the old newest-entry-mtime behavior."""
    from euler_tpu.training.checkpoint import watch_signature

    return watch_signature(model_dir)


def watch_reload(servers, model_dir: str, stop_event, poll_s: float):
    """Hot-swap every replica whenever a new COMPLETE checkpoint lands
    under model_dir — the serving fleet never restarts for a deploy,
    and never loads a half-written one."""
    last = _ckpt_signature(model_dir)
    while not stop_event.wait(poll_s):
        now = _ckpt_signature(model_dir)
        if now == last:
            continue
        last = now
        for server in servers:
            try:
                report = server.runtime.swap()
                print(
                    f"hot-reloaded {server.host}:{server.port}: "
                    f"{json.dumps(report)}",
                    flush=True,
                )
            except Exception as e:  # keep serving the old checkpoint
                print(
                    f"hot-reload FAILED on {server.host}:{server.port}: "
                    f"{e!r} (replica keeps its current checkpoint)",
                    flush=True,
                )


def _durability_probe(graph_json: dict, watch_ids, replication: int = 1) -> dict:
    """Boot one DURABLE graph shard (WAL + snapshots) in a temp dir,
    stream a couple of mutations through the wire, and report the
    operator-facing durability stats — the selftest's proof that
    `wal_bytes` / `last_snapshot_epoch` / `recovering` surface end to
    end, and what a fleet's `graph_shards` section will carry. With
    `replication > 1` the shard is a lease-coordinated replica group
    instead: R members, quorum-acked writes, and the probe additionally
    proves every follower converged bit-identical to the primary."""
    import shutil
    import tempfile
    import time as _time

    import numpy as np

    from euler_tpu.distributed import connect
    from euler_tpu.distributed.service import serve_shard
    from euler_tpu.distributed.writer import GraphWriter
    from euler_tpu.graph.builder import convert_json

    tmp = tempfile.mkdtemp(prefix="etpu_serve_durability_")
    svcs = []
    try:
        data_dir = f"{tmp}/graph"
        convert_json(graph_json, data_dir, num_partitions=1)
        if replication > 1:
            for r in range(replication):
                svcs.append(serve_shard(
                    data_dir, 0, native=False,
                    registry_path=f"{tmp}/reg",
                    wal_dir=f"{tmp}/wal_r{r}",
                    replica=r, group_size=replication, lease_ttl=2.0,
                ))
            deadline = _time.monotonic() + 15.0
            while _time.monotonic() < deadline and not any(
                s.repl_status()["role"] == "primary" for s in svcs
            ):
                _time.sleep(0.05)
            graph = connect(registry_path=f"{tmp}/reg", num_shards=1)
        else:
            svcs.append(serve_shard(
                data_dir, 0, native=False, wal_dir=f"{tmp}/wal",
            ))
            graph = connect(cluster={0: [(svcs[0].host, svcs[0].port)]})
        with GraphWriter(graph) as w:
            w.upsert_edges(
                np.asarray(watch_ids, np.uint64),
                np.roll(np.asarray(watch_ids, np.uint64), 1),
                None,
                np.full(len(watch_ids), 2.0, np.float32),
            )
            w.flush()
            pre = graph.shards[0].stats()
            w.publish()
        primary = next(
            (s for s in svcs if s.repl_status()["role"] == "primary"),
            svcs[0],
        )
        primary.snapshot_now()
        post = graph.shards[0].stats()
        out = {
            "wal_bytes": int(pre.get("wal_bytes", 0)),
            "wal_bytes_after_snapshot": int(post.get("wal_bytes", 0)),
            "last_snapshot_epoch": post.get("last_snapshot_epoch"),
            "recovering": post.get("recovering"),
            "graph_epoch": post.get("graph_epoch"),
        }
        if replication > 1:
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline and any(
                s._wal.tell() != primary._wal.tell() for s in svcs
            ):
                _time.sleep(0.05)
            ref = primary.store.arrays
            parity = all(
                sorted(s.store.arrays) == sorted(ref)
                and all(
                    np.array_equal(
                        np.asarray(s.store.arrays[k]), np.asarray(ref[k])
                    )
                    for k in ref
                )
                for s in svcs
            )
            st = primary.repl_status()
            out["replication"] = {
                "group_size": replication,
                "term": st["term"],
                "ack_mode": st["ack_mode"],
                "bit_parity": bool(parity),
            }
        if hasattr(graph, "stop_topology_watch"):
            graph.stop_topology_watch()
        return out
    except Exception as e:  # surfaced in the JSON, fails the selftest
        return {"error": repr(e)[:200]}
    finally:
        for svc in svcs:
            svc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def selftest(
    replicas: int = 1,
    hedge_ms: float | None = None,
    replication: int = 1,
) -> int:
    """In-process boot: synthetic graph → 2-step checkpoint → fleet +
    concurrent clients → bit-parity vs direct inference. Exit 0 = the
    serving path works end to end on this host. replicas > 1 also proves
    routed parity, fleet stats, and hot-reload canary parity."""
    import tempfile

    import numpy as np

    from euler_tpu.dataflow import FullNeighborDataFlow
    from euler_tpu.estimator import (
        Estimator,
        EstimatorConfig,
        id_batches,
        node_batches,
    )
    from euler_tpu.graph import Graph
    from euler_tpu.models import GraphSAGESupervised
    from euler_tpu.serving import (
        InferenceRuntime,
        ModelServer,
        ServingClient,
    )

    rng = np.random.default_rng(0)
    n = 48
    nodes = [
        {
            "id": i + 1,
            "type": 0,
            "weight": 1.0,
            "features": [
                {"name": "feat", "type": "dense",
                 "value": rng.normal(size=4).tolist()},
                {"name": "label", "type": "dense", "value": [1.0, 0.0]},
            ],
        }
        for i in range(n)
    ]
    edges = [
        {"src": i + 1, "dst": (i + d) % n + 1, "type": 0, "weight": 1.0,
         "features": []}
        for i in range(n)
        for d in (1, 2, 3)
    ]
    graph = Graph.from_json({"nodes": nodes, "edges": edges})

    def mkflow():
        return FullNeighborDataFlow(
            graph, ["feat"], num_hops=2, max_degree=4, label_feature="label"
        )

    flow = mkflow()
    model = GraphSAGESupervised(dims=[8, 8], label_dim=2)
    cfg = EstimatorConfig(
        model_dir=tempfile.mkdtemp(prefix="etpu_serve_selftest_"),
        total_steps=2,
        log_steps=10**9,
    )
    est = Estimator(
        model, node_batches(graph, flow, 16, rng=np.random.default_rng(1)),
        cfg,
    )
    est.train(log=False)

    all_ids = np.arange(1, n + 1, dtype=np.uint64)
    batches, chunks = id_batches(flow, all_ids, 16)
    _, direct = est.infer(batches, chunks)

    servers = []
    for i in range(max(1, replicas)):
        runtime = InferenceRuntime(model, mkflow(), cfg, buckets=(16,))
        runtime.warmup()
        servers.append(
            ModelServer(runtime, max_wait_us=5000, shard=i).start()
        )
    addrs = [(s.host, s.port) for s in servers]
    results: dict = {}

    def worker(k: int):
        client = ServingClient(
            addrs,
            routing="consistent_hash" if len(addrs) > 1 else None,
            hedge_ms=hedge_ms,
        )
        try:
            ids = all_ids[k * 6 : (k + 1) * 6]
            results[k] = (ids, client.predict(ids))
        finally:
            client.close()

    threads = [
        threading.Thread(target=worker, args=(k,)) for k in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ok = len(results) == 8 and all(
        np.array_equal(emb, direct[ids.astype(np.int64) - 1])
        for ids, emb in results.values()
    )
    stats_client = ServingClient(addrs)
    stats = stats_client.stats()
    fleet = stats_client.fleet_stats()
    reload_parity = None
    if len(addrs) > 1:
        # rolling hot reload of the same checkpoint: canary rows must be
        # bit-identical pre/post swap on every replica
        reports = stats_client.reload(canary_ids=all_ids[:16])
        reload_parity = all(
            r.get("canary_parity") is True for r in reports.values()
        )
        ok = ok and reload_parity and len(fleet) == len(addrs)
    stats_client.close()
    requests = sum(
        s.get("requests", 0) for s in fleet.values() if "error" not in s
    )
    batches_n = sum(
        s.get("batches", 0) for s in fleet.values() if "error" not in s
    )
    for s in servers:
        s.stop()
    durability = _durability_probe(
        {"nodes": nodes, "edges": edges}, all_ids[:4],
        replication=replication,
    )
    ok = ok and durability.get("wal_bytes", 0) > 0
    ok = ok and durability.get("recovering") is False
    if replication > 1:
        ok = ok and (
            durability.get("replication", {}).get("bit_parity") is True
        )
    out = {
        "selftest": "ok" if ok else "MISMATCH",
        "durability": durability,
        "replicas": len(addrs),
        "requests": requests if len(addrs) > 1 else stats["requests"],
        "batches": batches_n if len(addrs) > 1 else stats["batches"],
        "coalesced": (
            (batches_n if len(addrs) > 1 else stats["batches"])
            < (requests if len(addrs) > 1 else stats["requests"])
        ),
    }
    if reload_parity is not None:
        out["reload_parity"] = reload_parity
    print(json.dumps(out))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--selftest", action="store_true",
                    help="in-process server+client smoke; exit 0 on parity")
    ap.add_argument("--data", help="graph directory (Graph.load)")
    ap.add_argument("--model-dir", help="EstimatorConfig.model_dir (ckpt)")
    ap.add_argument("--features", default="feat")
    ap.add_argument("--label-feature", default=None)
    ap.add_argument("--dims", default="128,128")
    ap.add_argument("--label-dim", type=int, default=2)
    ap.add_argument("--conv", default="sage")
    ap.add_argument("--fanouts", default="10,10")
    ap.add_argument("--full-neighbor", action="store_true",
                    help="deterministic full-neighbor flow (replayable)")
    ap.add_argument("--max-degree", type=int, default=32)
    ap.add_argument("--buckets", default="8,32,128",
                    help="padded batch-size buckets, comma-separated")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-us", type=int, default=2000)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--registry", default=None)
    ap.add_argument("--replica", type=int, default=0,
                    help="shard index of the FIRST replica (registry key)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="number of ModelServer replicas to boot")
    ap.add_argument("--replication", type=int, default=1, metavar="R",
                    help="graph-shard replica-group size for the "
                         "selftest durability probe (R>1 proves "
                         "quorum-acked writes + follower bit-parity)")
    ap.add_argument("--hedge", type=float, default=None, metavar="MS",
                    help="recommended client hedge delay for this fleet "
                         "(ms; default p95-tracked, EULER_TPU_HEDGE_MS)")
    ap.add_argument("--reload", action="store_true",
                    help="watch --model-dir and hot-swap every replica "
                         "when a new checkpoint lands (zero downtime)")
    ap.add_argument("--native", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.replicas < 1:
        ap.error("--replicas must be >= 1")
    if args.selftest:
        return selftest(
            replicas=args.replicas,
            hedge_ms=args.hedge,
            replication=args.replication,
        )
    if not args.data or not args.model_dir:
        ap.error("--data and --model-dir are required (or --selftest)")
    servers = serve_fleet(args)
    for server in servers:
        print(
            f"serving model on {server.host}:{server.port} "
            f"(replica {server.shard}, buckets {server.runtime.buckets}, "
            f"max_batch {server.batcher.max_batch}, max_wait "
            f"{int(server.batcher.max_wait_s * 1e6)}us)",
            flush=True,
        )
    print(
        json.dumps({
            "fleet": [f"{s.host}:{s.port}" for s in servers],
            "routing": "consistent_hash",
            "hedge_ms": args.hedge,
            "hot_reload": bool(args.reload),
        }),
        flush=True,
    )
    stop_event = threading.Event()
    if args.reload:
        threading.Thread(
            target=watch_reload,
            args=(servers, args.model_dir, stop_event,
                  float(os.environ.get("EULER_TPU_RELOAD_POLL_S", 10.0))),
            daemon=True,
            name="ckpt-reload-watch",
        ).start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        stop_event.set()
        for server in servers:
            server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
