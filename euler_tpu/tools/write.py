"""Streaming-mutation console: pump an edge file into a live cluster.

The operational face of the write path (ISSUE 8): reads edge records
from a file (JSON-lines or TSV), batches them through a `GraphWriter`,
and publishes epochs on a row cadence — the "millions of users
generating events" shape, replayable from a file.

    python -m euler_tpu.tools.write --registry REG --num-shards N \
        --edges events.jsonl --batch 4096 --publish-every 50000
    python -m euler_tpu.tools.write --data DIR --edges events.jsonl
    python -m euler_tpu.tools.write --selftest

Record formats (one per line):
    {"src": 1, "dst": 2, "type": 0, "weight": 2.5}
    {"op": "delete", "src": 1, "dst": 2, "type": 0}
    1<TAB>2<TAB>0<TAB>2.5          (src dst [type] [weight])

Failure semantics ride the RPC stack: transport faults retry with the
batch's idempotency key (never double-applied), typed errors
(`OverloadError` = delta full → publish and continue; unknown-op = the
server predates the mutation verbs) fail fast. See OPERATIONS.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _parse_line(line: str):
    """line → ("upsert"|"delete", src, dst, type, weight) or None."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    if line.startswith("{"):
        rec = json.loads(line)
        return (
            rec.get("op", "upsert"),
            int(rec["src"]),
            int(rec["dst"]),
            int(rec.get("type", 0)),
            float(rec.get("weight", 1.0)),
        )
    parts = line.split()
    if len(parts) < 2:
        raise ValueError(f"bad edge line: {line!r}")
    return (
        "upsert",
        int(parts[0]),
        int(parts[1]),
        int(parts[2]) if len(parts) > 2 else 0,
        float(parts[3]) if len(parts) > 3 else 1.0,
    )


def stream_edges(
    graph,
    lines,
    batch: int = 4096,
    publish_every: int = 50_000,
    progress=None,
    replicated: bool = False,
) -> dict:
    """Stream parsed edge lines into `graph` via a GraphWriter; publish
    every `publish_every` rows and once at the end. Returns totals.

    `replicated` targets a replica-group cluster: per-shard primaries
    are discovered up front (`repl_status`) so the first batch lands on
    the lease holder instead of paying a NotPrimaryError redirect, and
    the totals report how many redirects the stream rode (failovers
    mid-stream show up here)."""
    from euler_tpu.distributed.writer import GraphWriter

    writer = GraphWriter(graph, batch_rows=batch)
    if replicated:
        writer.discover_primaries()
    n_up = n_del = 0
    since_publish = 0
    publishes = 0
    t0 = time.perf_counter()
    for line in lines:
        rec = _parse_line(line)
        if rec is None:
            continue
        op, src, dst, tt, w = rec
        if op == "delete":
            writer.delete_edges([src], [dst], [tt])
            n_del += 1
        else:
            writer.upsert_edges([src], [dst], [tt], [w])
            n_up += 1
        since_publish += 1
        if publish_every and since_publish >= publish_every:
            res = writer.publish()
            publishes += 1
            since_publish = 0
            if progress:
                progress(
                    f"published epoch(s) {res['epochs']} after "
                    f"{n_up + n_del} rows"
                )
    res = writer.publish()
    publishes += 1
    dt = time.perf_counter() - t0
    out = {
        "upserts": n_up,
        "deletes": n_del,
        "publishes": publishes,
        "epochs": res["epochs"],
        "rows_per_sec": round((n_up + n_del) / max(dt, 1e-9), 1),
    }
    if replicated:
        out["redirects"] = int(writer.redirects)
    return out


def _selftest() -> int:
    """In-process round trip: stream edges into a 2-shard graph and
    prove the merged store is bit-identical to a from-scratch build."""
    import numpy as np

    from euler_tpu.graph import Graph
    from euler_tpu.graph.builder import build_from_json

    nodes = [
        {"id": i, "type": 0, "weight": 1.0, "features": []}
        for i in range(1, 9)
    ]
    edges = [
        {"src": i, "dst": i % 8 + 1, "type": 0, "weight": 1.0,
         "features": []}
        for i in range(1, 9)
    ]
    data = {"nodes": nodes, "edges": edges}
    g = Graph.from_json(data, num_partitions=2)
    lines = [
        '{"src": 1, "dst": 5, "type": 0, "weight": 3.0}',
        "2\t6\t0\t2.0",
        '{"op": "delete", "src": 3, "dst": 4, "type": 0}',
    ]
    out = stream_edges(g, lines, batch=2, publish_every=2)
    ref = {
        "nodes": nodes,
        "edges": [e for e in edges if not (e["src"] == 3 and e["dst"] == 4)]
        + [
            {"src": 1, "dst": 5, "type": 0, "weight": 3.0, "features": []},
            {"src": 2, "dst": 6, "type": 0, "weight": 2.0, "features": []},
        ],
    }
    _, ref_shards = build_from_json(ref, 2)
    for p in range(2):
        for k, v in ref_shards[p].items():
            got = np.asarray(g.shards[p].arrays[k])
            if not np.array_equal(got, np.asarray(v)):
                print(f"selftest FAILED: part{p} {k} diverged", file=sys.stderr)
                return 1
    print(f"selftest ok: {out}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", default=None, help="local graph directory")
    ap.add_argument("--registry", default=None)
    ap.add_argument("--num-shards", type=int, default=None)
    ap.add_argument("--edges", default=None, help="edge file (jsonl/tsv)")
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument(
        "--publish-every",
        type=int,
        default=50_000,
        help="publish an epoch every N streamed rows (0 = only at EOF)",
    )
    ap.add_argument("--replication", type=int, default=1, metavar="R",
                    help="target cluster runs R-replica shard groups: "
                         "pre-discover per-shard primaries and report "
                         "redirects ridden (failovers mid-stream)")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.edges:
        ap.error("need --edges (or --selftest)")
    if args.data:
        from euler_tpu.graph import Graph

        graph = Graph.load(args.data, native=False)
    elif args.registry:
        from euler_tpu.distributed import connect

        graph = connect(
            registry_path=args.registry, num_shards=args.num_shards
        )
    else:
        ap.error("need --data or --registry")
    with open(args.edges) as f:
        out = stream_edges(
            graph,
            f,
            batch=args.batch,
            publish_every=args.publish_every,
            progress=lambda msg: print(msg, flush=True),
            replicated=args.replication > 1,
        )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
