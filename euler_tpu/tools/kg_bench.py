"""KG-family training throughput: TransE/H/R/D steps/sec at batch 100.

The reference publishes sec/epoch for the TransX family against OpenKE
(examples/TransX/README.md:53-60: TransE/H/R/D 9.36/11.87/26.30/11.71 s
vs OpenKE's 11.92/17.12/31.32/15.11 s on a Xeon E5-2682 v4 x8, FB15k =
483,142 train triples, bs=100). This driver measures the same workload
shape on TPU through the sharded-embedding path: batch 100 triples +
2x8 corrupted negatives per step, FB15k-sized tables (14,951 entities /
1,345 relations, dim 100), K steps per scan dispatch.

Prints one JSON line per variant:
  {"variant": ..., "steps_per_sec": ..., "sec_per_epoch_fb15k": ...}
sec_per_epoch_fb15k = (483142 / 100) / steps_per_sec — directly
comparable to the published table's rows.

Usage: python -m euler_tpu.tools.kg_bench [--smoke] [--variants transe,...]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

FB15K_TRIPLES = 483_142
PUBLISHED = {  # examples/TransX/README.md:53-60 (reference / OpenKE)
    "transe": (9.36, 11.92),
    "transh": (11.87, 17.12),
    "transr": (26.30, 31.32),
    "transd": (11.71, 15.11),
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="tiny sizes, CPU ok")
    ap.add_argument("--variants", default="transe,transh,transr,transd")
    ap.add_argument("--batch", type=int, default=100)
    ap.add_argument("--num-negs", type=int, default=8)
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--steps-per-call", type=int, default=32)
    ap.add_argument("--calls", type=int, default=20)
    args = ap.parse_args(argv)

    import jax

    if args.smoke:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    import optax

    from euler_tpu.models import TransX

    n_ent, n_rel = (2000, 40) if args.smoke else (14_951, 1_345)
    calls = 3 if args.smoke else args.calls
    k = 4 if args.smoke else args.steps_per_call
    b, negs = args.batch, args.num_negs

    rng = np.random.default_rng(0)

    def batch_stack(n_steps):
        return {
            "h": rng.integers(0, n_ent, (n_steps, b)).astype(np.int32),
            "r": rng.integers(0, n_rel, (n_steps, b)).astype(np.int32),
            "t": rng.integers(0, n_ent, (n_steps, b)).astype(np.int32),
            "neg_h": rng.integers(0, n_ent, (n_steps, b, negs)).astype(np.int32),
            "neg_t": rng.integers(0, n_ent, (n_steps, b, negs)).astype(np.int32),
        }

    for variant in args.variants.split(","):
        model = TransX(
            num_entities=n_ent, num_relations=n_rel, dim=args.dim,
            variant=variant,
        )
        tx = optax.adam(0.01)
        one = jax.tree_util.tree_map(lambda x: x[0], batch_stack(1))
        params = model.init(jax.random.PRNGKey(0), one)
        import flax.linen as nn

        params = nn.meta.unbox(params)
        opt_state = tx.init(params)

        @jax.jit
        def multi_step(params, opt_state, stacked):
            def body(carry, batch):
                params, opt_state = carry

                def loss_fn(p):
                    _, loss, _, _ = model.apply(p, batch)
                    return loss

                loss, grads = jax.value_and_grad(loss_fn)(params)
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), stacked
            )
            return params, opt_state, losses

        # one host-staged stack reused every call: the measurement targets
        # device step time (sampling negatives is a trivial int stream the
        # host pipeline hides — the local bench leg proves that pattern)
        stacked = jax.device_put(batch_stack(k))
        params, opt_state, _ = multi_step(params, opt_state, stacked)  # compile
        jax.block_until_ready(params)
        t0 = time.perf_counter()
        for _ in range(calls):
            params, opt_state, losses = multi_step(params, opt_state, stacked)
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        sps = calls * k / dt
        rec = {
            "variant": variant,
            "platform": platform,
            "batch": b,
            "dim": args.dim,
            "entities": n_ent,
            "steps_per_sec": round(sps, 1),
            "sec_per_epoch_fb15k": round(FB15K_TRIPLES / b / sps, 3),
        }
        if variant in PUBLISHED and not args.smoke:
            ref, openke = PUBLISHED[variant]
            rec["reference_sec_per_epoch"] = ref
            rec["openke_sec_per_epoch"] = openke
            rec["speedup_vs_reference"] = round(
                ref / rec["sec_per_epoch_fb15k"], 1
            )
        print(json.dumps(rec))
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
