"""Interactive GQL console (euler/tools/remote_console parity).

Connects to a local graph dir or a running cluster and evaluates GQL
chains, e.g.:

    > v([1,2]).sampleNB(0, 1, 3).as(nb)
    > sampleN(0, 5).values(f3).as(feats)

Usage:
    python -m euler_tpu.tools.console --data DIR
    python -m euler_tpu.tools.console --registry REG --num-shards N
"""

from __future__ import annotations

import argparse

import numpy as np

from euler_tpu.query import run_gql


def _print_result(name, value):
    if isinstance(value, tuple):
        for i, part in enumerate(value):
            print(f"{name}[{i}]:\n{np.asarray(part)}")
    else:
        print(f"{name}:\n{np.asarray(value)}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", default=None, help="local graph directory")
    ap.add_argument("--registry", default=None)
    ap.add_argument("--num-shards", type=int, default=None)
    ap.add_argument(
        "--plan",
        choices=("fused", "per-op", "off"),
        default=None,
        help="remote execution mode: fused = one exec_plan RPC per shard"
        " (default), per-op = one round per step (A/B fallback), off ="
        " legacy routing; sets EULER_TPU_FUSED_PLAN",
    )
    args = ap.parse_args(argv)
    if args.plan is not None:
        import os

        os.environ["EULER_TPU_FUSED_PLAN"] = {
            "fused": "1", "per-op": "0", "off": "off"
        }[args.plan]
    if args.data:
        from euler_tpu.graph import Graph

        graph = Graph.load(args.data)
    elif args.registry:
        from euler_tpu.distributed import connect

        graph = connect(
            registry_path=args.registry, num_shards=args.num_shards
        )
    else:
        ap.error("need --data or --registry")
    from euler_tpu.query.plan import is_remote_graph, plan_mode

    mode = plan_mode() if is_remote_graph(graph) else "local"
    print(f"euler_tpu console — GQL chains ({mode} execution); 'quit' to exit")
    while True:
        try:
            line = input("> ").strip()
        except (EOFError, KeyboardInterrupt):
            break
        if not line:
            continue
        if line in ("quit", "exit"):
            break
        try:
            results = run_gql(graph, line)
        except Exception as e:
            print(f"error: {e}")
            continue
        for name, value in results.items():
            _print_result(name, value)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
