"""Durable trainer CLI — the process `TrainerSupervisor` supervises.

Boots a GraphSAGE supervised trainer over a local graph dir or a remote
cluster, wrapped in a `TrainingSession` (atomic retained checkpoints,
async save, SIGTERM drain, anomaly guard, watchdog):

    python -m euler_tpu.tools.train --data DIR --model-dir CKPT \
        --total-steps 200 --checkpoint-every 20 [--resume]

`--resume` restores the newest COMPLETE retained checkpoint — params,
opt_state, step, and the batch-source cursor — so a respawn after
`kill -9` continues the run bit-exactly under the standing seed
contract. Exit codes: 0 = target step reached, 3 = preempted (SIGTERM
drain flushed a final checkpoint first), anything else = crash (the
supervisor respawns with `--resume`).

`--mutate-spec FILE` replays a deterministic graph-mutation schedule:
a JSON list of `{"step": S, "upsert_edges": [[src, dst, type, w], ...]}`
entries, each published when global step S is reached (entries at or
before the resumed step are applied at boot — the resumed process
reconstructs the same data-version timeline the uninterrupted run saw).
This pins the resume-across-a-mutation-epoch proof: the batch stream,
the RNG streams, AND the graph epoch schedule are all functions of the
global step, so kill -9 anywhere leaves nothing to lose.

`--losses-out FILE` appends one JSON line per run segment with the
per-step losses — the bit-parity oracle the tier-1 resume proof diffs
against an uninterrupted run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_graph(args):
    from euler_tpu.graph import Graph

    if args.cluster:
        from euler_tpu.distributed import connect

        spec = json.loads(args.cluster)
        cluster = {
            int(k): [(h, int(p)) for h, p in v] for k, v in spec.items()
        }
        return connect(cluster=cluster)
    if args.registry:
        from euler_tpu.distributed import connect

        return connect(registry_path=args.registry, num_shards=args.shards)
    return Graph.load(args.data, native=None if args.native else False)


def build_trainer(args, graph=None):
    """(session, est, source, graph) for the CLI args — importable so
    the tier-1 proof builds the bit-identical in-process reference."""
    from euler_tpu.dataflow import FullNeighborDataFlow
    from euler_tpu.estimator import Estimator, EstimatorConfig
    from euler_tpu.models import GraphSAGESupervised
    from euler_tpu.training import (
        SessionConfig,
        TrainingSession,
        resumable_node_batches,
    )

    if graph is None:
        graph = _load_graph(args)
    dims = [int(x) for x in args.dims.split(",")]
    features = args.features.split(",") if args.features else []
    # full-neighbor flow: deterministic per root set, so the batch
    # stream is a pure function of (source seed, cursor)
    flow = FullNeighborDataFlow(
        graph,
        features,
        num_hops=len(dims),
        max_degree=args.max_degree,
        label_feature=args.label_feature,
    )
    source = resumable_node_batches(
        graph, flow, args.batch_size, seed=args.source_seed
    )
    model = GraphSAGESupervised(
        dims=dims, label_dim=args.label_dim, conv=args.conv
    )
    est = Estimator(
        model,
        source,
        EstimatorConfig(
            model_dir=args.model_dir,
            total_steps=args.total_steps,
            log_steps=args.log_steps,
            learning_rate=args.learning_rate,
            seed=args.seed,
            keep_checkpoints=args.keep,
        ),
    )
    session = TrainingSession(
        est,
        source=source,
        graph=graph,
        cfg=SessionConfig(
            checkpoint_every=args.checkpoint_every,
            keep=args.keep,
            async_save=not args.sync_save,
            anomaly_policy=args.anomaly_policy,
            max_strikes=args.max_strikes,
            step_deadline_s=args.step_deadline_s,
        ),
    )
    return session, est, source, graph


def apply_local_mutation(graph, spec: dict) -> dict:
    """Publish one edge-upsert wave on an in-process graph: per-shard
    DeltaStore staged + merge_delta + one store-reference swap — the
    same copy-on-write publish the wire path uses, so the data version
    the trainer reads changes atomically at a step boundary."""
    import numpy as np

    from euler_tpu.graph.delta import DeltaStore

    rows = spec.get("upsert_edges") or []
    if not rows:
        return {}
    arr = np.asarray(rows, dtype=np.float64)
    src = arr[:, 0].astype(np.uint64)
    dst = arr[:, 1].astype(np.uint64)
    tt = arr[:, 2].astype(np.int32)
    w = arr[:, 3].astype(np.float32)
    parts = len(graph.shards)
    epochs = {}
    for p in range(parts):
        osel = (src.astype(np.int64) % parts) == p
        isel = (dst.astype(np.int64) % parts) == p
        if not osel.any() and not isel.any():
            continue
        delta = DeltaStore(p, parts)
        delta.stage_edges(
            src[osel], dst[osel], tt[osel], w[osel],
            src[isel], dst[isel], tt[isel], w[isel],
        )
        new_store, _rows, _ids = graph.shards[p].merge_delta(delta)
        graph.shards[p] = new_store  # one reference: no torn snapshot
        epochs[p] = int(new_store.graph_epoch)
    graph.refresh_shard_weights()
    return epochs


def apply_remote_mutation(graph, spec: dict) -> dict:
    """The same wave through the wire write path (remote clusters)."""
    import numpy as np

    from euler_tpu.distributed.writer import GraphWriter

    rows = spec.get("upsert_edges") or []
    if not rows:
        return {}
    arr = np.asarray(rows, dtype=np.float64)
    with GraphWriter(graph) as w:
        w.upsert_edges(
            arr[:, 0].astype(np.uint64),
            arr[:, 1].astype(np.uint64),
            arr[:, 2].astype(np.int32),
            arr[:, 3].astype(np.float32),
        )
        res = w.publish()
    return res.get("epochs", {})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", help="local graph directory (Graph.load)")
    ap.add_argument("--cluster", default=None,
                    help='remote cluster JSON {"0": [["host", port]], ...}')
    ap.add_argument("--registry", default=None)
    ap.add_argument("--shards", type=int, default=1)
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--total-steps", type=int, default=100)
    ap.add_argument("--checkpoint-every", type=int, default=20)
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--dims", default="8,8")
    ap.add_argument("--features", default="feat")
    ap.add_argument("--label-feature", default="label")
    ap.add_argument("--label-dim", type=int, default=2)
    ap.add_argument("--conv", default="sage")
    ap.add_argument("--max-degree", type=int, default=4)
    ap.add_argument("--learning-rate", type=float, default=0.05)
    ap.add_argument("--log-steps", type=int, default=10**9)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--source-seed", type=int, default=0)
    ap.add_argument("--resume", action="store_true",
                    help="restore the newest complete retained checkpoint")
    ap.add_argument("--sync-save", action="store_true",
                    help="inline checkpoint writes (A/B the async writer)")
    ap.add_argument("--anomaly-policy", default="skip",
                    choices=("off", "skip", "rollback", "abort"))
    ap.add_argument("--max-strikes", type=int, default=3)
    ap.add_argument("--step-deadline-s", type=float, default=0.0)
    ap.add_argument("--mutate-spec", default=None,
                    help="JSON schedule of step-aligned graph mutations")
    ap.add_argument("--losses-out", default=None,
                    help="append one JSON line of per-step losses per segment")
    ap.add_argument("--native", action="store_true")
    args = ap.parse_args(argv)
    if not (args.data or args.cluster or args.registry):
        ap.error("one of --data / --cluster / --registry is required")

    session, est, source, graph = build_trainer(args)
    resume_report = None
    if args.resume:
        resume_report = session.restore()

    schedule = []
    if args.mutate_spec:
        with open(args.mutate_spec, encoding="utf-8") as f:
            schedule = sorted(json.load(f), key=lambda m: int(m["step"]))
    apply_fn = (
        apply_remote_mutation
        if (args.cluster or args.registry)
        else apply_local_mutation
    )
    # catch-up: waves the pre-crash run already published are re-applied
    # at boot, so the resumed graph sits at the same data version the
    # uninterrupted run had at this step
    for m in schedule:
        if int(m["step"]) <= est.step:
            apply_fn(graph, m)
    pending = [m for m in schedule if int(m["step"]) > est.step]

    segments = []
    preempted = False
    targets = [int(m["step"]) for m in pending] + [args.total_steps]
    for i, target in enumerate(targets):
        remaining = target - est.step
        if remaining > 0:
            rep = session.run(remaining)
            segments.append(rep)
            if rep["preempted"]:
                preempted = True
                break
        if i < len(pending):
            apply_fn(graph, pending[i])

    if args.losses_out and segments:
        with open(args.losses_out, "a", encoding="utf-8") as f:
            for rep in segments:
                f.write(json.dumps({
                    "start_step": rep["start_step"],
                    "loss_steps": rep["loss_steps"],
                    "losses": rep["losses"],
                    "resumed_from": rep["resumed_from"],
                }) + "\n")
            f.flush()
            os.fsync(f.fileno())

    done = est.step >= args.total_steps
    print(json.dumps({
        "done": done,
        "preempted": preempted,
        "step": int(est.step),
        "resumed": resume_report,
        "telemetry": segments[-1]["telemetry"] if segments else None,
    }), flush=True)
    return 0 if done else 3


if __name__ == "__main__":
    sys.exit(main())
