"""Disaster-recovery console: epoch-consistent backup, point-in-time
restore, archive verification, and on-demand integrity scrubs.

The operational face of graph/backup.py (ISSUE 15). A *backup* is one
self-describing archive directory holding, per shard, the newest
committed snapshot plus the WAL slice that carries it to the recorded
epoch vector — committed atomically (tmp → fsync → rename) and
content-checksummed so `verify` can prove it cold. *Restore*
materializes fresh `--wal-dir`s that the normal `recover()` path
replays — at the archive head, or `--epoch E` for point-in-time
recovery (fat-finger publish? restore to E-1).

    python -m euler_tpu.tools.backup backup --wal-root WALS --out ARCH \\
        [--model-dir CKPTS]
    python -m euler_tpu.tools.backup verify --archive ARCH
    python -m euler_tpu.tools.backup restore --archive ARCH --out WALS2 \\
        [--epoch E] [--replication R] [--model-dir CKPTS2]
    python -m euler_tpu.tools.backup scrub --host H --port P [--no-repair]
    python -m euler_tpu.tools.backup --selftest

`scrub` triggers one synchronous at-rest integrity pass on a live shard
(CRC re-verification of snapshots and WAL segments; quarantine +
peer-repair) and prints the report. Failure semantics: `backup` refuses
to overwrite an existing archive, `restore` refuses unverifiable
archives and epochs outside the horizon, and corrupt artifacts are
quarantined (`*.corrupt`), never silently deleted. See OPERATIONS.md.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_backup(args) -> int:
    from euler_tpu.graph import backup as bk

    shard_dirs = bk.collect_shard_dirs(args.wal_root)
    if not shard_dirs:
        print(f"no shard WAL dirs under {args.wal_root}", file=sys.stderr)
        return 1
    man = bk.backup_cluster(
        shard_dirs, args.out,
        model_dir=args.model_dir, data_dir=args.data,
    )
    out = {
        "archive": args.out,
        "shards": {
            s: {"epoch": m["epoch"], "earliest_epoch": m["earliest_epoch"]}
            for s, m in man["shards"].items()
        },
        "trainer": (man.get("trainer") or {}).get("checkpoint"),
    }
    print(json.dumps(out))
    return 0


def _cmd_verify(args) -> int:
    from euler_tpu.graph import backup as bk

    v = bk.verify_archive(args.archive)
    print(json.dumps({
        "ok": v["ok"],
        "files_checked": v["files_checked"],
        "bad_files": v["bad_files"],
    }))
    return 0 if v["ok"] else 1


def _cmd_restore(args) -> int:
    from euler_tpu.graph import backup as bk

    rep = bk.restore_cluster(
        args.archive, args.out,
        epoch=args.epoch, replication=args.replication,
        model_dir=args.model_dir,
    )
    print(json.dumps(rep))
    return 0


def _cmd_scrub(args) -> int:
    from euler_tpu.graph import backup as bk

    rep = bk.scrub_remote(args.host, args.port)
    print(json.dumps(rep))
    return 0 if not rep.get("degraded") else 1


def _selftest() -> int:
    """In-process disaster round trip: write + publish through a durable
    shard, archive it, prove (a) a flipped archive byte is detected,
    (b) restore → recover is bit-identical to an independent
    from-scratch build of the same final graph."""
    import os
    import shutil
    import tempfile

    import numpy as np

    from euler_tpu.distributed.service import GraphService
    from euler_tpu.graph import Graph
    from euler_tpu.graph import backup as bk
    from euler_tpu.graph import wal as walmod
    from euler_tpu.graph.builder import build_from_json

    nodes = [
        {"id": i, "type": 0, "weight": 1.0, "features": []}
        for i in range(1, 9)
    ]
    edges = [
        {"src": i, "dst": i % 8 + 1, "type": 0, "weight": 1.0,
         "features": []}
        for i in range(1, 9)
    ]
    data = {"nodes": nodes, "edges": edges}
    tmp = tempfile.mkdtemp(prefix="etpu_bk_selftest_")
    svc = None
    try:
        wal_root = os.path.join(tmp, "wal")
        g = Graph.from_json(data, num_partitions=1)
        svc = GraphService(
            g.shards[0], g.meta, 0,
            wal_dir=os.path.join(wal_root, "shard_0"),
        )

        def cols(rows):
            src = np.asarray([r[0] for r in rows], np.uint64)
            dst = np.asarray([r[1] for r in rows], np.uint64)
            tt = np.asarray([r[2] for r in rows], np.int32)
            return src, dst, tt

        src, dst, tt = cols([(1, 5, 0), (2, 6, 0)])
        w = np.asarray([3.0, 2.0], np.float32)
        svc.dispatch(
            "upsert_edges", ["st:up", src, dst, tt, w, src, dst, tt, w]
        )
        dsrc, ddst, dtt = cols([(3, 4, 0)])
        svc.dispatch(
            "delete_edges", ["st:del", dsrc, ddst, dtt, dsrc, ddst, dtt]
        )
        svc.dispatch("publish_epoch", ["st:pub"])

        arch = os.path.join(tmp, "arch")
        bk.backup_cluster(bk.collect_shard_dirs(wal_root), arch)

        # (a) detection: flip one byte in a copy, verify must notice
        bad = os.path.join(tmp, "arch_bad")
        shutil.copytree(arch, bad)
        victim = os.path.join(bad, "shard_0", walmod.WAL_FILE)
        with open(victim, "r+b") as f:
            f.seek(walmod._HEADER.size + 3)
            b0 = f.read(1)
            f.seek(walmod._HEADER.size + 3)
            f.write(bytes([b0[0] ^ 0xFF]))
        if bk.verify_archive(bad)["ok"]:
            print("selftest FAILED: flipped archive byte not detected",
                  file=sys.stderr)
            return 1

        # (b) restore the intact archive, recover, compare against an
        # independent from-scratch build of the expected final graph
        out = os.path.join(tmp, "restored")
        bk.restore_cluster(arch, out)
        g2 = Graph.from_json(data, num_partitions=1)
        rec = walmod.recover(
            g2.meta, 0, os.path.join(out, "shard_0"), g2.shards[0]
        )
        ref = {
            "nodes": nodes,
            "edges": [
                e for e in edges
                if not (e["src"] == 3 and e["dst"] == 4)
            ] + [
                {"src": 1, "dst": 5, "type": 0, "weight": 3.0,
                 "features": []},
                {"src": 2, "dst": 6, "type": 0, "weight": 2.0,
                 "features": []},
            ],
        }
        _, ref_shards = build_from_json(ref, 1)
        for k, v in ref_shards[0].items():
            got = np.asarray(rec.store.arrays[k])
            if not np.array_equal(got, np.asarray(v)):
                print(f"selftest FAILED: {k} diverged from oracle",
                      file=sys.stderr)
                return 1
        print("selftest ok: backup detected corruption and restored "
              "bit-identical to the from-scratch oracle")
        return 0
    finally:
        if svc is not None:
            svc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--selftest", action="store_true")
    sub = ap.add_subparsers(dest="cmd")

    b = sub.add_parser("backup", help="archive a cluster's durable state")
    b.add_argument("--wal-root", required=True,
                   help="root holding shard_<i>[/replica_<r>] WAL dirs")
    b.add_argument("--out", required=True, help="archive dir to create")
    b.add_argument("--model-dir", default=None,
                   help="also archive the newest COMMIT-complete "
                        "trainer checkpoint from this dir")
    b.add_argument("--data", default=None,
                   help="immutable base graph dir (recorded in the "
                        "manifest for the restore runbook)")

    v = sub.add_parser("verify", help="re-checksum an archive at rest")
    v.add_argument("--archive", required=True)

    r = sub.add_parser("restore", help="materialize WAL dirs from an "
                                       "archive (at head or --epoch E)")
    r.add_argument("--archive", required=True)
    r.add_argument("--out", required=True,
                   help="wal-root to create (refuses to overwrite)")
    r.add_argument("--epoch", type=int, default=None,
                   help="point-in-time target epoch (default: head)")
    r.add_argument("--replication", type=int, default=1,
                   help="materialize R replica dirs per shard")
    r.add_argument("--model-dir", default=None,
                   help="restore the archived trainer checkpoint here")

    s = sub.add_parser("scrub", help="run one integrity pass on a live "
                                     "shard and print the report")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, required=True)

    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.cmd == "backup":
        return _cmd_backup(args)
    if args.cmd == "verify":
        return _cmd_verify(args)
    if args.cmd == "restore":
        return _cmd_restore(args)
    if args.cmd == "scrub":
        return _cmd_scrub(args)
    ap.error("need a subcommand (backup/verify/restore/scrub) "
             "or --selftest")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
