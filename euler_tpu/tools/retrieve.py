"""Retrieval-serving CLI (the embedding top-K fleet, tools/serve.py's
sibling for the retrieval path).

Boots RetrievalServer shards over a trained checkpoint's embedding
table:

    python -m euler_tpu.tools.retrieve --model-dir CKPT --num-ids 10000 \
        --metric cosine --num-parts 2 --part 0 --replicas 2 --port 9300

Every server loads the corpus via `EmbeddingCorpus.from_checkpoint`
(COMMIT discipline: a half-written checkpoint is invisible), shards it
by row id, and serves `retrieve` / `corpus_stats` / `reload_corpus`.
Clients front the fleet with `RetrievalClient([[shard0 replicas],
[shard1 replicas], ...])`. A later checkpoint hot-swaps in with
`RetrievalClient.reload_all` — zero downtime, canary bit-parity
reported per replica.

`--selftest` is the smoke mode: builds a synthetic corpus, commits it
as a real checkpoint in a temp dir, boots a 2-shard x 2-replica fleet
in-process, asserts filtered AND unfiltered answers match the
independent NumPy oracle bit-for-bit, hot-swaps to a second checkpoint
mid-session (canary proof + post-swap oracle parity), prints a JSON
summary, and exits 0 — wired into the fast test gate.
"""

from __future__ import annotations

import argparse
import json
import sys


def _load_ids(args):
    import numpy as np

    if args.ids:
        return np.load(args.ids).astype(np.uint64).reshape(-1)
    if args.num_ids:
        return np.arange(args.num_ids, dtype=np.uint64)
    raise SystemExit("need --ids FILE.npy or --num-ids N")


def _load_attrs(path):
    import numpy as np

    if not path:
        return None
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def make_loader(args, ids, attrs):
    """loader(source) for RetrievalServer: re-reads the newest COMMITted
    checkpoint (or source={'step': N} pins one) on every (re)load."""
    from euler_tpu.retrieval import EmbeddingCorpus

    def loader(source):
        step = (source or {}).get("step")
        return EmbeddingCorpus.from_checkpoint(
            args.model_dir,
            ids,
            attrs=attrs,
            metric=args.metric,
            step=step,
            leaf=args.leaf,
        )

    return loader


def serve(args) -> int:
    import threading

    from euler_tpu.distributed.rendezvous import make_registry
    from euler_tpu.retrieval.server import RetrievalServer

    ids = _load_ids(args)
    attrs = _load_attrs(args.attrs)
    loader = make_loader(args, ids, attrs)
    registry = make_registry(args.registry) if args.registry else None
    servers = []
    for r in range(args.replicas):
        port = args.port + r if args.port else 0
        srv = RetrievalServer(
            loader=loader,
            part=args.part,
            num_parts=args.num_parts,
            host=args.host,
            port=port,
            registry=registry,
            impl=args.impl,
            warm_k=args.warm_k,
        ).start()
        servers.append(srv)
        print(
            json.dumps(
                {
                    "serving": f"{srv.host}:{srv.port}",
                    "shard": args.part,
                    "num_parts": args.num_parts,
                    **srv._engine.corpus.stats(),
                }
            ),
            flush=True,
        )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        for srv in servers:
            srv.stop(drain_s=2.0)
    return 0


def selftest(seed: int = 0, verbose: bool = True) -> int:
    import tempfile

    import numpy as np

    from euler_tpu.retrieval import EmbeddingCorpus, numpy_topk_oracle
    from euler_tpu.retrieval.client import RetrievalClient
    from euler_tpu.retrieval.server import RetrievalServer
    from euler_tpu.training.checkpoint import CheckpointStore

    rng = np.random.default_rng(seed)
    n, d = 300, 24
    ids = np.sort(
        rng.choice(50_000, size=n, replace=False).astype(np.uint64)
    )
    attrs = {"cat": rng.integers(0, 4, size=n)}
    tables = {
        1: rng.standard_normal((n, d)).astype(np.float32),
        2: rng.standard_normal((n, d)).astype(np.float32),
    }
    model_dir = tempfile.mkdtemp(prefix="etpu_retrieve_selftest_")
    store = CheckpointStore(model_dir)
    store.save_leaves(1, [tables[1]], [], {})

    def loader(source):
        step = (source or {}).get("step")
        return EmbeddingCorpus.from_checkpoint(
            model_dir, ids, attrs=attrs, metric="cosine", step=step
        )

    servers, shard_addrs = [], []
    for part in range(2):
        reps = []
        for _ in range(2):
            srv = RetrievalServer(
                loader=loader, part=part, num_parts=2, warm_k=8
            ).start()
            servers.append(srv)
            reps.append((srv.host, srv.port))
        shard_addrs.append(reps)
    cli = RetrievalClient(shard_addrs)
    summary = {"rows": n, "dim": d, "fleet": "2 shards x 2 replicas"}
    ok = True
    try:
        q = rng.standard_normal((4, d)).astype(np.float32)
        got = cli.retrieve(q, 10)
        want = numpy_topk_oracle(ids, tables[1], q, 10, metric="cosine")
        unfiltered = all(
            np.array_equal(g, w) for g, w in zip(got, want)
        )
        dnf = [[("cat", "in", [0, 2])]]
        mask = np.isin(np.asarray(attrs["cat"]), [0, 2])
        gotf = cli.retrieve(q, 10, dnf=dnf)
        wantf = numpy_topk_oracle(
            ids, tables[1], q, 10, metric="cosine", mask=mask
        )
        filtered = all(
            np.array_equal(g, w) for g, w in zip(gotf, wantf)
        )
        # hot swap: commit checkpoint 2, roll the fleet, re-check parity
        store.save_leaves(2, [tables[2]], [], {})
        reports = cli.reload_all(canary_q=q, canary_k=4)
        swapped = all(
            r.get("swapped") is True and r.get("canary_parity") is False
            for r in reports.values()
        )
        got2 = cli.retrieve(q, 10)
        want2 = numpy_topk_oracle(ids, tables[2], q, 10, metric="cosine")
        post_swap = all(
            np.array_equal(g, w) for g, w in zip(got2, want2)
        )
        ok = unfiltered and filtered and swapped and post_swap
        summary.update(
            unfiltered_parity=unfiltered,
            filtered_parity=filtered,
            hot_swap=swapped,
            post_swap_parity=post_swap,
            versions=sorted(
                {r.get("to_version") for r in reports.values()}
            ),
            router=cli.router.stats(),
        )
    except Exception as e:  # surfaced in the JSON, fails the selftest
        ok = False
        summary["error"] = repr(e)
    finally:
        cli.close()
        for srv in servers:
            srv.stop()
    summary["selftest"] = "ok" if ok else "MISMATCH"
    if verbose:
        print(json.dumps(summary, indent=2))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--selftest", action="store_true",
                    help="in-process fleet smoke test vs the NumPy oracle")
    ap.add_argument("--model-dir", help="CheckpointStore dir with the "
                    "embedding table leaf")
    ap.add_argument("--ids", help=".npy of u64 row ids (row i of the "
                    "table gets ids[i])")
    ap.add_argument("--num-ids", type=int, default=0,
                    help="shorthand for ids = arange(N)")
    ap.add_argument("--attrs", default=None,
                    help=".npz of per-row attribute columns (DNF filters)")
    ap.add_argument("--metric", default="dot", choices=("dot", "cosine"))
    ap.add_argument("--leaf", type=int, default=None,
                    help="param-leaf index when the checkpoint holds "
                    "several [N, D] tables")
    ap.add_argument("--part", type=int, default=0)
    ap.add_argument("--num-parts", type=int, default=1)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--registry", default=None)
    ap.add_argument("--impl", default="auto",
                    choices=("auto", "xla", "pallas", "interpret"))
    ap.add_argument("--warm-k", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest(seed=args.seed)
    if not args.model_dir:
        ap.error("--model-dir is required (or --selftest)")
    return serve(args)


if __name__ == "__main__":
    sys.exit(main())
