#!/usr/bin/env bash
# Background watcher: probe the TPU tunnel on a loop; the moment it is
# alive, run the measurement suite ($SUITE, default tpu_suite.sh) and
# exit. Logs to $LOG (default /tmp/etpu_tpu_watch.log).
set -u
cd "$(dirname "$0")/../.."
LOG="${LOG:-/tmp/etpu_tpu_watch.log}"
OUT="${OUT:-/tmp/etpu_tpu_suite}"
SUITE="${SUITE:-euler_tpu/tools/tpu_suite.sh}"
MAX_TRIES="${MAX_TRIES:-40}"
SLEEP="${SLEEP:-900}"
for i in $(seq 1 "$MAX_TRIES"); do
  ts=$(date +%H:%M:%S)
  probe=$(timeout 120 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
  echo "[$ts] probe $i/$MAX_TRIES: ${probe:-unreachable}" >> "$LOG"
  if [ "${probe:-}" = "tpu" ] || [ "${probe:-}" = "axon" ]; then
    echo "[$ts] chip alive — running $SUITE" >> "$LOG"
    bash "$SUITE" "$OUT" >> "$LOG" 2>&1
    echo "[done] suite rc=$? → $OUT" >> "$LOG"
    exit 0
  fi
  sleep "$SLEEP"
done
echo "[giveup] tunnel never came up after $MAX_TRIES tries" >> "$LOG"
exit 1
