#!/usr/bin/env bash
# One-shot TPU measurement suite — run whenever a working chip is
# available (the round-4 build window had the tunnel down throughout;
# this captures every chip-gated measurement in priority order).
#
#   bash euler_tpu/tools/tpu_suite.sh [outdir]
#
# 1. Headline bench (local + remote legs) → bench.json
# 2. KG-family throughput (TransE/H/R/D vs the published OpenKE table)
#    → kg_bench.json
# 3. Wide-F Pallas end-to-end A/B (dims 256: EULER_TPU_PALLAS=off vs
#    =pallas, local leg only) → widef_off.json / widef_pallas.json
#    — if pallas wins, raise _PALLAS_AUTO_MAX_F (ops/pallas_kernels.py)
#    and record the row in ops/PALLAS_BENCH.md.
set -u
cd "$(dirname "$0")/../.."
OUT="${1:-/tmp/etpu_tpu_suite}"
mkdir -p "$OUT"

probe=$(timeout 120 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
echo "# platform probe: ${probe:-unreachable}"
if [ "${probe:-}" != "tpu" ] && [ "${probe:-}" != "axon" ]; then
  echo "# no chip — nothing measured" && exit 1
fi

echo "# 1/3 headline bench"
timeout 1200 python bench.py | tee "$OUT/bench.json"

echo "# 2/3 KG throughput"
timeout 900 python -m euler_tpu.tools.kg_bench | tee "$OUT/kg_bench.json"

echo "# 3/3 wide-F Pallas A/B (dims 256)"
EULER_BENCH_REMOTE=0 EULER_BENCH_FEAT_DIM=256 EULER_BENCH_DIMS=256,256 \
  EULER_TPU_PALLAS=off \
  timeout 900 python bench.py | tee "$OUT/widef_off.json"
EULER_BENCH_REMOTE=0 EULER_BENCH_FEAT_DIM=256 EULER_BENCH_DIMS=256,256 \
  EULER_TPU_PALLAS=pallas \
  timeout 900 python bench.py | tee "$OUT/widef_pallas.json"

echo "# done → $OUT"
