"""Unified model-zoo runner — the `examples/run_<model>.py` scripts of the
reference (e.g. examples/gcn/run_gcn.py:46-84) folded into one CLI.

    python -m euler_tpu.examples.run_model --model gcn --dataset cora \
        --mode train --total-steps 200
    python -m euler_tpu.examples.run_model --model transe --dataset fb15k
    python -m euler_tpu.examples.run_model --model deepwalk --dataset cora

Model families (27-model zoo parity):
  conv supervised:   gcn sage gat agnn appnp arma sgcn tagcn dna gated
                     geniepath graph (examples/<name>)
  conv unsupervised: graphsage_unsup dgi gae vgae
  layerwise:         fastgcn adaptivegcn
  relation:          rgcn
  graph clf:         gin set2set gated_graph graphgcn
  embeddings:        deepwalk node2vec line
  knowledge graph:   transe transh transr transd distmult rotate
  scalable:          scalable_gcn scalable_sage

--synthetic uses each dataset's offline stand-in (this environment has no
network egress); with raw files in $EULER_TPU_DATA the real datasets load.
"""

from __future__ import annotations

import argparse

import numpy as np

CONV_MODELS = {
    "gcn": "gcn",
    "graphsage": "sage",
    "sage": "sage",
    "gat": "gat",
    "agnn": "agnn",
    "appnp": "appnp",
    "arma": "arma",
    "sgcn": "sgcn",
    "tagcn": "tagcn",
    "dna": "dna",
    "gated": "gated",
    "geniepath": "geniepath",
    "graph": "graph",
    "lgcn": "lgcn",
    "adaptivegcn": None,  # layerwise family
}
GRAPH_CLF = {"gin": ("gin", "mean"), "set2set": ("gin", "set2set"),
             "gated_graph": ("gated", "mean"), "graphgcn": ("gcn", "attention")}
KG_MODELS = {"transe", "transh", "transr", "transd", "distmult", "rotate"}


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True)
    ap.add_argument("--dataset", default="cora")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--mode", default="train",
                    choices=["train", "evaluate", "infer", "train_and_evaluate"])
    ap.add_argument("--model-dir", default="/tmp/euler_tpu_runs")
    ap.add_argument("--hidden-dim", type=int, default=32)
    ap.add_argument("--embedding-dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--fanouts", type=int, nargs="*", default=[10, 10])
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--total-steps", type=int, default=100)
    ap.add_argument("--learning-rate", type=float, default=0.01)
    ap.add_argument("--optimizer", default="adam")
    ap.add_argument("--num-negs", type=int, default=5)
    ap.add_argument("--walk-len", type=int, default=5)
    ap.add_argument("--window", type=int, default=2)
    ap.add_argument("--p", type=float, default=1.0)
    ap.add_argument("--q", type=float, default=1.0)
    ap.add_argument("--log-steps", type=int, default=20)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before device init")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--data-parallel", type=int, default=0,
                    help="devices for a data-parallel mesh (0 = single)")
    ap.add_argument("--device-flow", action="store_true",
                    help="sample batches ON the accelerator (HBM-resident "
                         "adjacency, zero per-step wire bytes) — conv "
                         "models, graphsage_unsup, rgcn, fastgcn/"
                         "adaptivegcn, gae/vgae/dgi, graph classification, "
                         "deepwalk/node2vec/line, and the TransX family; "
                         "local graphs only")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize conv layers on backward "
                         "(jax.checkpoint) — trades FLOPs for HBM on "
                         "deep stacks / wide fanouts")
    return ap


def _require_checkpoint(est):
    """evaluate/infer score TRAINED parameters; without this guard a
    missing checkpoint either crashes opaquely (params None on the
    embedding-family fast path) or silently scores random init."""
    if not est.restore():
        raise SystemExit(
            f"no checkpoint under {est.cfg.model_dir!r} — run --mode train "
            "with the same --model-dir first"
        )


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.platform:
        # must land before the first device query; a plain JAX_PLATFORMS
        # env var can be overridden by site-level config
        import jax

        jax.config.update("jax_platforms", args.platform)
    from euler_tpu.datasets import get_dataset
    from euler_tpu.estimator import Estimator, EstimatorConfig, id_batches, node_batches
    from euler_tpu.graph import Graph

    rng = np.random.default_rng(args.seed)
    ds = get_dataset(args.dataset) if args.data_dir is None else None
    graph = (
        Graph.load(args.data_dir)
        if args.data_dir
        else ds.load_graph(synthetic=args.synthetic)
    )
    max_id = int(
        max(int(np.asarray(sh.node_ids).max(initial=0)) for sh in graph.shards)
    )

    cfg = EstimatorConfig(
        model_dir=f"{args.model_dir}/{args.model}_{args.dataset}",
        batch_size=args.batch_size,
        total_steps=args.total_steps,
        learning_rate=args.learning_rate,
        optimizer=args.optimizer,
        log_steps=args.log_steps,
        seed=args.seed,
    )
    mesh = None
    if args.data_parallel:
        from euler_tpu.parallel import make_mesh

        mesh = make_mesh(args.data_parallel)

    name = args.model
    feature = "feature"
    if args.remat and (
        name in KG_MODELS
        or name in ("deepwalk", "node2vec", "line", "fastgcn",
                    "adaptivegcn", "rgcn", "scalable_gcn", "scalable_sage")
    ):
        # embedding-table and dense-layerwise families have no conv stack
        # to rematerialize — say so instead of silently ignoring the flag
        print(f"# --remat has no effect for model {name!r} (no conv stack)")
    label_dim = getattr(ds, "num_classes", 2) if ds else 2
    dims = [args.hidden_dim] * args.layers
    flow = None  # set by families that evaluate/infer through a dataflow
    if args.device_flow and not (
        name in ("deepwalk", "node2vec", "line", "graphsage_unsup", "rgcn",
                 "fastgcn", "adaptivegcn", "gae", "vgae", "dgi")
        or name in KG_MODELS
        or name in GRAPH_CLF
        or (name in CONV_MODELS and CONV_MODELS[name])
    ):
        raise SystemExit(
            f"--device-flow is not implemented for model {name!r} — it "
            "covers conv models, graphsage_unsup, rgcn, fastgcn/"
            "adaptivegcn, gae/vgae/dgi, graph classification, deepwalk/"
            "node2vec/line, and the TransX family; rerun without the flag"
        )

    # ---- family dispatch -------------------------------------------------
    if name in KG_MODELS:
        from euler_tpu.models import TransX, kg_batches

        model = TransX(
            num_entities=max_id,
            num_relations=graph.meta.num_edge_types,
            dim=args.embedding_dim,
            variant=name,
        )
        if args.device_flow:
            from euler_tpu.dataflow import DeviceKGFlow

            bf = DeviceKGFlow(
                graph, args.batch_size, args.num_negs, mesh=mesh
            )
        else:
            bf = kg_batches(graph, args.batch_size, args.num_negs, rng=rng)
        est = Estimator(model, bf, cfg, mesh=mesh)
    elif name in ("deepwalk", "node2vec", "line"):
        from euler_tpu.models import SkipGramModel, deepwalk_batches, line_batches

        model = SkipGramModel(
            num_nodes=max_id, dim=args.embedding_dim,
            shared_context=(name == "line"),
        )
        if args.device_flow:
            from euler_tpu.dataflow import DeviceEdgeFlow, DeviceWalkFlow

            bf = (
                DeviceEdgeFlow(
                    graph, args.batch_size, args.num_negs, mesh=mesh
                )
                if name == "line"
                else DeviceWalkFlow(
                    graph, args.batch_size, args.walk_len, args.window,
                    args.num_negs, p=args.p if name == "node2vec" else 1.0,
                    q=args.q if name == "node2vec" else 1.0, mesh=mesh,
                )
            )
        else:
            bf = (
                line_batches(graph, args.batch_size, args.num_negs, rng=rng)
                if name == "line"
                else deepwalk_batches(
                    graph, args.batch_size, args.walk_len, args.window,
                    args.num_negs, p=args.p if name == "node2vec" else 1.0,
                    q=args.q if name == "node2vec" else 1.0, rng=rng,
                )
            )
        est = Estimator(model, bf, cfg, mesh=mesh)
    elif name in GRAPH_CLF:
        from euler_tpu.dataflow import WholeGraphDataFlow, graph_label_batches
        from euler_tpu.models import GraphClassifier

        conv, pool = GRAPH_CLF[name]
        flow = WholeGraphDataFlow(graph, [feature], max_nodes=16, max_degree=8, rng=rng)
        model = GraphClassifier(
            conv=conv, dims=tuple(dims),
            num_classes=max(flow.num_classes, 2), pool=pool,
            remat=args.remat,
        )
        if args.device_flow:
            from euler_tpu.dataflow import DeviceWholeGraphFlow

            bf = DeviceWholeGraphFlow(
                graph, [feature], batch_size=args.batch_size,
                mesh=mesh, host_flow=flow,
            )
        else:
            bf = graph_label_batches(graph, flow, args.batch_size, rng=rng)
        est = Estimator(model, bf, cfg, mesh=mesh)
    elif name in ("fastgcn", "adaptivegcn"):
        from euler_tpu.dataflow import LayerwiseDataFlow
        from euler_tpu.models import LayerwiseGCN

        flow = LayerwiseDataFlow(
            graph, [feature], layer_sizes=[64] * args.layers,
            label_feature="label", rng=rng,
        )
        model = LayerwiseGCN(dims=dims, label_dim=label_dim)
        if args.device_flow:
            from euler_tpu.dataflow import DeviceLayerwiseFlow

            bf = DeviceLayerwiseFlow(
                graph, [feature], batch_size=args.batch_size,
                layer_sizes=[64] * args.layers, label_feature="label",
                root_node_type=0, mesh=mesh,
            )
        else:
            bf = node_batches(graph, flow, args.batch_size, 0, rng=rng)
        est = Estimator(model, bf, cfg, mesh=mesh)
    elif name == "rgcn":
        from euler_tpu.dataflow import RelationDataFlow
        from euler_tpu.models import RGCNSupervised

        flow = RelationDataFlow(
            graph, [feature], num_relations=graph.meta.num_edge_types,
            fanout=args.fanouts[0], num_hops=args.layers,
            label_feature="label", rng=rng,
        )
        model = RGCNSupervised(
            dims=dims, num_relations=graph.meta.num_edge_types,
            label_dim=label_dim, num_bases=4,
        )
        if args.device_flow:
            from euler_tpu.dataflow import DeviceRelationFlow

            bf = DeviceRelationFlow(
                graph, [feature],
                num_relations=graph.meta.num_edge_types,
                batch_size=args.batch_size, fanout=args.fanouts[0],
                num_hops=args.layers, label_feature="label",
                root_node_type=0, mesh=mesh,
            )
        else:
            bf = node_batches(graph, flow, args.batch_size, 0, rng=rng)
        est = Estimator(model, bf, cfg, mesh=mesh)
    elif name in ("gae", "vgae"):
        from euler_tpu.dataflow import SageDataFlow
        from euler_tpu.models import GAE, gae_batches

        flow = SageDataFlow(graph, [feature], fanouts=args.fanouts[:1], rng=rng)
        model = GAE(
            dims=dims[:1], variational=(name == "vgae"), remat=args.remat
        )
        if args.device_flow:
            from euler_tpu.dataflow import DeviceGaeFlow
            from euler_tpu.estimator import DeviceFeatureCache

            est = Estimator(
                model,
                DeviceGaeFlow(graph, fanouts=args.fanouts[:1],
                              batch_size=args.batch_size, mesh=mesh),
                cfg, mesh=mesh,
                feature_cache=DeviceFeatureCache(graph, [feature]),
            )
        else:
            est = Estimator(
                model, gae_batches(graph, flow, args.batch_size, rng=rng),
                cfg, mesh=mesh,
            )
    elif name == "dgi":
        from euler_tpu.dataflow import SageDataFlow
        from euler_tpu.models import DGI, dgi_batches

        flow = SageDataFlow(graph, [feature], fanouts=args.fanouts[:1], rng=rng)
        model = DGI(dims=dims[:1], remat=args.remat)
        if args.device_flow:
            from euler_tpu.dataflow import DeviceDgiFlow
            from euler_tpu.estimator import DeviceFeatureCache

            est = Estimator(
                model,
                DeviceDgiFlow(graph, fanouts=args.fanouts[:1],
                              batch_size=args.batch_size, mesh=mesh),
                cfg, mesh=mesh,
                feature_cache=DeviceFeatureCache(graph, [feature]),
            )
        else:
            est = Estimator(
                model, dgi_batches(graph, flow, args.batch_size, rng=rng),
                cfg, mesh=mesh,
            )
    elif name in ("scalable_gcn", "scalable_sage"):
        from euler_tpu.models import ScalableGNN, ScalableTrainer

        model = ScalableGNN(dims=dims, label_dim=label_dim)
        trainer = ScalableTrainer(
            graph, model, [feature], max_id=max_id,
            batch_size=args.batch_size, fanout=args.fanouts[0],
            learning_rate=args.learning_rate, rng=rng,
        )
        hist = trainer.train(args.total_steps)
        print(f"final loss: {hist[-1]:.4f}")
        return 0
    elif name == "graphsage_unsup":
        from euler_tpu.dataflow import SageDataFlow
        from euler_tpu.estimator import unsupervised_batches
        from euler_tpu.models import GraphSAGEUnsupervised

        flow = SageDataFlow(graph, [feature], fanouts=args.fanouts[: args.layers], rng=rng)
        model = GraphSAGEUnsupervised(dims=dims, remat=args.remat)
        if args.device_flow:
            from euler_tpu.dataflow import DeviceUnsupSageFlow
            from euler_tpu.estimator import DeviceFeatureCache

            est = Estimator(
                model,
                DeviceUnsupSageFlow(
                    graph, fanouts=args.fanouts[: args.layers],
                    batch_size=args.batch_size, num_negs=args.num_negs,
                    mesh=mesh,
                ),
                cfg, mesh=mesh,
                feature_cache=DeviceFeatureCache(graph, [feature]),
            )
        else:
            est = Estimator(
                model,
                unsupervised_batches(
                    graph, flow, args.batch_size, num_negs=args.num_negs, rng=rng
                ),
                cfg, mesh=mesh,
            )
    elif name in CONV_MODELS and CONV_MODELS[name]:
        from euler_tpu.dataflow import SageDataFlow
        from euler_tpu.nn import SuperviseModel

        flow = SageDataFlow(
            graph, [feature], fanouts=args.fanouts[: args.layers],
            label_feature="label", rng=rng,
        )
        # the reference's GAT example defaults improved=True (run_gat.py
        # flags) — without it, zero-valid-neighbor roots in sampled flows
        # emit zero embeddings
        conv_kwargs = {"improved": True} if CONV_MODELS[name] == "gat" else None
        model = SuperviseModel(
            conv=CONV_MODELS[name], dims=dims, label_dim=label_dim,
            conv_kwargs=conv_kwargs, remat=args.remat,
        )
        if args.device_flow:
            from euler_tpu.dataflow import DeviceSageFlow
            from euler_tpu.estimator import DeviceFeatureCache

            est = Estimator(
                model,
                DeviceSageFlow(
                    graph, fanouts=args.fanouts[: args.layers],
                    batch_size=args.batch_size, label_feature="label",
                    root_node_type=0,  # node_batches(..., 0) parity
                    mesh=mesh,
                ),
                cfg, mesh=mesh,
                feature_cache=DeviceFeatureCache(graph, [feature]),
            )
        else:
            est = Estimator(
                model, node_batches(graph, flow, args.batch_size, 0, rng=rng),
                cfg, mesh=mesh,
            )
    else:
        raise SystemExit(f"unknown model {name!r}")

    # ---- drive ----------------------------------------------------------
    if args.mode != "train" and flow is None:
        import jax.numpy as jnp

        # reject an unsupported mode BEFORE demanding a checkpoint: the
        # "train first" advice would be a dead end for a mode this model
        # can never run
        kg_eval = name in KG_MODELS and args.mode == "evaluate"
        emb_infer = (
            name in ("deepwalk", "node2vec", "line") and args.mode == "infer"
        )
        if not (kg_eval or emb_infer):
            raise SystemExit(
                f"mode {args.mode!r} is not supported for model {name!r}"
            )
        _require_checkpoint(est)
        if kg_eval:
            from euler_tpu.models import kg_rank_eval

            if ds is not None and hasattr(ds, "eval_triples") and not args.synthetic:
                triples = ds.eval_triples("test")[:500]
            else:  # offline fallback: rank sampled training edges
                e = graph.sample_edge(200, rng=rng)
                triples = np.stack(
                    [e[:, 0], e[:, 2], e[:, 1]], axis=1
                ).astype(np.int32)
            print(kg_rank_eval(model, est.params, triples, num_entities=max_id))
            return 0
        if emb_infer:
            ids = np.concatenate(
                [np.asarray(sh.node_ids) for sh in graph.shards]
            )
            emb = np.asarray(
                model.apply(
                    est.params,
                    jnp.asarray(ids.astype(np.int64).astype(np.int32)),
                    method=model.embed,
                )
            )
            import os

            os.makedirs(cfg.model_dir, exist_ok=True)
            np.save(os.path.join(cfg.model_dir, "embedding_0.npy"), emb)
            np.save(os.path.join(cfg.model_dir, "ids_0.npy"), ids)
            print(f"wrote {emb.shape} embeddings to {cfg.model_dir}")
            return 0
    if args.mode == "train":
        hist = est.train()
        if len(hist):
            print(
                f"trained {len(hist)} steps; final loss {float(hist[-1]):.4f}"
            )
    elif args.mode == "train_and_evaluate":
        splits = ds.splits(graph) if ds else {"val": graph.sample_node(64)}
        batches_fn = lambda: id_batches(flow, splits["val"], args.batch_size)[0]  # noqa: E731
        print(est.train_and_evaluate(batches_fn, eval_every=max(args.total_steps // 2, 1)))
    elif args.mode == "evaluate":
        _require_checkpoint(est)
        splits = ds.splits(graph) if ds else {"test": graph.sample_node(64)}
        batches, _ = id_batches(flow, splits["test"], args.batch_size)
        print(est.evaluate(batches))
    elif args.mode == "infer":
        _require_checkpoint(est)
        splits = ds.splits(graph) if ds else {"test": graph.sample_node(64)}
        ids = np.concatenate(list(splits.values()))
        batches, chunks = id_batches(flow, ids, args.batch_size)
        idv, emb = est.infer(batches, chunks)
        print(f"wrote {emb.shape} embeddings to {cfg.model_dir}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
