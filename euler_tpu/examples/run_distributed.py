"""Distributed training demo — the reference's dist_tf_euler.sh topology
(scripts/dist_tf_euler.sh:2-43) mapped onto this framework: graph-server
processes per shard + a trainer that discovers them through the registry
and trains GraphSAGE over remote queries.

    python -m euler_tpu.examples.run_distributed --shards 2 --steps 50

Spawns one `euler_tpu.distributed.service` subprocess per shard on a
synthetic graph, waits for registry membership, trains, then tears down.
In a real deployment each service runs on its own host and the trainer
uses open_graph("remote://<registry>?shards=N").
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before device init")
    args = ap.parse_args(argv)

    if args.platform:
        import jax

        # env JAX_PLATFORMS is overridden by site-level platform pinning,
        # so an in-process config update is the reliable switch
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from euler_tpu.datasets.synthetic import random_graph
    from euler_tpu.graph import open_graph
    from euler_tpu.graph import format as tformat

    work = tempfile.mkdtemp(prefix="etpu_dist_")
    data = os.path.join(work, "data")
    reg = os.path.join(work, "registry")

    graph = random_graph(
        num_nodes=4000, out_degree=8, feat_dim=16, seed=0,
        num_partitions=args.shards,
    )
    for p, shard in enumerate(graph.shards):
        tformat.write_arrays(os.path.join(data, f"part_{p}"), shard.arrays)
    graph.meta.save(data)

    procs = [
        subprocess.Popen(
            [
                sys.executable, "-m", "euler_tpu.distributed.service",
                "--data", data, "--shard", str(s), "--registry", reg,
            ]
        )
        for s in range(args.shards)
    ]
    try:
        remote = open_graph(f"remote://{reg}?shards={args.shards}")
        print(f"connected to {args.shards} graph servers via {reg}")

        from euler_tpu.dataflow import SageDataFlow
        from euler_tpu.estimator import (
            DeviceFeatureCache,
            Estimator,
            EstimatorConfig,
        )
        from euler_tpu.models import GraphSAGESupervised

        rng = np.random.default_rng(0)
        # full hot path against the cluster: each batch is ONE RPC — the
        # serving shard samples roots, coordinates the multi-hop fanout
        # next to the data, and returns the LEAN wire (int32 feature-cache
        # rows + labels only); features stay device-side in the cache
        cache = DeviceFeatureCache(remote, ["feat"])
        flow = SageDataFlow(
            remote, ["feat"], fanouts=[5, 5], label_feature="label", rng=rng,
            feature_mode="rows", lean=True,
        )
        model = GraphSAGESupervised(dims=[32, 32], label_dim=2)
        est = Estimator(
            model,
            lambda: (flow.minibatch(args.batch_size),),
            EstimatorConfig(
                model_dir=os.path.join(work, "model"),
                total_steps=args.steps,
                log_steps=max(args.steps // 5, 1),
            ),
            feature_cache=cache,
        )
        est.train()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


if __name__ == "__main__":
    main()
