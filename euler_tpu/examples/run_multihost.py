"""Multi-host data-parallel training demo (dist_tf_euler.sh parity).

Worker mode — one process per host, same script everywhere:
    python -m euler_tpu.examples.run_multihost \
        --coordinator host0:12345 --num-processes 2 --process-id {0,1}

Spawn mode (single-machine demo/test): the parent launches N worker
subprocesses on localhost with virtual CPU devices, collects each worker's
loss trajectory, and checks every process agrees:
    python -m euler_tpu.examples.run_multihost --spawn 2 --steps 8

The training batch is DETERMINISTIC (round-robin roots + full-neighbor
expansion), so an N-process run must produce exactly the same loss
trajectory as a single-process run — the test asserts that.

Remote-graph mode (--remote-data/--remote-registry) is the full reference
deployment in miniature (scripts/dist_tf_euler.sh:2-43 + separate graph
servers via euler/python/start_service.py:70-80): jax.distributed trainer
processes pull LEAN one-RPC minibatches from GraphService processes. The
global batch stream is defined as `--slots` server-coordinated pulls per
step with per-(step, slot) seeds; an N-process run takes slot
`process_index` of each step, a 1-process run pulls every slot and
concatenates — so both see the same global batches and the loss
trajectories must match exactly.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def build_step(model, tx):
    import jax
    import optax

    from euler_tpu.dataflow.base import hydrate_blocks

    def step(params, opt_state, batch):
        def loss_fn(p):
            _, loss, _, metric = model.apply(p, hydrate_blocks(batch))
            return loss, metric

        (loss, metric), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, metric

    return jax.jit(step, donate_argnums=(0, 1))


def concat_lean_minibatches(mbs, fanouts):
    """Concatenate LEAN grid minibatches along the root axis.

    Valid because each piece's hop-h width (per·k^h) is a multiple of the
    fanout, so the grid mapping src j → dst j//k stays aligned after
    concatenation — the single-process trajectory can replay the exact
    global batch an N-process run assembles via put_global."""
    import numpy as np

    from euler_tpu.dataflow.base import MiniBatch, fanout_block

    n = sum(len(mb.root_idx) for mb in mbs)
    feats = tuple(
        np.concatenate([mb.feats[h] for mb in mbs])
        for h in range(len(fanouts) + 1)
    )
    blocks = []
    width = n
    for k in fanouts:
        blocks.append(
            fanout_block(
                width, k, None, None, lazy=True, ship_w=False,
                ship_mask=False,
            )
        )
        width *= k
    return MiniBatch(
        feats=feats,
        masks=None,
        blocks=tuple(blocks),
        root_idx=np.concatenate([mb.root_idx for mb in mbs]),
        labels=np.concatenate([mb.labels for mb in mbs]),
        hop_ids=None,
    )


def worker(args) -> list[float]:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # spawn/test path; real worker mode keeps the host's TPU devices
        jax.config.update("jax_platforms", "cpu")

    from euler_tpu.parallel import multihost

    multihost.initialize(
        args.coordinator, args.num_processes, args.process_id
    )
    import numpy as np
    import optax

    from euler_tpu.dataflow import FullNeighborDataFlow
    from euler_tpu.datasets.synthetic import random_graph
    from euler_tpu.nn import SuperviseModel

    pc, pid = jax.process_count(), jax.process_index()
    mesh = multihost.data_mesh()
    if args.batch % pc:
        raise ValueError("batch must divide evenly over processes")
    per = args.batch // pc

    if args.remote_data:
        return _remote_worker(args, mesh, pc, pid)

    # every host loads the (same) graph; real deployments point this at a
    # shared data dir or a remote:// cluster — sampling stays host-local
    graph = random_graph(num_nodes=600, out_degree=6, feat_dim=8, seed=0)
    flow = FullNeighborDataFlow(
        graph, ["feat"], num_hops=1, max_degree=6, label_feature="label"
    )
    model = SuperviseModel(conv="sage", dims=[16], label_dim=2)

    all_ids = np.arange(1, 601, dtype=np.uint64)

    def local_roots(step_k: int) -> np.ndarray:
        # deterministic global batch; this process takes its slice
        start = step_k * args.batch
        g = all_ids[(start + np.arange(args.batch)) % len(all_ids)]
        return g[pid * per : (pid + 1) * per]

    import jax.numpy as jnp  # noqa: F401  (backend init before tracing)

    params = model.init(jax.random.PRNGKey(0), flow.query(local_roots(0)))
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    params = multihost.replicate_global(mesh, params)
    opt_state = multihost.replicate_global(mesh, opt_state)
    step = build_step(model, tx)

    losses = []
    for k in range(args.steps):
        batch = multihost.put_global(mesh, flow.query(local_roots(k)))
        params, opt_state, loss, _ = step(params, opt_state, batch)
        losses.append(float(loss))
    print(json.dumps({"process": pid, "of": pc, "losses": losses}), flush=True)
    return losses


def _remote_worker(args, mesh, pc, pid) -> list[float]:
    """Trainer pulling lean one-RPC minibatches from GraphService
    processes — the reference's trainers-plus-graph-servers topology
    (dist_tf_euler.sh + start_service.py) on jax.distributed."""
    import jax
    import numpy as np
    import optax

    from euler_tpu.dataflow import SageDataFlow
    from euler_tpu.distributed import connect
    from euler_tpu.estimator import DeviceFeatureCache
    from euler_tpu.graph import Graph
    from euler_tpu.nn import SuperviseModel
    from euler_tpu.parallel import multihost

    slots = args.slots or pc
    if slots % pc:
        raise ValueError("slots must divide evenly over processes")
    if args.batch % slots:
        raise ValueError("batch must divide evenly over slots")
    per = args.batch // slots
    fanouts = [4, 4]

    remote = connect(
        registry_path=args.remote_registry, num_shards=args.remote_shards
    )
    # feature cache bootstraps from the local shard files (one-time
    # deployment step); per-batch wire traffic afterwards is rows-only
    local = Graph.load(args.remote_data, native=False)
    cache = DeviceFeatureCache(local, ["feat"])
    flow = SageDataFlow(
        remote, ["feat"], fanouts=fanouts, label_feature="label",
        feature_mode="rows", lean=True,
    )
    model = SuperviseModel(conv="sage", dims=[16, 16], label_dim=2)

    def pull(step_k: int, slot: int):
        # per-(step, slot) seed defines the global stream independently of
        # the process topology; the server coordinates root sampling +
        # fused fanout from this seed deterministically
        flow.rng = np.random.default_rng(90_000 + step_k * 1024 + slot)
        mb = flow.minibatch(per)
        assert mb.masks is None, "lean wire downgraded mid-test"
        return mb

    my_slots = list(range(pid * (slots // pc), (pid + 1) * (slots // pc)))

    def local_batch(step_k: int):
        return concat_lean_minibatches(
            [pull(step_k, s) for s in my_slots], fanouts
        )

    tx = optax.adam(1e-2)

    from euler_tpu.dataflow.base import hydrate_blocks

    def step(params, opt_state, batch):
        def loss_fn(p):
            hyd = cache.hydrate(hydrate_blocks(batch))
            _, loss, _, metric = model.apply(p, hyd)
            return loss, metric

        (loss, metric), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, metric

    step = jax.jit(step, donate_argnums=(0, 1))

    params = model.init(
        jax.random.PRNGKey(0), cache.hydrate(hydrate_blocks(local_batch(0)))
    )
    opt_state = tx.init(params)
    params = multihost.replicate_global(mesh, params)
    opt_state = multihost.replicate_global(mesh, opt_state)

    losses = []
    for k in range(args.steps):
        batch = multihost.put_global(mesh, local_batch(k))
        params, opt_state, loss, _ = step(params, opt_state, batch)
        losses.append(float(loss))
    print(
        json.dumps({"process": pid, "of": pc, "losses": losses}), flush=True
    )
    return losses


def spawn(args) -> int:
    port = args.port
    env_base = {
        k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"
    }
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = []
    for pid in range(args.spawn):
        cmd = [
            sys.executable, "-m", "euler_tpu.examples.run_multihost",
            "--coordinator", f"localhost:{port}",
            "--num-processes", str(args.spawn),
            "--process-id", str(pid),
            "--steps", str(args.steps), "--batch", str(args.batch),
        ]
        if args.remote_data:
            cmd += [
                "--remote-data", args.remote_data,
                "--remote-registry", args.remote_registry,
                "--remote-shards", str(args.remote_shards),
                "--slots", str(args.slots or args.spawn),
            ]
        procs.append(
            subprocess.Popen(
                cmd, env=env_base, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = [p.communicate(timeout=600)[0] for p in procs]
    losses = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("{"):
                rec = json.loads(line)
                losses[rec["process"]] = rec["losses"]
    if len(losses) != args.spawn:
        print("worker output:\n" + "\n".join(out[-3000:] for out in outs))
        raise SystemExit("not all workers reported losses")
    ref = losses[0]
    for pid, ls in losses.items():
        if not all(abs(a - b) < 1e-6 for a, b in zip(ref, ls)):
            raise SystemExit(f"process {pid} diverged: {ls} vs {ref}")
    print(json.dumps({"multihost_losses": ref}))
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--spawn", type=int, default=0,
                    help="parent mode: launch N localhost workers")
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--port", type=int, default=12377)
    ap.add_argument("--remote-data", default=None,
                    help="graph data dir: pull lean one-RPC minibatches "
                         "from GraphService processes instead of sampling "
                         "in-process")
    ap.add_argument("--remote-registry", default=None)
    ap.add_argument("--remote-shards", type=int, default=2)
    ap.add_argument("--slots", type=int, default=0,
                    help="global stream slots per step (default: process "
                         "count); a 1-process run replays all slots")
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.spawn:
        return spawn(args)
    worker(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
