"""Multi-host data-parallel training demo (dist_tf_euler.sh parity).

Worker mode — one process per host, same script everywhere:
    python -m euler_tpu.examples.run_multihost \
        --coordinator host0:12345 --num-processes 2 --process-id {0,1}

Spawn mode (single-machine demo/test): the parent launches N worker
subprocesses on localhost with virtual CPU devices, collects each worker's
loss trajectory, and checks every process agrees:
    python -m euler_tpu.examples.run_multihost --spawn 2 --steps 8

The training batch is DETERMINISTIC (round-robin roots + full-neighbor
expansion), so an N-process run must produce exactly the same loss
trajectory as a single-process run — the test asserts that.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def build_step(model, tx):
    import jax
    import optax

    from euler_tpu.dataflow.base import hydrate_blocks

    def step(params, opt_state, batch):
        def loss_fn(p):
            _, loss, _, metric = model.apply(p, hydrate_blocks(batch))
            return loss, metric

        (loss, metric), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, metric

    return jax.jit(step, donate_argnums=(0, 1))


def worker(args) -> list[float]:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # spawn/test path; real worker mode keeps the host's TPU devices
        jax.config.update("jax_platforms", "cpu")

    from euler_tpu.parallel import multihost

    multihost.initialize(
        args.coordinator, args.num_processes, args.process_id
    )
    import numpy as np
    import optax

    from euler_tpu.dataflow import FullNeighborDataFlow
    from euler_tpu.datasets.synthetic import random_graph
    from euler_tpu.nn import SuperviseModel

    pc, pid = jax.process_count(), jax.process_index()
    mesh = multihost.data_mesh()
    if args.batch % pc:
        raise ValueError("batch must divide evenly over processes")
    per = args.batch // pc

    # every host loads the (same) graph; real deployments point this at a
    # shared data dir or a remote:// cluster — sampling stays host-local
    graph = random_graph(num_nodes=600, out_degree=6, feat_dim=8, seed=0)
    flow = FullNeighborDataFlow(
        graph, ["feat"], num_hops=1, max_degree=6, label_feature="label"
    )
    model = SuperviseModel(conv="sage", dims=[16], label_dim=2)

    all_ids = np.arange(1, 601, dtype=np.uint64)

    def local_roots(step_k: int) -> np.ndarray:
        # deterministic global batch; this process takes its slice
        start = step_k * args.batch
        g = all_ids[(start + np.arange(args.batch)) % len(all_ids)]
        return g[pid * per : (pid + 1) * per]

    import jax.numpy as jnp  # noqa: F401  (backend init before tracing)

    params = model.init(jax.random.PRNGKey(0), flow.query(local_roots(0)))
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    params = multihost.replicate_global(mesh, params)
    opt_state = multihost.replicate_global(mesh, opt_state)
    step = build_step(model, tx)

    losses = []
    for k in range(args.steps):
        batch = multihost.put_global(mesh, flow.query(local_roots(k)))
        params, opt_state, loss, _ = step(params, opt_state, batch)
        losses.append(float(loss))
    print(json.dumps({"process": pid, "of": pc, "losses": losses}), flush=True)
    return losses


def spawn(args) -> int:
    port = args.port
    env_base = {
        k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"
    }
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = []
    for pid in range(args.spawn):
        cmd = [
            sys.executable, "-m", "euler_tpu.examples.run_multihost",
            "--coordinator", f"localhost:{port}",
            "--num-processes", str(args.spawn),
            "--process-id", str(pid),
            "--steps", str(args.steps), "--batch", str(args.batch),
        ]
        procs.append(
            subprocess.Popen(
                cmd, env=env_base, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = [p.communicate(timeout=600)[0] for p in procs]
    losses = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("{"):
                rec = json.loads(line)
                losses[rec["process"]] = rec["losses"]
    if len(losses) != args.spawn:
        print("worker output:\n" + "\n".join(out[-3000:] for out in outs))
        raise SystemExit("not all workers reported losses")
    ref = losses[0]
    for pid, ls in losses.items():
        if not all(abs(a - b) < 1e-6 for a, b in zip(ref, ls)):
            raise SystemExit(f"process {pid} diverged: {ls} vs {ref}")
    print(json.dumps({"multihost_losses": ref}))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--spawn", type=int, default=0,
                    help="parent mode: launch N localhost workers")
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--port", type=int, default=12377)
    args = ap.parse_args(argv)
    if args.spawn:
        return spawn(args)
    worker(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
