"""ServingClient — predict() against a ModelServer, with retries.

Transport is the graph client's replica pool (distributed/client.py
RemoteShard): round-robin replicas with bad-host quarantine + timed
revival, bounded retries for TRANSPORT faults only. Server-side
decisions come back as "err" frames and are re-raised typed without
retry: OverloadError and DeadlineExceededError are deterministic
admission/deadline verdicts — retrying them at the transport layer would
amplify exactly the overload they signal. Callers own backoff policy.
"""

from __future__ import annotations

import json

import numpy as np

from euler_tpu.distributed.client import RemoteShard
from euler_tpu.distributed.errors import RpcError  # noqa: F401 (re-export)
from euler_tpu.serving.batcher import (  # noqa: F401 (re-exports)
    DeadlineExceededError,
    OverloadError,
)


class ServingClient:
    """Client for one model served by N replicas."""

    # Load-bearing verb table — graftlint's wire-protocol checker diffs
    # it against the verbs this module actually sends and against
    # ModelServer.HANDLED_VERBS; tests/test_wire_parity.py does the same
    # with the real classes at runtime.
    WIRE_VERBS = frozenset({"predict", "server_stats", "ping"})

    def __init__(self, replicas, deadline_ms: float | None = None):
        """replicas: (host, port) or [(host, port), ...].
        deadline_ms: default per-request deadline shipped to the server
        (None = requests wait as long as the transport allows)."""
        if isinstance(replicas, tuple) and len(replicas) == 2 and isinstance(
            replicas[0], str
        ):
            replicas = [replicas]
        self._pool = RemoteShard(0, list(replicas))
        self.deadline_ms = deadline_ms

    @property
    def rpc_count(self) -> int:
        return self._pool.rpc_count

    def _call(self, op: str, values: list) -> list:
        # err frames already come back typed (errors.from_wire in the
        # transport): OverloadError / DeadlineExceeded are RpcError
        # subclasses, raised as themselves and never transport-retried
        return self._pool.call(op, values)

    # -- surface ---------------------------------------------------------

    def predict(
        self, node_ids, deadline_ms: float | None = None
    ) -> np.ndarray:
        """Embeddings for node_ids ([n, D]); raises OverloadError /
        DeadlineExceededError on fast-fail verdicts."""
        ids = np.asarray(node_ids, dtype=np.uint64).reshape(-1)
        dl = deadline_ms if deadline_ms is not None else self.deadline_ms
        return self._call(
            "predict", [ids, float(dl) if dl is not None else None]
        )[0]

    def stats(self) -> dict:
        return json.loads(self._call("server_stats", [])[0])

    def ping(self) -> bool:
        return self._call("ping", []) == [0]

    def close(self):
        for r in self._pool.replicas:
            r.drop()
        self._pool.close()
