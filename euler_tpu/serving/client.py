"""ServingClient — predict() against a ModelServer fleet, with retries.

Transport is the graph client's replica pool (distributed/client.py
RemoteShard): round-robin replicas with bad-host quarantine + timed
revival, bounded retries for TRANSPORT faults only. Server-side
decisions come back as "err" frames and are re-raised typed without
retry: OverloadError and DeadlineExceededError are deterministic
admission/deadline verdicts — retrying them at the transport layer would
amplify exactly the overload they signal. Callers own backoff policy.

With `routing=` configured, predict() goes through a ServingRouter
instead of the round-robin pool: consistent-hash or least-loaded replica
choice, transport failover, and (optional) budget-capped hedging — see
serving/router.py. `fleet_stats()` / `ping_all()` address every replica
individually either way, so operators see the whole fleet, not whichever
replica the pool rotated onto.
"""

from __future__ import annotations

import json

import numpy as np

from euler_tpu.distributed.client import RemoteShard, _Replica
from euler_tpu.distributed.errors import RpcError  # noqa: F401 (re-export)
from euler_tpu.serving.batcher import (  # noqa: F401 (re-exports)
    DeadlineExceededError,
    OverloadError,
)


class ServingClient:
    """Client for one model served by N replicas."""

    # Load-bearing verb table — graftlint's wire-protocol checker diffs
    # it against the verbs this module (and the router) actually sends
    # and against ModelServer.HANDLED_VERBS; tests/test_wire_parity.py
    # does the same with the real classes at runtime.
    WIRE_VERBS = frozenset({"predict", "server_stats", "ping", "reload"})

    def __init__(
        self,
        replicas,
        deadline_ms: float | None = None,
        routing=None,
        hedge_ms: float | None = None,
        tenant: str | None = None,
    ):
        """replicas: (host, port) or [(host, port), ...].
        deadline_ms: default per-request deadline shipped to the server
        (None = requests wait as long as the transport allows).
        routing: None (PR-2 round-robin pool), a policy name
        ("consistent_hash" / "least_loaded"), or a ServingRouter to
        route predict() through. hedge_ms pins the router's hedge delay
        (None = p95-tracked). tenant: default tenant every predict is
        accounted to (per-tenant admission quotas)."""
        if isinstance(replicas, tuple) and len(replicas) == 2 and isinstance(
            replicas[0], str
        ):
            replicas = [replicas]
        self.replicas = [(str(h), int(p)) for h, p in replicas]
        self._pool = RemoteShard(0, self.replicas)
        self.deadline_ms = deadline_ms
        self.tenant = tenant
        self._router = None
        if routing is not None:
            from euler_tpu.serving.router import ServingRouter

            self._router = (
                routing
                if isinstance(routing, ServingRouter)
                else ServingRouter(
                    self.replicas,
                    policy=routing,
                    deadline_ms=deadline_ms,
                    hedge_ms=hedge_ms,
                )
            )
        # per-address handles for the fleet operator surface (stats/ping
        # must reach EVERY replica, not whichever the pool rotates onto)
        self._fleet = [
            _Replica(h, p, shard=i) for i, (h, p) in enumerate(self.replicas)
        ]

    @property
    def rpc_count(self) -> int:
        n = self._pool.rpc_count
        if self._router is not None:
            n += self._router.rpc_count
        return n

    @property
    def router(self):
        """The configured ServingRouter (None in round-robin mode)."""
        return self._router

    def _call(self, op: str, values: list) -> list:
        # err frames already come back typed (errors.from_wire in the
        # transport): OverloadError / DeadlineExceeded are RpcError
        # subclasses, raised as themselves and never transport-retried
        return self._pool.call(op, values)

    # -- surface ---------------------------------------------------------

    def predict(
        self,
        node_ids,
        deadline_ms: float | None = None,
        tenant: str | None = None,
    ) -> np.ndarray:
        """Embeddings for node_ids ([n, D]); raises OverloadError /
        DeadlineExceededError on fast-fail verdicts. Routed through the
        ServingRouter when one is configured."""
        ids = np.asarray(node_ids, dtype=np.uint64).reshape(-1)
        dl = deadline_ms if deadline_ms is not None else self.deadline_ms
        tn = tenant if tenant is not None else self.tenant
        if self._router is not None:
            return self._router.predict(ids, deadline_ms=dl, tenant=tn)
        return self._call(
            "predict", [ids, float(dl) if dl is not None else None, tn]
        )[0]

    def stats(self) -> dict:
        """server_stats from ONE replica (whichever the pool rotates
        onto) — fleet_stats() for the whole fleet. The reply carries the
        server's per-verb wire_bytes_in/out; this handle's own counters
        ride along under client_wire_bytes_*."""
        out = json.loads(self._call("server_stats", [])[0])
        out["client_wire_bytes_out"] = dict(self._pool.wire_bytes_out)
        out["client_wire_bytes_in"] = dict(self._pool.wire_bytes_in)
        return out

    def fleet_stats(self, timeout_s: float = 2.0) -> dict:
        """server_stats from EVERY replica, keyed "host:port";
        unreachable replicas map to {"error": ...} instead of vanishing
        from the operator's view."""
        out = {}
        for r in self._fleet:
            try:
                out[f"{r.host}:{r.port}"] = json.loads(
                    r.call("server_stats", [], timeout_s=timeout_s)[0]
                )
            except Exception as e:
                r.drop()
                out[f"{r.host}:{r.port}"] = {"error": repr(e)[:200]}
        return out

    def ping(self) -> bool:
        return self._call("ping", []) == [0]

    def ping_all(self, timeout_s: float = 2.0) -> dict:
        """Per-replica liveness, keyed "host:port" — a dead replica is
        False here while ping() may happily answer from a survivor."""
        out = {}
        for r in self._fleet:
            try:
                out[f"{r.host}:{r.port}"] = (
                    r.call("ping", [], timeout_s=timeout_s) == [0]
                )
            except Exception:
                r.drop()
                out[f"{r.host}:{r.port}"] = False
        return out

    def reload(
        self,
        model_dir: str | None = None,
        canary_ids=None,
        timeout_s: float = 120.0,
    ) -> dict:
        """Rolling zero-downtime hot reload across the fleet: each
        replica swaps to the checkpoint under `model_dir` (None =
        re-restore its current model_dir, picking up a newer checkpoint
        saved in place) while the others keep serving. Returns per-
        replica reports keyed "host:port"; with canary_ids each report
        carries `canary_parity` — pre/post-swap rows measured through
        that replica's LIVE batcher."""
        canary = (
            np.asarray(canary_ids, np.uint64).reshape(-1)
            if canary_ids is not None
            else None
        )
        out = {}
        for r in self._fleet:
            try:
                out[f"{r.host}:{r.port}"] = json.loads(
                    r.call(
                        "reload",
                        [model_dir, canary],
                        timeout_s=timeout_s,
                        budget_ms=timeout_s * 1e3,
                    )[0]
                )
            except Exception as e:
                r.drop()
                out[f"{r.host}:{r.port}"] = {"error": repr(e)[:200]}
        return out

    def close(self):
        if self._router is not None:
            self._router.close()
        for r in self._pool.replicas:
            r.drop()
        for r in self._fleet:
            r.drop()
        self._pool.close()
