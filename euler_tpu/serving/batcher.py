"""Dynamic micro-batcher: coalesce concurrent predict requests into one
device step.

The serving win on accelerators comes from batching concurrent requests
against a persistent compiled program (Ragged Paged Attention, arXiv:
2604.15464): a single 1-row predict wastes almost the whole step, and N
callers each paying their own step serialize on the device. The batcher
holds each arriving request for at most `max_wait_us`, packs every
request that fits under `max_batch` total rows into one runtime.predict
call, and fans the rows back out to the per-request futures.

Overload semantics (admission control): the pending queue is BOUNDED.
When it is full, submit() fast-fails with OverloadError instead of
queueing — callers get backpressure in microseconds, not a hang that
times out downstream (the reference serves recommendation traffic where
a fast degraded answer beats a slow exact one). Requests carry optional
deadlines; a request whose deadline has passed when the dispatcher picks
it up is rejected without touching the device — its device slot goes to
a request that can still use the answer.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

# the serving fast-fail verdicts are the distributed layer's typed errors
# (RpcError subclasses): they cross the wire as an err-frame name prefix
# and are exempt from transport retry at EVERY client, graph or serving
from euler_tpu.distributed.errors import (  # noqa: F401 (re-exports)
    DeadlineExceeded,
    DeadlineExceededError,
    OverloadError,
)


@dataclass
class _Request:
    ids: object
    n: int
    future: Future
    deadline: float | None  # absolute time.monotonic(), None = no deadline
    enqueued: float = field(default_factory=time.monotonic)


class MicroBatcher:
    """max-batch / max-wait-µs coalescing over a bounded request queue.

    runtime: anything with `predict(ids) -> np.ndarray` (row i of the
    output answers id i). One dispatcher thread owns the runtime, so
    stateful flows (rngs) are never raced.
    """

    def __init__(
        self,
        runtime,
        max_batch: int = 128,
        max_wait_us: int = 2000,
        max_queue: int = 256,
    ):
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self.runtime = runtime
        self.max_batch = int(max_batch)
        self.max_wait_s = max(int(max_wait_us), 0) / 1e6
        self.max_queue = int(max_queue)
        self._pending: list[_Request] = []
        self._cond = threading.Condition()
        self._closed = False
        # telemetry (read via stats(); racy reads are fine)
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.rejected_overload = 0
        self.rejected_deadline = 0
        self.errors = 0
        self._thread = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="micro-batcher"
        )
        self._thread.start()

    # -- client surface --------------------------------------------------

    def submit(self, ids, deadline: float | None = None) -> Future:
        """Enqueue one request; returns a Future of its [n, D] embeddings.

        deadline: absolute time.monotonic() bound, or None. Raises
        OverloadError IMMEDIATELY when the queue is full (admission
        control — the caller never blocks on a saturated server)."""
        import numpy as np

        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty id list")
        req = _Request(ids=ids, n=len(ids), future=Future(), deadline=deadline)
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._pending) >= self.max_queue:
                self.rejected_overload += 1
                raise OverloadError(
                    f"queue full ({self.max_queue} pending)"
                )
            self.requests += 1
            self._pending.append(req)
            self._cond.notify_all()
        return req.future

    def predict(self, ids, deadline: float | None = None):
        """submit() + wait. Raises DeadlineExceededError / OverloadError /
        whatever the runtime raised."""
        return self.submit(ids, deadline).result()

    def stats(self) -> dict:
        return {
            "requests": self.requests,
            "batches": self.batches,
            "rows": self.rows,
            "rejected_overload": self.rejected_overload,
            "rejected_deadline": self.rejected_deadline,
            "errors": self.errors,
            "pending": len(self._pending),
            "max_batch": self.max_batch,
            "max_wait_us": int(self.max_wait_s * 1e6),
            "max_queue": self.max_queue,
        }

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5)
        for req in self._drain():
            req.future.set_exception(RuntimeError("batcher closed"))

    def _drain(self) -> list:
        with self._cond:
            out, self._pending = self._pending, []
        return out

    # -- dispatcher ------------------------------------------------------

    def _take_batch(self) -> list:
        """Block until work, then linger up to max_wait_s (measured from
        the OLDEST pending request) packing arrivals under max_batch."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if self._closed:
                return []
            cutoff = self._pending[0].enqueued + self.max_wait_s
            while (
                sum(r.n for r in self._pending) < self.max_batch
                and not self._closed
            ):
                remaining = cutoff - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            taken, total = [], 0
            while self._pending:
                r = self._pending[0]
                if taken and total + r.n > self.max_batch:
                    break  # next dispatch takes it; a single oversized
                    # request still runs alone (runtime chunks it)
                taken.append(self._pending.pop(0))
                total += r.n
            return taken

    def _dispatch_loop(self):
        import numpy as np

        while True:
            taken = self._take_batch()
            if not taken:
                if self._closed:
                    return
                continue
            now = time.monotonic()
            live = []
            for r in taken:
                if r.deadline is not None and now > r.deadline:
                    self.rejected_deadline += 1
                    r.future.set_exception(
                        DeadlineExceededError(
                            f"deadline passed {now - r.deadline:.3f}s "
                            "before dispatch"
                        )
                    )
                else:
                    live.append(r)
            if not live:
                continue
            try:
                emb = self.runtime.predict(
                    np.concatenate([r.ids for r in live])
                )
                self.batches += 1
                self.rows += sum(r.n for r in live)
                off = 0
                for r in live:
                    r.future.set_result(emb[off : off + r.n])
                    off += r.n
            except BaseException as e:  # report per-request, keep serving
                self.errors += 1
                for r in live:
                    if not r.future.done():
                        r.future.set_exception(e)
