"""Dynamic micro-batcher: coalesce concurrent predict requests into one
device step.

The serving win on accelerators comes from batching concurrent requests
against a persistent compiled program (Ragged Paged Attention, arXiv:
2604.15464): a single 1-row predict wastes almost the whole step, and N
callers each paying their own step serialize on the device. The batcher
holds each arriving request for at most `max_wait_us`, packs every
request that fits under `max_batch` total rows into one runtime.predict
call, and fans the rows back out to the per-request futures.

Overload semantics (admission control): the pending queue is BOUNDED.
When it is full, submit() fast-fails with OverloadError instead of
queueing — callers get backpressure in microseconds, not a hang that
times out downstream (the reference serves recommendation traffic where
a fast degraded answer beats a slow exact one). Requests carry optional
deadlines; a request whose deadline has passed when the dispatcher picks
it up is rejected without touching the device — its device slot goes to
a request that can still use the answer.

Tenant quotas layer OVER the bounded queue: a `TenantQuota` caps each
tenant's admission rate (token bucket) and/or its share of the pending
queue, so one tenant's flood trips ITS typed OverloadError long before
the global queue fills — other tenants never see the overload it caused.

Load signals: `stats()` reads every counter under the batcher lock and
reports `inflight` (admitted, unanswered), `queue_depth`, and
`ewma_batch_ms` (EWMA device-step latency) — the signals a fleet router
ranks replicas by (least-loaded routing, hedge-delay tracking).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

# the serving fast-fail verdicts are the distributed layer's typed errors
# (RpcError subclasses): they cross the wire as an err-frame name prefix
# and are exempt from transport retry at EVERY client, graph or serving
from euler_tpu.distributed.errors import (  # noqa: F401 (re-exports)
    DeadlineExceeded,
    DeadlineExceededError,
    OverloadError,
)

# EWMA weight for the per-batch device-step latency signal: ~last 10
# batches dominate, one straggler step moves the signal but cannot own it
_EWMA_ALPHA = 0.2


class TenantQuota:
    """Per-tenant admission control, layered over the bounded queue.

    Two independent caps, each rejecting with an OverloadError naming
    the tenant (never the global queue error):

      qps         — token bucket: `qps` tokens/s refill up to `burst`;
                    an empty bucket rejects THAT tenant's next request.
      max_pending — at most this many of the tenant's requests admitted
                    but unanswered; a flooding tenant hits its share
                    long before the global queue fills, so every other
                    tenant's admission is untouched.

    Requests with tenant=None bypass the quota (single-tenant callers
    keep their PR-2 behavior). EULER_TPU_TENANT_QPS configures the qps
    cap fleet-wide; `from_env()` returns None when nothing is set so
    the no-quota hot path costs nothing.
    """

    # bounded tenant tracking: past this, the stalest idle tenant's
    # bucket is dropped (it re-fills fresh on its next request)
    MAX_TRACKED = 1024

    def __init__(self, qps=None, burst=None, max_pending=None):
        env = os.environ.get("EULER_TPU_TENANT_QPS")
        configured = qps if qps is not None else (float(env) if env else None)
        self.qps = float(configured) if configured is not None else None
        if burst is not None:
            self.burst = float(burst)
        else:
            self.burst = max(1.0, self.qps) if self.qps is not None else 0.0
        self.max_pending = int(max_pending) if max_pending is not None else None
        self._lock = threading.Lock()
        # tenant -> [tokens, last_seen_monotonic, pending, admitted, rejected]
        self._tenants: dict = {}

    def admit(self, tenant: str) -> None:
        """Charge one request to `tenant`; raises a tenant-named
        OverloadError when its quota is exhausted."""
        now = time.monotonic()
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                if len(self._tenants) >= self.MAX_TRACKED:
                    self._evict_idle()
                st = self._tenants[tenant] = [self.burst, now, 0, 0, 0]
            if self.qps is not None:
                st[0] = min(self.burst, st[0] + (now - st[1]) * self.qps)
                st[1] = now
                if st[0] < 1.0:
                    st[4] += 1
                    raise OverloadError(
                        f"tenant {tenant!r}: qps quota exceeded"
                        f" ({self.qps:g}/s, burst {self.burst:g})"
                    )
                st[0] -= 1.0
            else:
                st[1] = now
            if self.max_pending is not None and st[2] >= self.max_pending:
                st[4] += 1
                raise OverloadError(
                    f"tenant {tenant!r}: pending quota exceeded"
                    f" ({self.max_pending} in flight)"
                )
            st[2] += 1
            st[3] += 1

    def release(self, tenant: str) -> None:
        """One of `tenant`'s admitted requests resolved."""
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None and st[2] > 0:
                st[2] -= 1

    def _evict_idle(self) -> None:
        # caller holds self._lock. Tenants with requests in flight are
        # never evicted (their pending count must survive to release()).
        idle = [k for k, v in self._tenants.items() if v[2] == 0]
        if not idle:
            raise OverloadError(
                f"tenant table full ({self.MAX_TRACKED} tenants in flight)"
            )
        victim = min(idle, key=lambda k: self._tenants[k][1])
        del self._tenants[victim]

    def stats(self) -> dict:
        with self._lock:
            return {
                t: {"pending": v[2], "admitted": v[3], "rejected": v[4]}
                for t, v in self._tenants.items()
            }

    @classmethod
    def from_env(cls) -> "TenantQuota | None":
        """A quota when EULER_TPU_TENANT_QPS is set, else None (no
        per-tenant admission layer at all)."""
        if os.environ.get("EULER_TPU_TENANT_QPS"):
            return cls()
        return None


@dataclass
class _Request:
    ids: object
    n: int
    future: Future
    deadline: float | None  # absolute time.monotonic(), None = no deadline
    tenant: str | None = None
    enqueued: float = field(default_factory=time.monotonic)


class MicroBatcher:
    """max-batch / max-wait-µs coalescing over a bounded request queue.

    runtime: anything with `predict(ids) -> np.ndarray` (row i of the
    output answers id i). One dispatcher thread owns the runtime, so
    stateful flows (rngs) are never raced.
    """

    def __init__(
        self,
        runtime,
        max_batch: int = 128,
        max_wait_us: int = 2000,
        max_queue: int = 256,
        tenant_quota: TenantQuota | None = None,
    ):
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self.runtime = runtime
        self.max_batch = int(max_batch)
        self.max_wait_s = max(int(max_wait_us), 0) / 1e6
        self.max_queue = int(max_queue)
        self.tenant_quota = tenant_quota
        self._pending: list[_Request] = []
        self._cond = threading.Condition()
        self._closed = False
        # telemetry — every write AND read happens under self._cond, so a
        # stats() snapshot is internally consistent (a fleet router ranking
        # replicas must never see inflight and queue_depth from different
        # moments)
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.rejected_overload = 0
        self.rejected_deadline = 0
        self.errors = 0
        self.inflight = 0  # admitted, future not yet resolved
        self.ewma_batch_ms = 0.0  # EWMA device-step latency (load signal)
        self._thread = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="micro-batcher"
        )
        self._thread.start()

    # -- client surface --------------------------------------------------

    def submit(self, ids, deadline: float | None = None, tenant=None) -> Future:
        """Enqueue one request; returns a Future of its [n, D] embeddings.

        deadline: absolute time.monotonic() bound, or None. Raises
        OverloadError IMMEDIATELY when the queue is full (admission
        control — the caller never blocks on a saturated server) or when
        `tenant`'s quota is exhausted (typed per tenant, not global)."""
        import numpy as np

        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty id list")
        req = _Request(
            ids=ids, n=len(ids), future=Future(), deadline=deadline,
            tenant=tenant if tenant is None else str(tenant),
        )
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._pending) >= self.max_queue:
                self.rejected_overload += 1
                raise OverloadError(
                    f"queue full ({self.max_queue} pending)"
                )
            if self.tenant_quota is not None and req.tenant is not None:
                # raises the tenant-named OverloadError; counts as an
                # overload rejection for the global telemetry too
                try:
                    self.tenant_quota.admit(req.tenant)
                except OverloadError:
                    self.rejected_overload += 1
                    raise
            self.requests += 1
            self.inflight += 1
            self._pending.append(req)
            self._cond.notify_all()
        return req.future

    def predict(self, ids, deadline: float | None = None, tenant=None):
        """submit() + wait. Raises DeadlineExceededError / OverloadError /
        whatever the runtime raised."""
        return self.submit(ids, deadline, tenant=tenant).result()

    def stats(self) -> dict:
        with self._cond:
            out = {
                "requests": self.requests,
                "batches": self.batches,
                "rows": self.rows,
                "rejected_overload": self.rejected_overload,
                "rejected_deadline": self.rejected_deadline,
                "errors": self.errors,
                "pending": len(self._pending),
                # load signals (ISSUE 7): what least-loaded routing ranks by
                "inflight": self.inflight,
                "queue_depth": len(self._pending),
                "ewma_batch_ms": round(self.ewma_batch_ms, 3),
                "max_batch": self.max_batch,
                "max_wait_us": int(self.max_wait_s * 1e6),
                "max_queue": self.max_queue,
            }
        if self.tenant_quota is not None:
            out["tenants"] = self.tenant_quota.stats()
        return out

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5)
        for req in self._drain():
            self._resolve(req, exc=RuntimeError("batcher closed"))

    def _drain(self) -> list:
        with self._cond:
            out, self._pending = self._pending, []
        return out

    def _resolve(self, req: _Request, result=None, exc=None) -> None:
        """Answer one admitted request and return its quota/inflight
        charge — the ONLY way a request leaves the batcher."""
        if exc is not None:
            if not req.future.done():
                req.future.set_exception(exc)
        else:
            req.future.set_result(result)
        with self._cond:
            self.inflight -= 1
        if self.tenant_quota is not None and req.tenant is not None:
            self.tenant_quota.release(req.tenant)

    # -- dispatcher ------------------------------------------------------

    def _take_batch(self) -> list:
        """Block until work, then linger up to max_wait_s (measured from
        the OLDEST pending request) packing arrivals under max_batch."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if self._closed:
                return []
            cutoff = self._pending[0].enqueued + self.max_wait_s
            while (
                sum(r.n for r in self._pending) < self.max_batch
                and not self._closed
            ):
                remaining = cutoff - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            taken, total = [], 0
            while self._pending:
                r = self._pending[0]
                if taken and total + r.n > self.max_batch:
                    break  # next dispatch takes it; a single oversized
                    # request still runs alone (runtime chunks it)
                taken.append(self._pending.pop(0))
                total += r.n
            return taken

    def _dispatch_loop(self):
        import numpy as np

        while True:
            taken = self._take_batch()
            if not taken:
                if self._closed:
                    return
                continue
            now = time.monotonic()
            live = []
            for r in taken:
                if r.deadline is not None and now > r.deadline:
                    with self._cond:
                        self.rejected_deadline += 1
                    self._resolve(
                        r,
                        exc=DeadlineExceededError(
                            f"deadline passed {now - r.deadline:.3f}s "
                            "before dispatch"
                        ),
                    )
                else:
                    live.append(r)
            if not live:
                continue
            try:
                t0 = time.perf_counter()
                emb = self.runtime.predict(
                    np.concatenate([r.ids for r in live])
                )
                step_ms = (time.perf_counter() - t0) * 1e3
                with self._cond:
                    self.batches += 1
                    self.rows += sum(r.n for r in live)
                    self.ewma_batch_ms = (
                        step_ms
                        if self.batches == 1
                        else (1.0 - _EWMA_ALPHA) * self.ewma_batch_ms
                        + _EWMA_ALPHA * step_ms
                    )
                off = 0
                for r in live:
                    self._resolve(r, result=emb[off : off + r.n])
                    off += r.n
            except BaseException as e:  # report per-request, keep serving
                with self._cond:
                    self.errors += 1
                for r in live:
                    self._resolve(r, exc=e)
