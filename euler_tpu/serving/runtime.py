"""InferenceRuntime — trained artifacts as an online prediction function.

Loads an Orbax checkpoint + flax model + dataflow once, pre-compiles one
jitted predict program per padded batch-size bucket, and serves
`predict(node_ids) -> embeddings`. The executed program is EXACTLY the
`Estimator.infer` embed program (shared through the cross-instance jit
cache when a feature cache roots it), so served predictions are
bit-identical to offline inference on the same checkpoint: every request
batch is padded to a bucket size, and each row of a padded batch depends
only on that row's subgraph — batch composition cannot change results.

Bucketing is the TPU-serving move (Ragged Paged Attention, arXiv:
2604.15464): concurrent requests coalesce into a small fixed menu of
padded shapes against persistent compiled programs, instead of paying a
retrace/recompile per request size.

Hot reload: the checkpoint + jitted program set live in one immutable
`_Engine`; `swap()` builds and warms a NEW engine off the dispatch path,
then publishes it with a single reference assignment. Every predict()
grabs the engine reference once at entry, so an in-flight request —
including a chunked oversized one — runs start to finish on one
checkpoint and a swap can never drop, error, or mix it.
"""

from __future__ import annotations

import threading

import numpy as np

DEFAULT_BUCKETS = (8, 32, 128)


class _Engine:
    """One checkpoint's serving state: estimator + its embed program.

    Immutable after construction — swap() replaces the whole object, so
    readers never observe a half-updated (est, embed) pair."""

    __slots__ = ("est", "embed")

    def __init__(self, est, embed):
        self.est = est
        self.embed = embed


class InferenceRuntime:
    """One model + checkpoint + dataflow, compiled for serving.

    `flow` must build batches deterministically per root for bit-parity
    with offline infer (e.g. FullNeighborDataFlow, or any flow whose
    query(roots) depends only on the roots). Sampling flows still serve
    correctly — their predictions just aren't replayable.

    Not thread-safe by design: `predict` is called from ONE dispatcher
    thread (the MicroBatcher's); direct callers must serialize. `swap`
    IS safe to call from any other thread while the dispatcher runs.
    """

    def __init__(
        self,
        model,
        flow,
        cfg=None,
        feature_cache=None,
        buckets=DEFAULT_BUCKETS,
        mesh=None,
        params=None,
    ):
        """cfg: EstimatorConfig (model_dir locates the checkpoint) or a
        model_dir string. params: pre-loaded parameter pytree — skips the
        checkpoint restore (in-process selftests, tests)."""
        from euler_tpu.estimator import EstimatorConfig

        if isinstance(cfg, str):
            cfg = EstimatorConfig(model_dir=cfg)
        self.model = model
        self.flow = flow
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"bad buckets {buckets!r}")
        self._mesh = mesh
        self._feature_cache = feature_cache
        # serializes swap() callers and guards the _cfg/_engine publishes;
        # the predict path never takes it (it reads one reference)
        self._swap_lock = threading.Lock()
        with self._swap_lock:
            self._cfg = cfg
            self._engine = self._build_engine(cfg, params)
        # telemetry for the micro-batching proof: executed device batches
        # must undercut request count under concurrency
        self.device_batches = 0
        self.reloads = 0
        self.lock = threading.Lock()  # guards direct multi-caller use

    def _build_engine(self, cfg, params) -> _Engine:
        """Estimator + compiled embed program for one checkpoint — built
        entirely off the dispatch path (nothing reads it until the
        engine reference is published)."""
        from euler_tpu.estimator import Estimator

        est = Estimator(
            self.model,
            self._probe_batch_fn(),
            cfg,
            mesh=self._mesh,
            feature_cache=self._feature_cache,
            init_params=params,
        )
        if params is None:
            if not est.restore():
                raise FileNotFoundError(
                    "no checkpoint under "
                    f"{est.cfg.model_dir!r} — train + save first, or "
                    "pass params="
                )
        else:
            est._ensure_init()
        return _Engine(est, est.embed_program())

    def _probe_batch_fn(self):
        """Init-shape probe batch for Estimator._ensure_init: any roots of
        the smallest bucket size work (absent ids fetch zero features)."""
        bucket = self.buckets[0]

        def fn():
            try:
                roots = self.flow.graph.sample_node(
                    bucket, rng=np.random.default_rng(0)
                )
            except Exception:
                roots = np.ones(bucket, np.uint64)
            return (self.flow.query(roots),)

        return fn

    # -- serving surface -------------------------------------------------

    @property
    def params(self):
        return self._engine.est.params

    @property
    def _est(self):
        """The live engine's Estimator (back-compat accessor)."""
        return self._engine.est

    @property
    def _embed(self):
        """The live engine's jitted embed program (back-compat accessor)."""
        return self._engine.embed

    def bucket_for(self, n: int) -> int:
        """Smallest bucket holding n roots (n > max bucket → max bucket;
        predict then chunks)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def warmup(self) -> None:
        """Trace + compile every bucket's program up front, so the first
        real request never pays a compile."""
        eng = self._engine
        for b in self.buckets:
            self._predict_bucket(np.ones(b, np.uint64), b, eng)

    def poll_graph_epoch(self) -> bool:
        """Streaming-mutation handshake for the serving path: re-observe
        each remote shard's graph_epoch (`refresh_epoch` flushes that
        shard's ReadCache on a bump), so predictions served after a
        publish read the new epoch instead of cached pre-publish bytes.
        Local in-process graphs swap their store references at publish
        and need no poll. Safe from any thread (the predict path holds
        no state this touches); call it between batches or on a timer.
        Returns True when any shard reported a new epoch."""
        bumped = False
        graph = getattr(self.flow, "graph", None)
        for sh in getattr(graph, "shards", []) or []:
            fn = getattr(sh, "refresh_epoch", None)
            if fn is None:
                continue
            cache = getattr(sh, "_cache", None)
            before = getattr(cache, "epoch", None)
            after = int(fn())
            if before is not None and after != before:
                bumped = True
        return bumped

    def swap(self, cfg=None, params=None, warm: bool = True) -> dict:
        """Zero-downtime checkpoint hot reload.

        Builds a NEW engine from `cfg` (an EstimatorConfig / model_dir
        string; default: re-restore the current model_dir, picking up a
        newer checkpoint written in place) or from a `params` pytree,
        warms every bucket's jitted program against it, then publishes it
        with one reference assignment. Only COMPLETE checkpoints are
        candidates: the restore resolves the newest retained
        `ckpt_<step>/` whose COMMIT marker committed
        (training/checkpoint.py), so a swap racing a trainer's in-flight
        save loads the previous good checkpoint instead of a torn one —
        and a model_dir holding ONLY torn state raises instead of
        swapping. The dispatch path is never paused:
        requests in flight — even mid-chunk — finish on the engine they
        started on, and the first request after the publish runs the new
        checkpoint on already-compiled programs."""
        from euler_tpu.estimator import EstimatorConfig

        if isinstance(cfg, str):
            cfg = EstimatorConfig(model_dir=cfg)
        with self._swap_lock:
            new_cfg = cfg if cfg is not None else self._cfg
            eng = self._build_engine(new_cfg, params)
            warmed = []
            if warm:
                for b in self.buckets:
                    self._predict_bucket(np.ones(b, np.uint64), b, eng)
                    warmed.append(b)
            self._cfg = new_cfg
            self._engine = eng  # atomic publish: the swap itself
            self.reloads += 1
            return {
                "reloaded": True,
                "reloads": self.reloads,
                "warmed_buckets": warmed,
                "model_dir": getattr(new_cfg, "model_dir", None),
            }

    def predict(self, node_ids) -> np.ndarray:
        """Embeddings for `node_ids` ([n, D] float); pads each chunk to a
        bucket so only pre-compiled shapes ever execute."""
        eng = self._engine  # one checkpoint per request, even chunked
        ids = np.asarray(node_ids, dtype=np.uint64).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty id list")
        top = self.buckets[-1]
        if len(ids) <= top:
            return self._predict_bucket(ids, self.bucket_for(len(ids)), eng)
        return np.concatenate(
            [
                self._predict_bucket(ids[lo : lo + top], top, eng)
                for lo in range(0, len(ids), top)
            ]
        )

    def _predict_bucket(
        self, ids: np.ndarray, bucket: int, eng: _Engine
    ) -> np.ndarray:
        batch, n = self.flow.query_padded(ids, bucket)
        batch = eng.est._put((batch,))
        emb = np.asarray(eng.embed(eng.est.params, batch[0]))
        self.device_batches += 1
        return emb[:n]
