"""InferenceRuntime — trained artifacts as an online prediction function.

Loads an Orbax checkpoint + flax model + dataflow once, pre-compiles one
jitted predict program per padded batch-size bucket, and serves
`predict(node_ids) -> embeddings`. The executed program is EXACTLY the
`Estimator.infer` embed program (shared through the cross-instance jit
cache when a feature cache roots it), so served predictions are
bit-identical to offline inference on the same checkpoint: every request
batch is padded to a bucket size, and each row of a padded batch depends
only on that row's subgraph — batch composition cannot change results.

Bucketing is the TPU-serving move (Ragged Paged Attention, arXiv:
2604.15464): concurrent requests coalesce into a small fixed menu of
padded shapes against persistent compiled programs, instead of paying a
retrace/recompile per request size.
"""

from __future__ import annotations

import threading

import numpy as np

DEFAULT_BUCKETS = (8, 32, 128)


class InferenceRuntime:
    """One model + checkpoint + dataflow, compiled for serving.

    `flow` must build batches deterministically per root for bit-parity
    with offline infer (e.g. FullNeighborDataFlow, or any flow whose
    query(roots) depends only on the roots). Sampling flows still serve
    correctly — their predictions just aren't replayable.

    Not thread-safe by design: `predict` is called from ONE dispatcher
    thread (the MicroBatcher's); direct callers must serialize.
    """

    def __init__(
        self,
        model,
        flow,
        cfg=None,
        feature_cache=None,
        buckets=DEFAULT_BUCKETS,
        mesh=None,
        params=None,
    ):
        """cfg: EstimatorConfig (model_dir locates the checkpoint) or a
        model_dir string. params: pre-loaded parameter pytree — skips the
        checkpoint restore (in-process selftests, tests)."""
        from euler_tpu.estimator import Estimator, EstimatorConfig

        if isinstance(cfg, str):
            cfg = EstimatorConfig(model_dir=cfg)
        self.flow = flow
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"bad buckets {buckets!r}")
        self._est = Estimator(
            model,
            self._probe_batch_fn(),
            cfg,
            mesh=mesh,
            feature_cache=feature_cache,
            init_params=params,
        )
        if params is None:
            if not self._est.restore():
                raise FileNotFoundError(
                    "no checkpoint under "
                    f"{self._est.cfg.model_dir!r} — train + save first, or "
                    "pass params="
                )
        else:
            self._est._ensure_init()
        self._embed = self._est.embed_program()
        # telemetry for the micro-batching proof: executed device batches
        # must undercut request count under concurrency
        self.device_batches = 0
        self.lock = threading.Lock()  # guards direct multi-caller use

    def _probe_batch_fn(self):
        """Init-shape probe batch for Estimator._ensure_init: any roots of
        the smallest bucket size work (absent ids fetch zero features)."""
        bucket = self.buckets[0]

        def fn():
            try:
                roots = self.flow.graph.sample_node(
                    bucket, rng=np.random.default_rng(0)
                )
            except Exception:
                roots = np.ones(bucket, np.uint64)
            return (self.flow.query(roots),)

        return fn

    # -- serving surface -------------------------------------------------

    @property
    def params(self):
        return self._est.params

    def bucket_for(self, n: int) -> int:
        """Smallest bucket holding n roots (n > max bucket → max bucket;
        predict then chunks)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def warmup(self) -> None:
        """Trace + compile every bucket's program up front, so the first
        real request never pays a compile."""
        for b in self.buckets:
            self._predict_bucket(np.ones(b, np.uint64), b)

    def predict(self, node_ids) -> np.ndarray:
        """Embeddings for `node_ids` ([n, D] float); pads each chunk to a
        bucket so only pre-compiled shapes ever execute."""
        ids = np.asarray(node_ids, dtype=np.uint64).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty id list")
        top = self.buckets[-1]
        if len(ids) <= top:
            return self._predict_bucket(ids, self.bucket_for(len(ids)))
        return np.concatenate(
            [
                self._predict_bucket(ids[lo : lo + top], top)
                for lo in range(0, len(ids), top)
            ]
        )

    def _predict_bucket(self, ids: np.ndarray, bucket: int) -> np.ndarray:
        batch, n = self.flow.query_padded(ids, bucket)
        batch = self._est._put((batch,))
        emb = np.asarray(self._embed(self.params, batch[0]))
        self.device_batches += 1
        return emb[:n]
