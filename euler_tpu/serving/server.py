"""ModelServer — `predict`/`server_stats`/`reload` wire verbs over the
pooled-TCP stack.

Reuses the graph service's `_PoolServer` (distributed/service.py): a
selector thread parks idle connections, a bounded worker pool runs the
request cycle. Each worker blocks on its request's future while the
micro-batcher coalesces every in-flight worker's request into one device
step — the pool's concurrency IS the batching window. No coordinator
threads (a model server never fans out to peers).

Verbs:
  predict      [ids u64[n], deadline_ms float|None, tenant str|None]
                                                    → [emb f32[n, D]]
  server_stats []                                   → [json]
  ping         []                                   → [0]
  reload       [model_dir str|None, canary u64|None]→ [json report]

Overload and deadline rejections ride the existing "err" status frame
with a typed prefix ("OverloadError: ...", "DeadlineExceeded: ...") so
clients raise the typed exception instead of a generic RpcError — and
never failover-retry either (they are deterministic server decisions,
not transport faults). Requests without an explicit predict deadline
inherit the wire-envelope budget every verb now carries.

`reload` is the zero-downtime hot-reload verb: it runs in ONE pool
worker while every other worker keeps serving — the new checkpoint's
programs build and warm off the dispatch path, the engine publish is a
single reference swap, and when the caller ships canary ids the pre/post
rows go through the LIVE batcher (the exact served path) so the returned
`canary_parity` is a bit-level proof, not a side computation.
"""

from __future__ import annotations

import collections
import json
import time

import numpy as np

from euler_tpu.distributed.service import _PoolServer
from euler_tpu.serving.batcher import MicroBatcher, TenantQuota


class ModelServer:
    """Serves one InferenceRuntime over the wire protocol."""

    def __init__(
        self,
        runtime,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch: int | None = None,
        max_wait_us: int = 2000,
        max_queue: int = 256,
        workers: int | None = None,
        registry=None,
        shard: int = 0,
        tenant_quota: TenantQuota | None = None,
    ):
        self.runtime = runtime
        if max_batch is None:
            max_batch = max(getattr(runtime, "buckets", (128,)))
        if tenant_quota is None:
            tenant_quota = TenantQuota.from_env()
        self.batcher = MicroBatcher(
            runtime,
            max_batch=max_batch,
            max_wait_us=max_wait_us,
            max_queue=max_queue,
            tenant_quota=tenant_quota,
        )
        self.may_coordinate = False  # _PoolServer: no coordinator threads
        if workers is None:
            # graph-service sizing (cpu*2) is for CPU-bound store ops; a
            # serving worker spends its life parked on a batcher future
            # while the DEVICE computes, and the number of workers is the
            # coalescing window — size for concurrency, not cores
            import os

            workers = min(64, max(16, (os.cpu_count() or 1) * 4))
        self.server = _PoolServer((host, port), self, workers)
        self.host, self.port = self.server.server_address
        self.registry = registry
        self.shard = shard
        self._beat = None
        self._started = time.monotonic()
        # per-verb wire byte counters, filled by _PoolServer at the
        # socket seam (same telemetry stance as the graph service);
        # surfaced through server_stats -> fleet_stats
        self.wire_bytes_in: collections.Counter = collections.Counter()
        self.wire_bytes_out: collections.Counter = collections.Counter()

    # -- lifecycle -------------------------------------------------------

    def start(self):
        self.server.start()
        if self.registry is not None:
            self._beat = self.registry.register(
                self.shard, self.host, self.port
            )
        return self

    def stop(self, drain_s: float | None = None):
        """Shut down; with drain_s, gracefully: deregister, refuse new
        connections, finish in-flight predicts (bounded), then close."""
        if self._beat is not None:
            self._beat.set()
        if drain_s:
            self.server.drain(drain_s)
        self.server.shutdown()
        self.server.server_close()
        self.batcher.close()

    # -- _PoolServer service surface -------------------------------------

    # Load-bearing: dispatch() gates on it, graftlint's wire-protocol
    # checker diffs it against the `op ==` arms and ServingClient's
    # WIRE_VERBS, and tests/test_wire_parity.py asserts parity at runtime.
    HANDLED_VERBS = frozenset({"predict", "server_stats", "ping", "reload"})

    def is_coordinator(self, op: str) -> bool:
        return False

    def dispatch(self, op: str, a: list) -> list:
        if op not in self.HANDLED_VERBS:
            raise ValueError(f"unknown op {op!r}")
        if op == "predict":
            deadline_ms = a[1] if len(a) > 1 else None
            tenant = a[2] if len(a) > 2 else None
            deadline = (
                time.monotonic() + float(deadline_ms) / 1e3
                if deadline_ms
                else None
            )
            if deadline is None:
                # no explicit predict deadline: the wire-envelope budget
                # (every verb carries one now) bounds the batcher wait too
                from euler_tpu.distributed.service import current_deadline

                deadline = current_deadline()
            # admission control raises OverloadError HERE (fast-fail);
            # otherwise the worker blocks on the future while the batcher
            # coalesces it with the other in-flight workers' requests
            return [self.batcher.predict(a[0], deadline, tenant=tenant)]
        if op == "server_stats":
            stats = self.batcher.stats()
            stats.update(
                device_batches=getattr(self.runtime, "device_batches", None),
                buckets=list(getattr(self.runtime, "buckets", ())),
                reloads=getattr(self.runtime, "reloads", 0),
                uptime_s=round(time.monotonic() - self._started, 3),
                wire_bytes_in=dict(self.wire_bytes_in),
                wire_bytes_out=dict(self.wire_bytes_out),
            )
            durability = self._graph_durability()
            if durability is not None:
                stats["graph_shards"] = durability
            return [json.dumps(stats)]
        if op == "ping":
            return [0]
        if op == "reload":
            return [json.dumps(self._reload(a))]
        raise RuntimeError(
            f"op {op!r} is in HANDLED_VERBS but has no dispatch arm"
        )

    def _graph_durability(self) -> dict | None:
        """Per-shard durability lag of the graph this server reads
        (remote shards only — their `stats` verb carries `wal_bytes` /
        `last_snapshot_epoch` / `recovering`). Surfaces through
        `server_stats` → `ServingClient.fleet_stats()`, so operators see
        how far the serving fleet's graph is from its last snapshot
        without polling the graph tier separately. None for in-process
        graphs (no wire, publish swaps are their durability story)."""
        flow = getattr(self.runtime, "flow", None)
        graph = getattr(flow, "graph", None)
        out: dict = {}
        for sh in getattr(graph, "shards", []) or []:
            if not hasattr(sh, "call") or not hasattr(sh, "stats"):
                continue  # local store: no stats verb
            key = str(getattr(sh, "shard", len(out)))
            try:
                # tight deadline: a dead graph shard shows up as an error
                # entry in ~1s instead of stalling server_stats behind
                # the full transport retry budget
                s = json.loads(sh.call("stats", [], deadline_s=1.0)[0])
            except Exception as e:  # a dead shard must show up, not vanish
                out[key] = {"error": repr(e)[:200]}
                continue
            out[key] = {
                k: s.get(k)
                for k in (
                    "graph_epoch", "wal_bytes", "last_snapshot_epoch",
                    "recovering", "delta_pending",
                )
            }
        return out or None

    def _reload(self, a: list) -> dict:
        """Hot-swap the runtime's checkpoint with a canary bit-parity
        proof measured through the live batcher (the served path)."""
        from euler_tpu.distributed.service import current_deadline

        model_dir = a[0] if a else None
        canary = a[1] if len(a) > 1 else None
        deadline = current_deadline()
        pre = None
        if canary is not None and len(canary):
            canary = np.asarray(canary, np.uint64).reshape(-1)
            pre = self.batcher.predict(canary, deadline)
        report = self.runtime.swap(cfg=model_dir if model_dir else None)
        if pre is not None:
            post = self.batcher.predict(canary, deadline)
            report["canary_n"] = int(len(canary))
            report["canary_parity"] = bool(
                pre.shape == post.shape
                and pre.dtype == post.dtype
                and np.array_equal(pre, post)
            )
        return report
