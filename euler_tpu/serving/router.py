"""ServingRouter — N replicated ModelServers behind one predict() surface.

PR 2's ModelServer is a single process: one batcher's throughput is the
fleet's throughput, and one straggling device step IS the p99. The
router scales serving the way the reference scales query serving — a
fixed fleet of workers behind a shared frontier (grpc_worker_service.cc:
48-96) — but lives CLIENT-side (the gRPC load-balancing shape): no proxy
hop, no single choke point; a fleet is just a replica address list.

Pieces:

  Routing policies (pluggable, `POLICIES`):
    consistent_hash — requests hash onto a vnode ring built from replica
        ADDRESSES, so the same ids land on the same replica (bucket and
        cache affinity) and the assignment is stable under replica-list
        order — two routers over the same fleet agree without talking.
    least_loaded — replicas ranked by the router's own in-flight count,
        then the fleet's `server_stats` load signals (queue_depth, EWMA
        batch latency) polled on a short TTL.

  Hedged requests: when the primary attempt has not answered after a
    p95-tracked delay (EULER_TPU_HEDGE_MS pins it), the SAME request is
    re-issued to the next replica in the preference order and the first
    answer wins — bit-identical to the unhedged answer by construction,
    because every replica serves the same checkpoint through the same
    deterministic padded-bucket programs. A RetryBudget-shaped token
    bucket (distributed/retry.py) caps hedges: each hedge spends a
    token, each success refills a fraction, and a dry bucket means the
    fleet is degraded — more duplicate load is exactly wrong, so hedging
    stops (EULER_TPU_HEDGE_BUDGET caps the bucket).

  Failover: transport faults quarantine the replica and the attempt
    moves on — a killed replica costs one connect error, not an error
    surfaced to the caller. Typed server verdicts (OverloadError,
    DeadlineExceeded) are deterministic decisions and NEVER cause
    failover; they surface unless a concurrent hedge genuinely answers.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from bisect import bisect_right
from collections import deque
from concurrent.futures import FIRST_COMPLETED
from concurrent.futures import wait as futures_wait

import numpy as np

from euler_tpu.distributed.client import _DaemonExecutor, _Replica
from euler_tpu.distributed.errors import (
    DeadlineExceeded,
    OverloadError,  # noqa: F401 (re-export: the quota verdict callers catch)
    RpcError,
)
from euler_tpu.distributed.retry import RetryBudget, default_timeout_s

# fallback hedge delay until the latency window has enough samples for a
# real p95 (and the floor under a degenerate all-equal window)
_HEDGE_DEFAULT_S = 0.05
_HEDGE_MIN_SAMPLES = 20


def hedge_ms_from_env() -> float | None:
    """EULER_TPU_HEDGE_MS: pinned hedge delay (None = p95-tracked)."""
    v = os.environ.get("EULER_TPU_HEDGE_MS")
    return float(v) if v else None


class _ReplicaState:
    """One replica's routing state. Mutable fields are written under the
    router lock only; `replica` owns its (thread-local) sockets."""

    __slots__ = (
        "host", "port", "index", "replica",
        "inflight", "queue_depth", "ewma_batch_ms", "bad_until",
    )

    def __init__(self, host: str, port: int, index: int):
        self.host = str(host)
        self.port = int(port)
        self.index = index
        self.replica = _Replica(self.host, self.port, shard=index)
        self.inflight = 0  # router-local in-flight attempts
        self.queue_depth = 0  # last polled server_stats load signals
        self.ewma_batch_ms = 0.0
        self.bad_until = 0.0  # monotonic quarantine horizon

    def key(self) -> str:
        return f"{self.host}:{self.port}"


class RoutingPolicy:
    """Replica preference order per request: order(ids) returns every
    replica, most-preferred first — slot 0 is the primary, slot 1 the
    hedge target, the rest the failover chain."""

    name = "?"
    uses_load_signals = False

    def __init__(self, states: list[_ReplicaState]):
        self.states = states

    def order(self, ids: np.ndarray) -> list[_ReplicaState]:
        raise NotImplementedError


class ConsistentHashPolicy(RoutingPolicy):
    """Vnode hash ring keyed by replica ADDRESS: assignment depends only
    on (request ids, fleet membership), never on replica-list order —
    the property the cache/bucket-affinity claim rests on."""

    name = "consistent_hash"
    VNODES = 64

    def __init__(self, states):
        super().__init__(states)
        points = []
        for st in states:
            for v in range(self.VNODES):
                points.append((self._hash(f"{st.key()}#{v}".encode()), st))
        points.sort(key=lambda t: t[0])
        self._ring = [h for h, _ in points]
        self._owners = [st for _, st in points]

    @staticmethod
    def _hash(raw: bytes) -> int:
        return int.from_bytes(
            hashlib.blake2b(raw, digest_size=8).digest(), "big"
        )

    def order(self, ids):
        key = self._hash(np.ascontiguousarray(ids).tobytes())
        start = bisect_right(self._ring, key) % len(self._ring)
        out, seen = [], set()
        for i in range(len(self._owners)):
            st = self._owners[(start + i) % len(self._owners)]
            if id(st) not in seen:
                seen.add(id(st))
                out.append(st)
                if len(out) == len(self.states):
                    break
        return out


class LeastLoadedPolicy(RoutingPolicy):
    """Rank by the freshest signal first: the router's own in-flight
    count (always current), then the polled queue depth and EWMA batch
    latency, with the replica address as a list-order-stable tiebreak."""

    name = "least_loaded"
    uses_load_signals = True

    def order(self, ids):
        return sorted(
            self.states,
            key=lambda st: (
                st.inflight,
                st.queue_depth,
                st.ewma_batch_ms,
                st.key(),
            ),
        )


POLICIES = {
    ConsistentHashPolicy.name: ConsistentHashPolicy,
    LeastLoadedPolicy.name: LeastLoadedPolicy,
}


class ServingRouter:
    """Routes predict() over a fleet of ModelServer replicas."""

    def __init__(
        self,
        replicas,
        policy="consistent_hash",
        deadline_ms: float | None = None,
        hedge: bool = True,
        hedge_ms: float | None = None,
        hedge_budget: RetryBudget | None = None,
        attempt_timeout_s: float = 10.0,
        quarantine_s: float = 2.0,
        stats_refresh_s: float = 0.5,
        workers: int | None = None,
    ):
        """replicas: [(host, port), ...] — one entry per ModelServer.
        policy: name in POLICIES, or a RoutingPolicy subclass.
        hedge_ms: pinned hedge delay; None tracks the p95 of this
        router's own latency window (EULER_TPU_HEDGE_MS overrides)."""
        replicas = list(replicas)
        if not replicas:
            raise ValueError("need at least one replica")
        self._states = [
            _ReplicaState(h, p, i) for i, (h, p) in enumerate(replicas)
        ]
        if isinstance(policy, str):
            try:
                policy = POLICIES[policy]
            except KeyError:
                raise ValueError(
                    f"unknown routing policy {policy!r}"
                    f" (have: {sorted(POLICIES)})"
                ) from None
        self.policy: RoutingPolicy = policy(self._states)
        self.deadline_ms = deadline_ms
        self.hedge_enabled = bool(hedge) and len(self._states) > 1
        self.hedge_ms = hedge_ms if hedge_ms is not None else hedge_ms_from_env()
        self._hedge_budget = hedge_budget or RetryBudget(
            cap=float(os.environ.get("EULER_TPU_HEDGE_BUDGET", 16.0))
        )
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.quarantine_s = float(quarantine_s)
        self.stats_refresh_s = float(stats_refresh_s)
        self._lock = threading.Lock()
        self._lat_ms: deque = deque(maxlen=512)  # bounded p95 window
        self._stats_next = 0.0
        self._ex = _DaemonExecutor(
            workers or max(16, 4 * len(self._states)), "serving-router"
        )
        # telemetry (reads under the lock via stats())
        self.requests = 0
        self.rpc_count = 0
        self.failovers = 0
        self.hedges = 0
        self.hedges_won = 0
        self.hedges_denied = 0

    # -- surface ---------------------------------------------------------

    def predict(
        self, node_ids, deadline_ms: float | None = None, tenant=None
    ) -> np.ndarray:
        """Embeddings for node_ids ([n, D]) from the first replica to
        answer; raises OverloadError / DeadlineExceededError verdicts,
        RpcError when every replica is unreachable."""
        ids = np.asarray(node_ids, dtype=np.uint64).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty id list")
        dl = deadline_ms if deadline_ms is not None else self.deadline_ms
        budget_s = float(dl) / 1e3 if dl is not None else default_timeout_s()
        deadline = time.monotonic() + budget_s
        if self.policy.uses_load_signals:
            self._refresh_load()
        order = self.policy.order(ids)
        with self._lock:
            self.requests += 1
        # futures -> is_hedge; the primary attempt owns the full failover
        # chain, a hedge starts one replica further along it
        futs = {self._ex.submit(self._attempt, order, 0, ids, tenant,
                                deadline): False}
        if self.hedge_enabled:
            delay = min(
                self._hedge_delay_s(), max(deadline - time.monotonic(), 0.0)
            )
            done, _ = futures_wait(
                set(futs), timeout=delay, return_when=FIRST_COMPLETED
            )
            if not done:
                if self._hedge_budget.try_spend():
                    with self._lock:
                        self.hedges += 1
                    futs[self._ex.submit(
                        self._attempt, order, 1, ids, tenant, deadline
                    )] = True
                else:
                    with self._lock:
                        self.hedges_denied += 1
        return self._harvest(futs, deadline)

    def _harvest(self, futs: dict, deadline: float) -> np.ndarray:
        """First successful attempt wins (bit-identical across replicas,
        so WHICH one is immaterial); errors surface only when no attempt
        succeeds — typed verdicts first, they are the real decision."""
        typed_err = None
        last_err = None
        pending = dict(futs)
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            done, _ = futures_wait(
                set(pending), timeout=remaining,
                return_when=FIRST_COMPLETED,
            )
            if not done:
                break
            for f in done:
                is_hedge = pending.pop(f)
                try:
                    out = f.result()
                except RpcError as e:
                    typed_err = typed_err or e
                    last_err = e
                except Exception as e:
                    last_err = e
                else:
                    if is_hedge:
                        with self._lock:
                            self.hedges_won += 1
                    return out
        if typed_err is not None:
            raise typed_err
        if last_err is not None:
            raise last_err
        raise DeadlineExceeded(
            "router: predict budget exhausted with attempts in flight"
        )

    def _attempt(self, order, start, ids, tenant, deadline):
        """One attempt chain: walk the preference order from `start`,
        failing over on transport faults (quarantine + next replica),
        raising typed server verdicts immediately."""
        now = time.monotonic()
        seq = order[start:] + order[:start]
        live = [st for st in seq if st.bad_until <= now]
        # all-quarantined fallback: least-recently-failed first (timed
        # revival — a fleet-wide blip must not strand the router)
        seq = live + sorted(
            (st for st in seq if st.bad_until > now),
            key=lambda st: st.bad_until,
        )
        err = None
        for st in seq:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            with self._lock:
                st.inflight += 1
                self.rpc_count += 1
            t0 = time.monotonic()
            try:
                out = st.replica.call(
                    "predict",
                    [ids, None, tenant],
                    timeout_s=min(remaining, self.attempt_timeout_s),
                    budget_ms=remaining * 1e3,
                )
                with self._lock:
                    self._lat_ms.append((time.monotonic() - t0) * 1e3)
                self._hedge_budget.on_success()
                return out[0]
            except RpcError:
                raise  # deterministic server verdict: never failover
            except (OSError, ConnectionError, ValueError) as e:
                err = e
                st.replica.drop()
                with self._lock:
                    st.bad_until = time.monotonic() + self.quarantine_s
                    self.failovers += 1
            finally:
                with self._lock:
                    st.inflight -= 1
        if err is not None:
            raise RpcError(
                f"router: all {len(seq)} replicas failed: {err}"
            )
        raise DeadlineExceeded(
            f"router: predict budget exhausted after {len(seq)} replicas"
        )

    # -- hedge delay -----------------------------------------------------

    def _hedge_delay_s(self) -> float:
        if self.hedge_ms is not None:
            return float(self.hedge_ms) / 1e3
        with self._lock:
            window = list(self._lat_ms)
        if len(window) < _HEDGE_MIN_SAMPLES:
            return _HEDGE_DEFAULT_S
        return max(float(np.percentile(window, 95)) / 1e3, 1e-3)

    # -- load signals ----------------------------------------------------

    def _refresh_load(self) -> None:
        """Refresh the fleet's server_stats load signals at most every
        stats_refresh_s — asynchronously, so ranking never waits on a
        slow or dead replica."""
        now = time.monotonic()
        with self._lock:
            if now < self._stats_next:
                return
            self._stats_next = now + self.stats_refresh_s
        for st in self._states:
            self._ex.submit(self._poll_one, st)

    def _poll_one(self, st: _ReplicaState) -> None:
        try:
            out = st.replica.call("server_stats", [], timeout_s=1.0)
            d = json.loads(out[0])
        except Exception:
            return  # dead replicas are handled by the predict-path
            # quarantine; stale signals just rank it where it was
        with self._lock:
            st.queue_depth = int(d.get("queue_depth", 0))
            st.ewma_batch_ms = float(d.get("ewma_batch_ms", 0.0))

    # -- fleet operator surface ------------------------------------------

    def fleet_stats(self, timeout_s: float = 2.0) -> dict:
        """Fresh server_stats from EVERY replica, keyed "host:port";
        unreachable replicas map to {"error": ...} instead of hiding."""
        out = {}
        for st in self._states:
            try:
                out[st.key()] = json.loads(
                    st.replica.call("server_stats", [],
                                    timeout_s=timeout_s)[0]
                )
            except Exception as e:
                st.replica.drop()
                out[st.key()] = {"error": repr(e)[:200]}
        return out

    def ping_all(self, timeout_s: float = 2.0) -> dict:
        """Per-replica liveness, keyed "host:port"."""
        out = {}
        for st in self._states:
            try:
                out[st.key()] = (
                    st.replica.call("ping", [], timeout_s=timeout_s) == [0]
                )
            except Exception:
                st.replica.drop()
                out[st.key()] = False
        return out

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            window = list(self._lat_ms)
            return {
                "policy": self.policy.name,
                "replicas": {
                    st.key(): {
                        "inflight": st.inflight,
                        "queue_depth": st.queue_depth,
                        "ewma_batch_ms": st.ewma_batch_ms,
                        "quarantined": st.bad_until > now,
                    }
                    for st in self._states
                },
                "requests": self.requests,
                "rpc_count": self.rpc_count,
                "failovers": self.failovers,
                "hedges": self.hedges,
                "hedges_won": self.hedges_won,
                "hedges_denied": self.hedges_denied,
                "hedge_tokens": self._hedge_budget.tokens,
                "p95_ms": (
                    round(float(np.percentile(window, 95)), 3)
                    if window else None
                ),
            }

    def close(self):
        self._ex.close()
        for st in self._states:
            st.replica.drop()
