"""Online inference serving: micro-batched, admission-controlled GNN
model server.

    InferenceRuntime — checkpoint + model + dataflow, compiled per bucket
    MicroBatcher     — coalesce concurrent requests into one device step
    ModelServer      — predict/server_stats wire verbs (pooled-TCP stack)
    ServingClient    — retrying client with typed fast-fail errors

See SCALE.md "Online serving" for the batching policy and overload
semantics, and `python -m euler_tpu.tools.serve` for the CLI.
"""

from euler_tpu.serving.batcher import (  # noqa: F401
    DeadlineExceededError,
    MicroBatcher,
    OverloadError,
)
from euler_tpu.serving.client import ServingClient  # noqa: F401
from euler_tpu.serving.runtime import InferenceRuntime  # noqa: F401
from euler_tpu.serving.server import ModelServer  # noqa: F401
