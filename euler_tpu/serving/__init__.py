"""Online inference serving: micro-batched, admission-controlled GNN
model serving — single replica or a routed fleet.

    InferenceRuntime — checkpoint + model + dataflow, compiled per bucket;
                       swap() hot-reloads a checkpoint with zero downtime
    MicroBatcher     — coalesce concurrent requests into one device step
    TenantQuota      — per-tenant admission layered over the bounded queue
    ModelServer      — predict/server_stats/reload wire verbs (pooled TCP)
    ServingClient    — retrying client with typed fast-fail errors,
                       fleet_stats()/ping_all() operator surface
    ServingRouter    — replicated routing (consistent-hash / least-loaded),
                       budget-capped hedging, transport failover

See SCALE.md "Online serving" for the batching policy and overload
semantics, SCALE.md "Serving fleet" for the fleet topology and knobs,
and `python -m euler_tpu.tools.serve` for the CLI.
"""

from euler_tpu.serving.batcher import (  # noqa: F401
    DeadlineExceededError,
    MicroBatcher,
    OverloadError,
    TenantQuota,
)
from euler_tpu.serving.client import ServingClient  # noqa: F401
from euler_tpu.serving.router import (  # noqa: F401
    ConsistentHashPolicy,
    LeastLoadedPolicy,
    RoutingPolicy,
    ServingRouter,
)
from euler_tpu.serving.runtime import InferenceRuntime  # noqa: F401
from euler_tpu.serving.server import ModelServer  # noqa: F401
