"""Elastic online resharding: live shard split/merge with fenced cutover.

`ReshardCoordinator` grows or shrinks a running cluster P -> P' under
concurrent writer / trainer / serving traffic, as a durable phase
machine (spirit of the elastic-consistent-hashing line of work,
arXiv 2112.01075 — minimize rows moved, never stop the world):

  plan      compute the `id % P` -> `id % P'` row-movement schedule
            over lcm(P, P') residue classes — residues whose shard
            number is unchanged never move.
  copy      bulk-move state via the existing replication snapshot
            payload (`wal_ship want="snapshot"`), re-CRC'd by the
            codec frame on every blob.
  catch_up  tail each source's WAL suffix over `wal_ship` until the
            total lag is under EULER_TPU_RESHARD_LAG bytes.
  cutover   fence every source (term-bumped, durable marker), drain
            the fencing-window tail, replay it, repartition to P',
            boot destination shards at generation G+1 (invisible to
            clients), then atomically publish the new topology through
            the registry — `connect()`'s topology watch re-routes and
            read caches fully flush on the bumped topology epoch.
  abort     any pre-commit failure (or a resumed post-kill coordinator
            that finds the topology unflipped) unfences the sources,
            kills half-born destinations and removes their state:
            zero data loss, the old topology keeps serving.

Every phase transition is appended to a CRC'd JSONL phase log
(`<state>/phases.jsonl`, fsync'd) so a kill -9'd coordinator can be
re-run with `--resume`: if the registry topology already flipped the
reshard rolls forward to done; otherwise it rolls back to aborted —
never a mixed state. The registry `set_topology` rename is the single
commit point.

Destination boot recipe: the post-tail repartitioned arrays are the
pristine base (`part_<d>` tensor dirs + meta at P'), staged-but-
unpublished source records are re-scattered into each destination's
WAL (same batch keys, so post-cutover client retries dedupe), and a
seeded snapshot carries the merged applied-key window with every
publish result sanitized to the full-flush sentinel.

Bit-parity contract: the resharded cluster equals a from-scratch
`build_from_json` at the new shard count over the canonically-ordered
equivalent graph.json — pinned by tests/test_reshard.py through
`cluster_signature` (repartition to one shard + hash, order-free).

The module also carries the minimal load-driven autoscaling policy:
`propose_scaling` turns serving/retrieval `server_stats` and per-shard
store/WAL pressure into typed `Recommendation`s (scale replicas,
split/merge shards); `AutoscaleLoop` polls it on an interval.

CLI:
    python -m euler_tpu.distributed.reshard \
        --registry /path/reg --shards 2 --to 3 --state /path/reshard
    (add --resume after a coordinator crash, --abort to roll back)

Knobs:
    EULER_TPU_RESHARD_LAG            catch-up exit lag, bytes (65536)
    EULER_TPU_RESHARD_CATCHUP_S      catch-up budget, seconds (120)
    EULER_TPU_RESHARD_FENCE_TIMEOUT_S  per-source fence deadline (30)
    EULER_TPU_RESHARD_BOOT_TIMEOUT_S   destination boot deadline (60)
    EULER_TPU_RESHARD_KILL_AT        chaos: SIGKILL self right after
                                     this phase record lands (tests)
    EULER_TPU_RESHARD_SPLIT_WAL_MB   autoscaler split threshold (64)
    EULER_TPU_RESHARD_SPLIT_ROWS     autoscaler split threshold (1e6)
    EULER_TPU_AUTOSCALE_QPS_HIGH     per-replica scale-up qps (100)
    EULER_TPU_AUTOSCALE_QPS_LOW      per-replica scale-down qps (10)
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import hashlib
import json
import math
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import zlib

import numpy as np

from euler_tpu.graph import format as tformat
from euler_tpu.graph import wal as walmod
from euler_tpu.graph.builder import _csr_adjacency
from euler_tpu.distributed.errors import RpcError
from euler_tpu.graph.delta import DeltaStore, _segment_arange
from euler_tpu.graph.meta import DENSE, SPARSE, GraphMeta

# every verb this client surface sends — graftlint's wire-protocol
# checker proves it is a subset of the server's HANDLED_VERBS, and
# tests/test_wire_parity.py pins the runtime twin
WIRE_VERBS = frozenset(
    {
        "fence",
        "get_meta",
        "ping",
        "publish_epoch",
        "stats",
        "unfence",
        "wal_pos",
        "wal_ship",
    }
)


# ---------------------------------------------------------------------------
# movement schedule


def plan_moves(num_shards: int, new_num_shards: int) -> list[dict]:
    """Row-movement schedule for `id % P` -> `id % P'`.

    One entry per residue class modulo lcm(P, P'): ids congruent to
    `residue` live on shard `src` today and `dst` afterwards; `moved`
    is False exactly when the shard number is unchanged, so the
    schedule is movement-minimal for modulo partitioning (only
    residues whose home actually changes ship any bytes)."""
    p, p2 = int(num_shards), int(new_num_shards)
    if p < 1 or p2 < 1:
        raise ValueError(f"shard counts must be >= 1, got {p} -> {p2}")
    lcm = math.lcm(p, p2)
    return [
        {
            "residue": r,
            "src": r % p,
            "dst": r % p2,
            "moved": (r % p) != (r % p2),
        }
        for r in range(lcm)
    ]


# ---------------------------------------------------------------------------
# repartitioning (the bulk data plane, pure numpy, bit-parity with builder)


def _gather_ragged(indptr, values, rows):
    """Gather ragged rows (CSR indptr/values) at `rows`, preserving
    per-row order — the vectorized `np.repeat + segment-arange` idiom
    from graph/delta.py."""
    indptr = np.asarray(indptr, dtype=np.int64)
    rows = np.asarray(rows, dtype=np.int64)
    counts = indptr[rows + 1] - indptr[rows]
    idx = np.repeat(indptr[rows], counts) + _segment_arange(counts)
    new_indptr = np.zeros(len(rows) + 1, dtype=np.int64)
    np.cumsum(counts, out=new_indptr[1:])
    return new_indptr, np.asarray(values)[idx]


def _concat_feature_planes(parts, specs, prefix):
    """Splice per-part feature arrays into global planes keyed by the
    on-disk array base name. Dense -> ("dense", matrix); ragged ->
    (kind, indptr, values) with part offsets folded in."""
    out = {}
    for kind, fid in sorted({(s.kind, s.fid) for s in specs.values()}):
        if kind == DENSE:
            name = f"{prefix}_dense_{fid}"
            out[name] = (
                "dense",
                np.vstack([np.asarray(p[name], dtype=np.float32) for p in parts]),
            )
            continue
        tag = "sparse" if kind == SPARSE else "bin"
        base = f"{prefix}_{tag}_{fid}"
        ips = [np.asarray(p[f"{base}_indptr"], dtype=np.int64) for p in parts]
        vals = [np.asarray(p[f"{base}_values"]) for p in parts]
        offs = np.concatenate([[0], np.cumsum([len(v) for v in vals])])
        indptr = np.concatenate(
            [np.zeros(1, dtype=np.int64)]
            + [ip[1:] + off for ip, off in zip(ips, offs[:-1])]
        )
        out[base] = (kind, indptr, np.concatenate(vals))
    return out


def repartition_arrays(
    meta: GraphMeta, parts: list[dict], new_p: int
) -> tuple[GraphMeta, list[dict]]:
    """Repartition a cluster's array dicts from P = len(parts) shards
    to `new_p`, bit-identical to `build_from_json` at `new_p` over the
    canonically-ordered equivalent graph.json (nodes by id; edges by
    (src, dst, type, weight-bits) — unique (src, dst, type) triples
    make that ordering total).

    Nodes land on `id % new_p`; out-edges on `src % new_p`; in-edges on
    `dst % new_p` (builder partitioning), each dest preserving canonical
    order, so per-dest arrays match the builder's input-order contract.
    Returns (meta_at_new_p, per-dest array dicts); the fresh meta
    recomputes per-dest weight sums with the builder's exact f64
    accumulation order."""
    new_p = int(new_p)
    if new_p < 1:
        raise ValueError(f"new_p must be >= 1, got {new_p}")
    netypes = int(meta.num_edge_types)

    nid = np.concatenate([np.asarray(p["node_ids"], dtype=np.uint64) for p in parts])
    ntt = np.concatenate([np.asarray(p["node_types"], dtype=np.int32) for p in parts])
    nw = np.concatenate(
        [np.asarray(p["node_weights"], dtype=np.float32) for p in parts]
    )
    esrc = np.concatenate([np.asarray(p["edge_src"], dtype=np.uint64) for p in parts])
    edst = np.concatenate([np.asarray(p["edge_dst"], dtype=np.uint64) for p in parts])
    ett = np.concatenate([np.asarray(p["edge_types"], dtype=np.int32) for p in parts])
    ew = np.concatenate(
        [np.asarray(p["edge_weights"], dtype=np.float32) for p in parts]
    )

    node_feats = _concat_feature_planes(parts, meta.node_features, "nf")
    edge_feats = _concat_feature_planes(parts, meta.edge_features, "ef")

    num_labels = len(meta.graph_labels)
    glabel_global = []
    for i in range(num_labels):
        segs = [
            np.asarray(p["glabel_nodes"], dtype=np.uint64)[
                int(p["glabel_indptr"][i]) : int(p["glabel_indptr"][i + 1])
            ]
            for p in parts
        ]
        glabel_global.append(np.concatenate(segs))

    # canonical global edge order: lexsort is last-key-primary, so src
    # is the primary key — partitioned by src this reproduces each
    # dest's builder input order
    wbits = np.ascontiguousarray(ew).view(np.uint32)
    perm = np.lexsort((wbits, ett, edst, esrc))
    esrc_s, edst_s = esrc[perm], edst[perm]
    ett_s, ew_s = ett[perm], ew[perm]

    meta2 = GraphMeta.from_dict(meta.to_dict())
    meta2.num_partitions = new_p
    meta2.node_weight_sums = []
    meta2.edge_weight_sums = []

    n_res = (nid % np.uint64(new_p)).astype(np.int64)
    o_res = (esrc_s % np.uint64(new_p)).astype(np.int64)
    i_res = (edst_s % np.uint64(new_p)).astype(np.int64)
    out_parts = []
    for d in range(new_p):
        rows = np.flatnonzero(n_res == d)
        rows = rows[np.argsort(nid[rows], kind="stable")]
        node_ids_d = nid[rows]
        osel = o_res == d
        out_pos = np.flatnonzero(osel)
        in_pos = np.flatnonzero(i_res == d)
        arrays: dict[str, np.ndarray] = {
            "node_ids": node_ids_d,
            "node_types": ntt[rows],
            "node_weights": nw[rows],
            "edge_src": esrc_s[out_pos],
            "edge_dst": edst_s[out_pos],
            "edge_types": ett_s[out_pos],
            "edge_weights": ew_s[out_pos],
        }
        arrays.update(
            _csr_adjacency(
                node_ids_d,
                esrc_s[out_pos],
                edst_s[out_pos],
                ett_s[out_pos],
                ew_s[out_pos],
                np.arange(len(out_pos), dtype=np.int64),
                netypes,
                "adj",
            )
        )
        # in-edge eidx points at the LOCAL out-edge row when this dest
        # also owns the edge's src half, else -1 (builder contract)
        local_out = np.cumsum(osel) - 1
        in_eidx = np.where(osel[in_pos], local_out[in_pos], -1).astype(np.int64)
        arrays.update(
            _csr_adjacency(
                node_ids_d,
                edst_s[in_pos],
                esrc_s[in_pos],
                ett_s[in_pos],
                ew_s[in_pos],
                in_eidx,
                netypes,
                "inadj",
            )
        )
        for base, plane in node_feats.items():
            if plane[0] == "dense":
                arrays[base] = plane[1][rows]
            else:
                ip, vals = _gather_ragged(plane[1], plane[2], rows)
                arrays[f"{base}_indptr"] = ip
                arrays[f"{base}_values"] = vals
        orig = perm[out_pos]  # feature rows ride with the src-owned half
        for base, plane in edge_feats.items():
            if plane[0] == "dense":
                arrays[base] = plane[1][orig]
            else:
                ip, vals = _gather_ragged(plane[1], plane[2], orig)
                arrays[f"{base}_indptr"] = ip
                arrays[f"{base}_values"] = vals
        gl_indptr = np.zeros(num_labels + 1, dtype=np.int64)
        gl_flat = []
        for i in range(num_labels):
            g = glabel_global[i]
            mine = np.sort(g[(g % np.uint64(new_p)).astype(np.int64) == d])
            gl_flat.append(mine)
            gl_indptr[i + 1] = gl_indptr[i] + len(mine)
        arrays["glabel_indptr"] = gl_indptr
        arrays["glabel_nodes"] = (
            np.concatenate(gl_flat) if gl_flat else np.zeros(0, dtype=np.uint64)
        )

        nw_sum = np.zeros(meta.num_node_types, dtype=np.float64)
        np.add.at(
            nw_sum, arrays["node_types"], arrays["node_weights"].astype(np.float64)
        )
        ew_sum = np.zeros(netypes, dtype=np.float64)
        np.add.at(
            ew_sum, arrays["edge_types"], arrays["edge_weights"].astype(np.float64)
        )
        meta2.node_weight_sums.append(nw_sum.tolist())
        meta2.edge_weight_sums.append(ew_sum.tolist())
        out_parts.append(arrays)
    return meta2, out_parts


def cluster_signature(meta: GraphMeta, parts: list[dict]) -> str:
    """Shard-count-independent content hash: repartition to one shard
    (canonical order) and digest every array's name/dtype/shape/bytes.
    Equal signatures <=> bit-identical logical graphs — the reshard
    correctness oracle."""
    _m1, one = repartition_arrays(meta, parts, 1)
    h = hashlib.sha256()
    for name in sorted(one[0]):
        a = np.ascontiguousarray(one[0][name])
        h.update(name.encode())
        h.update(b"\x00")
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def load_cluster(data_dir: str) -> tuple[GraphMeta, list[dict]]:
    """Read a convert_json-layout dir (euler.meta.json + part_<p>/)
    back into (meta, array dicts) — the handle tests and the bench
    hand to `cluster_signature`."""
    meta = GraphMeta.load(data_dir)
    parts = [
        dict(tformat.read_arrays(os.path.join(data_dir, f"part_{p}"), mmap=False))
        for p in range(meta.num_partitions)
    ]
    return meta, parts


# ---------------------------------------------------------------------------
# durable phase log


class _PhaseLog:
    """Append-only CRC'd JSONL — the coordinator's durable memory.

    Each line is `<json>\\t<crc32 hex>`; append is write+flush+fsync so
    a phase record is on disk before the phase's side effects begin.
    Loading stops at the first torn/corrupt line (a kill mid-append
    loses only that line, mirroring the WAL's torn-tail discipline)."""

    def __init__(self, path: str):
        self.path = path
        self._seq = len(self._repair())

    def _repair(self) -> list[dict]:
        """Load the valid prefix and truncate any torn tail, so a later
        append is never glued onto a half-written line (which would CRC-
        fail the COMBINED line and silently lose the new record)."""
        out = []
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except OSError:
            return out
        valid = 0
        pos = 0
        for line in blob.split(b"\n"):
            end = pos + len(line)
            if line:
                # a line missing its newline is torn even if the CRC
                # happens to pass — append() writes line+\n as one unit
                rec = None
                if end < len(blob):
                    payload, _tab, crc = line.rpartition(b"\t")
                    try:
                        if format(zlib.crc32(payload), "08x").encode() == crc:
                            rec = json.loads(payload)
                    except (ValueError, json.JSONDecodeError):
                        rec = None
                if rec is None:
                    break
                out.append(rec)
                valid = end + 1
            pos = end + 1
        if valid < len(blob):
            with open(self.path, "ab") as f:
                f.truncate(valid)
                f.flush()
                os.fsync(f.fileno())
        return out

    def records(self) -> list[dict]:
        out = []
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except OSError:
            return out
        for line in blob.split(b"\n"):
            if not line:
                continue
            payload, _tab, crc = line.rpartition(b"\t")
            try:
                if format(zlib.crc32(payload), "08x").encode() != crc:
                    break
                out.append(json.loads(payload))
            except (ValueError, json.JSONDecodeError):
                break
        return out

    def append(self, phase: str, **data) -> dict:
        rec = {"seq": self._seq, "phase": phase, **data}
        payload = json.dumps(rec, sort_keys=True)
        line = f"{payload}\t{format(zlib.crc32(payload.encode()), '08x')}\n"
        with open(self.path, "a") as f:
            f.write(line)
            f.flush()
            os.fsync(f.fileno())
        self._seq += 1
        return rec


# ---------------------------------------------------------------------------
# coordinator


def _env_f(name: str, default: str) -> float:
    return float(os.environ.get(name, default))


class ReshardCoordinator:
    """Drive one live reshard P -> P' to completion (or clean abort).

    `registry` must be a shared-dir registry spec (the coordinator
    passes it to destination shard subprocesses and reads gen'd
    heartbeats back). Sources must be solo durable shards (wal_dir'd;
    replica-group reshard is ROADMAP future work — the fence verb only
    reaches the receiving primary)."""

    def __init__(
        self,
        registry: str,
        num_shards: int,
        new_num_shards: int,
        state_dir: str,
        host: str = "127.0.0.1",
        env: dict | None = None,
    ):
        from euler_tpu.distributed.rendezvous import make_registry

        if not isinstance(registry, str):
            raise TypeError("registry must be a spec string (shared dir)")
        self.registry_spec = registry
        self.registry = make_registry(registry)
        if not hasattr(self.registry, "members"):
            raise RuntimeError(
                "reshard needs a shared-dir registry (members/meta reads)"
            )
        self.num_shards = int(num_shards)
        self.new_num_shards = int(new_num_shards)
        if self.new_num_shards < 1 or self.new_num_shards == self.num_shards:
            raise ValueError(
                f"bad shard counts {self.num_shards} -> {self.new_num_shards}"
            )
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self.host = host
        self.env = dict(env or {})
        self.log = _PhaseLog(os.path.join(state_dir, "phases.jsonl"))
        # adopt the logged generation on resume so a re-run coordinator
        # agrees with its dead predecessor about the commit point
        plan_rec = next(
            (r for r in self.log.records() if r["phase"] == "plan"), None
        )
        if plan_rec is not None:
            if (
                int(plan_rec["P"]) != self.num_shards
                or int(plan_rec["P2"]) != self.new_num_shards
            ):
                raise RuntimeError(
                    f"state dir belongs to a {plan_rec['P']}->"
                    f"{plan_rec['P2']} reshard, not "
                    f"{self.num_shards}->{self.new_num_shards}"
                )
            self.gen = int(plan_rec["gen"])
            self.gen2 = int(plan_rec["gen2"])
            self.src_topology_epoch = int(plan_rec.get("topology_epoch", 0))
        else:
            topo = self.registry.topology()
            self.gen = int(topo["gen"]) if topo else 0
            self.gen2 = self.gen + 1
            self.src_topology_epoch = 0
        self.token = f"reshard-g{self.gen2}"
        self.dest_root = os.path.join(state_dir, f"gen_{self.gen2}")
        self.meta: GraphMeta | None = None
        self.report: dict = {"token": self.token, "gen2": self.gen2}
        self._src_handles = None
        self._state: list[dict] = []
        self._dest_procs: list = []

    # -- plumbing ---------------------------------------------------------

    def _sources(self):
        from euler_tpu.distributed.client import RemoteShard

        if self._src_handles is None:
            table = self.registry.wait_for(self.num_shards, timeout=30.0)
            self._src_handles = [
                RemoteShard(s, table[s]) for s in range(self.num_shards)
            ]
        return self._src_handles

    def _checkpoint(self, phase: str, **data):
        """Durable phase record + the chaos injection point: with
        EULER_TPU_RESHARD_KILL_AT=<phase> the process SIGKILLs itself
        the instant the record is on disk — tests drive every
        phase-boundary crash deterministically through it."""
        self.log.append(phase, **data)
        print(f"reshard {self.token}: phase {phase}", flush=True)
        if os.environ.get("EULER_TPU_RESHARD_KILL_AT") == phase:
            os.kill(os.getpid(), signal.SIGKILL)

    # -- snapshot / tail transport ---------------------------------------

    def _fetch_snapshot(self, sh) -> dict:
        """Pull one source's publish-consistent snapshot over the
        replication bootstrap payload (v2 codec-aware or legacy)."""
        from euler_tpu.distributed import codec

        reply = sh.call(
            "wal_ship",
            [0, 0, None, "snapshot", None, None, None, codec.wire_codec()],
            deadline_s=_env_f("EULER_TPU_RESHARD_FENCE_TIMEOUT_S", "30") * 4,
        )
        term, epoch, wal_pos = int(reply[0]), int(reply[1]), int(reply[2])
        head = json.loads(reply[4])
        if isinstance(head, dict):
            use = str(head["codec"])
            applied = walmod._applied_from_blob(
                codec.decompress(use, bytes(np.ascontiguousarray(reply[3])))
            )
            arrays = {}
            for n, dt, shape, blob in zip(
                head["names"], head["dtypes"], head["shapes"], reply[5:]
            ):
                raw = codec.decompress(use, bytes(np.ascontiguousarray(blob)))
                arrays[n] = (
                    np.frombuffer(raw, np.dtype(dt)).reshape(shape).copy()
                )
        else:
            applied = walmod._applied_from_blob(
                bytes(np.ascontiguousarray(reply[3]))
            )
            arrays = {n: np.array(a, copy=True) for n, a in zip(head, reply[5:])}
        return {
            "term": term,
            "epoch": epoch,
            "pos": wal_pos,
            "applied": applied,
            "arrays": arrays,
        }

    def _copy_source(self, s: int):
        """(Re)copy one source: force a publish-consistent snapshot
        state, then pull it. Also the need_snapshot recovery path when
        the WAL prefix gets trimmed under a tail fetch."""
        sh = self._sources()[s]
        st = self._state[s] if s < len(self._state) else None
        n = 0 if st is None else st.get("copies", 0)
        # an EMPTY publish still captures a publish-consistent snapshot
        # state server-side, so want="snapshot" always has one to ship
        sh.call("publish_epoch", [f"{self.token}:pre:{s}:{n}"])
        snap = self._fetch_snapshot(sh)
        snap.update(fetched=snap["pos"], buf=bytearray(), copies=n + 1)
        if st is None:
            self._state.append(snap)
        else:
            self._state[s] = snap

    def _fetch_tail(self, s: int, upto: int):
        """Append the source's raw WAL records in [fetched, upto) to
        its buffer. Positions are logical offsets; `read_raw` always
        ships the first record whole so progress is guaranteed."""
        from euler_tpu.distributed import codec

        offer = codec.wire_codec()
        sh = self._sources()[s]
        while self._state[s]["fetched"] < upto:
            st = self._state[s]
            reply = sh.call(
                "wal_ship",
                [st["fetched"], 1 << 20, None, "log", None, None, None,
                 offer, st["fetched"]],
            )
            if bool(reply[3]):  # need_snapshot: prefix trimmed under us
                self._copy_source(s)
                continue
            raw = (
                bytes(np.ascontiguousarray(reply[1])) if len(reply[1]) else b""
            )
            blob = (
                codec.decompress(str(reply[4]), raw)
                if (len(reply) >= 6 and raw)
                else raw
            )
            if not blob:
                break
            st["buf"] += blob
            st["fetched"] = int(reply[2])

    # -- phases -----------------------------------------------------------

    def _phase_plan(self):
        srcs = self._sources()
        self.meta = GraphMeta.from_dict(json.loads(srcs[0].call("get_meta", [])[0]))
        if int(self.meta.num_partitions) != self.num_shards:
            raise RuntimeError(
                f"cluster is {self.meta.num_partitions}-way, coordinator"
                f" was told {self.num_shards}"
            )
        stats = [json.loads(sh.call("stats", [])[0]) for sh in srcs]
        self.src_topology_epoch = max(
            int(s.get("topology_epoch", 0)) for s in stats
        )
        moves = plan_moves(self.num_shards, self.new_num_shards)
        moved = sum(1 for m in moves if m["moved"])
        self.report["plan"] = {
            "residues": len(moves),
            "moved_residues": moved,
            "moved_fraction": moved / len(moves),
        }
        self._checkpoint(
            "plan",
            P=self.num_shards,
            P2=self.new_num_shards,
            gen=self.gen,
            gen2=self.gen2,
            residues=len(moves),
            moved_residues=moved,
            topology_epoch=self.src_topology_epoch,
        )

    def _phase_copy(self):
        t0 = time.perf_counter()
        self._state = []
        for s in range(self.num_shards):
            self._copy_source(s)
        self.report["copy_s"] = round(time.perf_counter() - t0, 3)
        self._checkpoint(
            "copy",
            positions=[int(st["pos"]) for st in self._state],
            epochs=[int(st["epoch"]) for st in self._state],
        )

    def _phase_catch_up(self):
        t0 = time.perf_counter()
        lag_max = int(float(os.environ.get("EULER_TPU_RESHARD_LAG", "65536")))
        budget = _env_f("EULER_TPU_RESHARD_CATCHUP_S", "120")
        srcs = self._sources()
        while True:
            total = 0
            for s, sh in enumerate(srcs):
                end = int(sh.call("wal_pos", [])[2])
                if end > self._state[s]["fetched"]:
                    self._fetch_tail(s, end)
                total += max(0, end - self._state[s]["fetched"])
            if total <= lag_max:
                break
            if time.perf_counter() - t0 > budget:
                raise RuntimeError(
                    f"catch_up lag {total}B still above {lag_max}B after"
                    f" {budget}s — writers outrun the tail fetch"
                )
        self.report["catch_up_s"] = round(time.perf_counter() - t0, 3)
        self._checkpoint("catch_up", lag=int(total))

    def _replay_source(self, s: int) -> dict:
        """Replay one source's shipped WAL suffix onto its snapshot
        arrays — the exact `wal.recover` loop (staged keys land in the
        applied window, publish records merge per round, records after
        the last publish stay pending)."""
        from euler_tpu.graph.store import GraphStore

        st = self._state[s]
        store = GraphStore(self.meta, dict(st["arrays"]), s)
        store.graph_epoch = int(st["epoch"])
        recs, valid_end = walmod.parse_records(bytes(st["buf"]), st["pos"])
        if valid_end != st["fetched"]:
            raise RuntimeError(
                f"source {s}: shipped tail torn at {valid_end}, expected"
                f" {st['fetched']}"
            )
        applied = collections.OrderedDict(st["applied"])
        delta = None
        pending: list[tuple[str, list]] = []
        for op, a, _end, _term in recs:
            if op == "publish_epoch":
                key = a[0] if a else None
                if key is not None and f"pub:{key}" in applied:
                    continue
                d, delta = delta, None
                pending = []
                if d is None or d.empty:
                    result = (
                        int(store.graph_epoch),
                        np.empty(0, np.int64),
                        np.empty(0, np.uint64),
                        int(store.num_nodes),
                    )
                else:
                    store, rows, ids = store.merge_delta(d)
                    result = (
                        int(store.graph_epoch),
                        rows,
                        ids,
                        int(store.num_nodes),
                    )
                if key is not None:
                    applied[f"pub:{key}"] = result
            else:
                key = str(a[0])
                if key in applied:
                    continue
                if delta is None:
                    delta = DeltaStore(
                        s, self.meta.num_partitions, max_rows=2**62
                    )
                walmod.stage_record(delta, op, a)
                applied[key] = True
                pending.append((op, a))
        return {
            "arrays": store.arrays,
            "epoch": int(store.graph_epoch),
            "applied": applied,
            "pending": pending,
        }

    def _seed_dest_wal(self, d, arrays_d, replayed, epoch):
        """Build destination d's WAL dir: re-scattered pending records
        (same batch keys -> post-cutover client retries dedupe) plus a
        seeded snapshot carrying the merged applied window with every
        publish result sanitized to the full-flush sentinel."""
        from euler_tpu.distributed.writer import GraphWriter

        wal_dir = os.path.join(self.dest_root, f"wal_{d}")
        os.makedirs(wal_dir, exist_ok=True)
        wal = walmod.WriteAheadLog(os.path.join(wal_dir, walmod.WAL_FILE))
        pending_keys = set()
        for r in replayed:
            for op, a in r["pending"]:
                pending_keys.add(str(a[0]))
                for dest, sub in GraphWriter._resplit(
                    op, list(a[1:]), self.new_num_shards
                ):
                    if dest == d:
                        wal.append(op, [a[0]] + list(sub))
        # merged applied window: batch keys are unique to one source so
        # the union is well defined; pending keys are EXCLUDED — their
        # WAL records re-add them during destination recovery (seeding
        # them here would make recovery skip the re-staged rows)
        applied_d: collections.OrderedDict = collections.OrderedDict()
        dest_n = int(len(arrays_d["node_ids"]))
        for r in replayed:
            for k, v in r["applied"].items():
                if k in pending_keys:
                    continue
                if k.startswith("pub:"):
                    ep = int(v[0]) if isinstance(v, tuple) else int(epoch)
                    # rows/ids None = the client's full-flush sentinel —
                    # source row numbering is meaningless at P'
                    applied_d[k] = (ep, None, None, dest_n)
                else:
                    applied_d[k] = True
        muts = [k for k in applied_d if not k.startswith("pub:")]
        for k in muts[: max(0, len(muts) - 4096)]:
            del applied_d[k]
        walmod.write_snapshot(wal_dir, int(epoch), arrays_d, applied_d, 0)

    def _spawn_dests(self, data_dir: str) -> list[int]:
        env = dict(os.environ)
        env.update(self.env)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("EULER_TPU_RESHARD_KILL_AT", None)  # chaos targets US
        for d in range(self.new_num_shards):
            cmd = [
                sys.executable, "-m", "euler_tpu.distributed.service",
                "--data", data_dir,
                "--shard", str(d),
                "--host", self.host,
                "--port", "0",
                "--registry", self.registry_spec,
                "--wal-dir", os.path.join(self.dest_root, f"wal_{d}"),
                "--no-native",
                "--generation", str(self.gen2),
                "--topology-epoch", str(self.src_topology_epoch + 1),
            ]
            logf = open(os.path.join(self.dest_root, f"dest_{d}.log"), "ab")
            self._dest_procs.append(
                subprocess.Popen(
                    cmd, env=env, stdout=logf, stderr=logf,
                    start_new_session=True,
                )
            )
            logf.close()
        return [p.pid for p in self._dest_procs]

    def _await_dests(self, epoch: int) -> dict:
        from euler_tpu.distributed.client import RemoteShard

        deadline = time.monotonic() + _env_f(
            "EULER_TPU_RESHARD_BOOT_TIMEOUT_S", "60"
        )
        table = {}
        for d in range(self.new_num_shards):
            while True:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"destination shard {d} (gen {self.gen2}) did not"
                        " become ready"
                    )
                # an aborted earlier attempt at this SAME generation can
                # leave stale heartbeats from its kill -9'd destinations
                # (dead processes never deregister) — probe every
                # gen-matching candidate and let the live one win
                hit = None
                for h, p, m in self.registry.members(d):
                    if int((m or {}).get("gen", 0)) != self.gen2:
                        continue
                    addr = (h, p)
                    try:
                        sh = RemoteShard(d, [addr])
                        sh.call("ping", [])
                        got = int(sh.call("wal_pos", [])[3])
                    except (OSError, ConnectionError, RpcError):
                        continue
                    if got == int(epoch):
                        hit = addr
                        break
                    raise RuntimeError(
                        f"destination {d} booted at epoch {got},"
                        f" expected {epoch}"
                    )
                if hit is not None:
                    table[d] = f"{hit[0]}:{hit[1]}"
                    break
                time.sleep(0.05)
        return table

    def _phase_cutover(self):
        srcs = self._sources()
        # durable intent BEFORE the first fence lands: a coordinator
        # killed past this point knows (on resume) it may have fenced
        # sources and must either roll forward or unfence them
        self._checkpoint("fence_begin", token=self.token)
        t0 = time.perf_counter()
        fence_to = _env_f("EULER_TPU_RESHARD_FENCE_TIMEOUT_S", "30")
        ends = []
        for sh in srcs:
            reply = sh.call(
                "fence", [self.token, self.gen2], deadline_s=fence_to
            )
            ends.append(int(reply[1]))
        # distinct kill point: every source IS fenced now, so an abort
        # from any later phase owes each of them an unfence
        self._checkpoint("fenced", ends=ends)
        # the fence reply's wal_end is final (the flag is checked before
        # staging and the fence serializes behind in-flight stages), so
        # one drain to wal_end captures the whole fencing-window tail
        for s in range(self.num_shards):
            self._fetch_tail(s, ends[s])
            if self._state[s]["fetched"] != ends[s]:
                raise RuntimeError(
                    f"source {s}: tail drain stalled at"
                    f" {self._state[s]['fetched']} < {ends[s]}"
                )
        replayed = [self._replay_source(s) for s in range(self.num_shards)]
        epoch = max(r["epoch"] for r in replayed)
        all_nid = np.concatenate(
            [np.asarray(r["arrays"]["node_ids"], np.uint64) for r in replayed]
        )
        self.report["rows_moved"] = int(
            np.count_nonzero(
                (all_nid % np.uint64(self.num_shards))
                != (all_nid % np.uint64(self.new_num_shards))
            )
        )
        meta2, parts2 = repartition_arrays(
            self.meta, [r["arrays"] for r in replayed], self.new_num_shards
        )
        data_dir = os.path.join(self.dest_root, "data")
        os.makedirs(data_dir, exist_ok=True)
        for d in range(self.new_num_shards):
            tformat.write_arrays(
                os.path.join(data_dir, f"part_{d}"), parts2[d], fsync=True
            )
        meta2.save(data_dir)
        for d in range(self.new_num_shards):
            self._seed_dest_wal(d, parts2[d], replayed, epoch)
        pids = self._spawn_dests(data_dir)
        self._checkpoint("dests_spawned", pids=pids, data_dir=data_dir)
        self.report["dests"] = self._await_dests(epoch)
        # THE commit point: one atomic rename in the registry flips
        # every connect()'s topology watch to the new generation
        self.registry.set_topology(self.new_num_shards, self.gen2, int(epoch))
        unavail_ms = round((time.perf_counter() - t0) * 1e3, 3)
        self.report.update(
            epoch=int(epoch), cutover_ms=unavail_ms, unavail_ms=unavail_ms
        )
        self._checkpoint(
            "committed", gen2=self.gen2, epoch=int(epoch), cutover_ms=unavail_ms
        )
        # sources stay fenced (durable marker) and gen-invisible; the
        # operator retires them once the new generation is warm

    # -- lifecycle --------------------------------------------------------

    def run(self, resume: bool = False) -> dict:
        recs = self.log.records()
        if recs:
            last = recs[-1]["phase"]
            if last in ("done", "aborted"):
                self.report["outcome"] = last
                return self.report
            if not resume:
                raise RuntimeError(
                    f"{self.state_dir}: unfinished reshard (last phase"
                    f" {last!r}) — rerun with resume=True (CLI --resume)"
                    " or abort"
                )
            return self._resume(recs)
        try:
            self._phase_plan()
            self._phase_copy()
            self._phase_catch_up()
            self._phase_cutover()
        except BaseException:
            self._abort("phase failure")
            raise
        self._checkpoint("done")
        self.report["outcome"] = "done"
        return self.report

    def _resume(self, recs: list[dict]) -> dict:
        """Post-kill recovery: the registry topology flip is the commit
        point — at or past it, roll forward; before it, roll back."""
        committed = any(r["phase"] == "committed" for r in recs)
        topo = self.registry.topology()
        if committed or (topo is not None and int(topo.get("gen", 0)) >= self.gen2):
            self._checkpoint("done", note="resume roll-forward")
            self.report["outcome"] = "done"
            return self.report
        self._abort("resume pre-commit roll-back")
        return self.report

    def abort(self) -> dict:
        recs = self.log.records()
        if recs and recs[-1]["phase"] in ("done", "aborted"):
            self.report["outcome"] = recs[-1]["phase"]
            return self.report
        self._abort("operator abort")
        return self.report

    def _abort(self, reason: str):
        """Roll back with zero data loss: kill half-born destinations,
        unfence every source (writes resume on the OLD topology),
        remove destination state, persist the terminal record."""
        recs = self.log.records()
        pids = [
            pid for r in recs if r["phase"] == "dests_spawned"
            for pid in r.get("pids", [])
        ]
        pids += [p.pid for p in self._dest_procs]
        for pid in set(pids):
            try:
                os.kill(int(pid), signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass
        for p in self._dest_procs:
            try:
                p.wait(timeout=5)
            except Exception:
                pass
        fenced = any(r["phase"] == "fence_begin" for r in recs)
        if fenced:
            try:
                for sh in self._sources():
                    try:
                        sh.call("unfence", [self.token])
                    except (OSError, ConnectionError):
                        # source mid-respawn: its durable fence marker
                        # names OUR token; retry once it heartbeats back
                        time.sleep(0.5)
                        sh.call("unfence", [self.token])
            except Exception:
                self.log.append("abort_unfence_failed", reason=reason)
                raise
        shutil.rmtree(self.dest_root, ignore_errors=True)
        self._checkpoint("aborted", reason=reason)
        self.report["outcome"] = "aborted"


# ---------------------------------------------------------------------------
# load-driven autoscaling policy


@dataclasses.dataclass(frozen=True)
class Recommendation:
    """One typed scaling action.

    kind: scale_serving_replicas | scale_retrieval_replicas |
          split_shard | merge_shards
    target: proposed replica count (scale_*) or shard count (split/merge)
    """

    kind: str
    target: int
    reason: str
    metrics: dict = dataclasses.field(default_factory=dict)


def _fleet_pressure(fleet: dict) -> tuple[int, float, float]:
    """(live_replicas, avg_qps_per_replica, overload_rejects) from a
    `fleet_stats()`-shaped dict (addr -> server_stats json; entries
    with an "error" key are unreachable)."""
    live = [s for s in fleet.values() if isinstance(s, dict) and "error" not in s]
    qps = []
    rejects = 0.0
    for s in live:
        b = s.get("batcher", s)
        up = float(s.get("uptime_s", 0.0)) or 1.0
        qps.append(float(b.get("requests", 0)) / up)
        rejects += float(b.get("rejected_overload", 0))
    avg = sum(qps) / len(qps) if qps else 0.0
    return len(live), avg, rejects


def _scale_fleet(kind: str, fleet: dict, high: float, low: float):
    n, avg, rejects = _fleet_pressure(fleet)
    if n == 0:
        return None
    if rejects > 0 or avg > high:
        return Recommendation(
            kind,
            n + 1,
            f"{'overload rejects' if rejects > 0 else 'qps'} above budget"
            f" ({avg:.1f} qps/replica, {int(rejects)} rejects)",
            {"replicas": n, "qps_per_replica": avg, "rejected_overload": rejects},
        )
    if avg < low and n > 1:
        return Recommendation(
            kind,
            n - 1,
            f"idle fleet ({avg:.1f} qps/replica < {low})",
            {"replicas": n, "qps_per_replica": avg},
        )
    return None


def propose_scaling(
    serving: dict | None = None,
    retrieval: dict | None = None,
    shards: dict | None = None,
    num_shards: int | None = None,
) -> list[Recommendation]:
    """Pure policy: stats in, typed `Recommendation`s out (no side
    effects — the operator or a supervisor loop acts on them).

    serving / retrieval: `fleet_stats()`-shaped dicts.
    shards: shard -> {"wal_bytes": .., "num_nodes": ..} store/WAL
    pressure (e.g. from `server_stats`'s "graph_shards" block).
    """
    high = _env_f("EULER_TPU_AUTOSCALE_QPS_HIGH", "100")
    low = _env_f("EULER_TPU_AUTOSCALE_QPS_LOW", "10")
    split_wal = _env_f("EULER_TPU_RESHARD_SPLIT_WAL_MB", "64") * (1 << 20)
    split_rows = _env_f("EULER_TPU_RESHARD_SPLIT_ROWS", "1000000")
    out: list[Recommendation] = []
    if serving:
        rec = _scale_fleet("scale_serving_replicas", serving, high, low)
        if rec:
            out.append(rec)
    if retrieval:
        rec = _scale_fleet("scale_retrieval_replicas", retrieval, high, low)
        if rec:
            out.append(rec)
    if shards:
        p = int(num_shards if num_shards is not None else len(shards))
        hot = []
        for sid, st in sorted(shards.items()):
            wal_b = float(st.get("wal_bytes", 0) or 0)
            rows = float(st.get("num_nodes", 0) or 0)
            if wal_b > split_wal or rows > split_rows:
                hot.append((sid, wal_b, rows))
        if hot:
            sid, wal_b, rows = hot[0]
            out.append(
                Recommendation(
                    "split_shard",
                    p + 1,
                    f"shard {sid} over pressure threshold"
                    f" (wal {int(wal_b)}B, {int(rows)} rows)",
                    {"shard": sid, "wal_bytes": wal_b, "num_nodes": rows,
                     "hot_shards": [h[0] for h in hot]},
                )
            )
        elif p > 1 and all(
            float(st.get("wal_bytes", 0) or 0) < split_wal / 4
            and float(st.get("num_nodes", 0) or 0) < split_rows / 4
            for st in shards.values()
        ):
            out.append(
                Recommendation(
                    "merge_shards",
                    p - 1,
                    f"all {p} shards under a quarter of the split"
                    " thresholds",
                    {"num_shards": p},
                )
            )
    return out


class AutoscaleLoop:
    """Poll a stats source and hand `Recommendation`s to a callback.

    `stats_fn` returns the `propose_scaling` kwargs (serving=...,
    retrieval=..., shards=..., num_shards=...); `on_recommend` receives
    each non-empty recommendation list. Polling faults are swallowed —
    an unreachable fleet must not kill the policy loop."""

    def __init__(self, stats_fn, on_recommend, interval_s: float | None = None):
        self.stats_fn = stats_fn
        self.on_recommend = on_recommend
        self.interval_s = (
            _env_f("EULER_TPU_AUTOSCALE_INTERVAL_S", "10")
            if interval_s is None
            else float(interval_s)
        )
        self.ticks = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick(self) -> list[Recommendation]:
        try:
            recs = propose_scaling(**(self.stats_fn() or {}))
        except (OSError, ConnectionError, ValueError, KeyError):
            return []
        self.ticks += 1
        if recs:
            self.on_recommend(recs)
        return recs

    def _run(self):
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.interval_s)

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="euler-autoscale", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--registry", required=True)
    ap.add_argument("--shards", type=int, required=True,
                    help="current shard count P")
    ap.add_argument("--to", type=int, required=True,
                    help="target shard count P'")
    ap.add_argument("--state", required=True,
                    help="coordinator state dir (phase log + dest state)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--resume", action="store_true",
                    help="recover a killed coordinator: roll forward if"
                         " the topology flipped, else roll back")
    ap.add_argument("--abort", action="store_true",
                    help="roll back an unfinished reshard")
    args = ap.parse_args(argv)
    co = ReshardCoordinator(
        args.registry, args.shards, args.to, args.state, host=args.host
    )
    if args.abort:
        report = co.abort()
    else:
        report = co.run(resume=args.resume)
    print(json.dumps(report, sort_keys=True, default=str), flush=True)
    return 0 if report.get("outcome") in ("done", "aborted") else 1


if __name__ == "__main__":
    sys.exit(main())
