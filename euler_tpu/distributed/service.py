"""Graph shard server — the reference's GrpcServer/GrpcWorker role
(euler/service/grpc_server.h:38-80, grpc_worker.cc:40-96): load one shard,
serve batch queries over threaded TCP, heartbeat into the registry.

Start programmatically (`GraphService(...).start()`) or as a process:
    python -m euler_tpu.distributed.service --data DIR --shard 0 \
        --num-shards 2 --port 9190 --registry /path/reg
(euler.start() parity, euler/python/start_service.py:70-80).
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import queue
import selectors
import socket
import struct
import threading
import time

import numpy as np

from euler_tpu.distributed import chaos, wire
from euler_tpu.distributed.errors import (
    NotPrimaryError,
    ReshardFencedError,
    RpcError,
)
from euler_tpu.distributed.registry import Registry
from euler_tpu.distributed.rendezvous import make_registry
from euler_tpu.graph import format as tformat
from euler_tpu.graph.meta import GraphMeta
from euler_tpu.graph.store import GraphStore


def _rng_from(seed) -> np.random.Generator:
    return np.random.default_rng(seed if seed is not None else None)


# per-request context (worker-thread confined): the absolute monotonic
# deadline unwrapped from the wire envelope, readable by services whose
# dispatch wants it (ModelServer derives the batcher deadline from it)
_REQUEST = threading.local()


def current_deadline() -> float | None:
    """Absolute time.monotonic() deadline of the request this worker is
    dispatching, or None when the client sent no budget."""
    return getattr(_REQUEST, "deadline", None)


class _PoolServer:
    """Bounded worker-pool TCP server (the reference serves with a fixed
    set of completion-queue threads, grpc_worker_service.cc:48-96, not a
    thread per connection).

    One selector thread watches every idle connection; when a connection
    turns readable it is handed to the pool, where a worker runs the full
    request cycle — blocking frame read, dispatch (the native engine
    releases the GIL inside its C++ calls), wire encode (no shared lock) —
    then parks the connection back on the selector. The protocol is
    request/response lockstep per connection, so a connection is owned by
    at most one worker at a time and thread count stays constant no matter
    how many clients connect.

    Fan-out ops (a coordinator issues blocking leaf RPCs to peer shards)
    run on a SEPARATE coordinator pool: if they shared the main pool, two
    mutually-dependent servers could each fill every worker with blocked
    coordinators, leaving no worker to serve the peer's leaf sub-requests
    — a distributed deadlock. Leaf ops touch only the local store, so the
    main pool always drains.
    """

    def __init__(self, addr, service, workers: int | None = None):
        self.service = service
        self.lsock = socket.create_server(addr, backlog=128)
        self.lsock.setblocking(False)
        self.server_address = self.lsock.getsockname()
        self.num_workers = workers or min(
            32, max(2, (os.cpu_count() or 1) * 2)
        )
        self._sel = selectors.DefaultSelector()
        self._jobs: queue.SimpleQueue = queue.SimpleQueue()
        self._coord_jobs: queue.SimpleQueue = queue.SimpleQueue()
        self._park: queue.SimpleQueue = queue.SimpleQueue()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # no coordinator threads on servers that can never fan out
        # (single-partition serving, the common case)
        self.num_coordinators = (
            max(2, self.num_workers // 2)
            if getattr(service, "may_coordinate", True)
            else 0
        )
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        # drain support: requests currently queued or executing; guarded
        # by the condition so drain() can wait for quiescence
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._accepting = True

    def start(self):
        self._sel.register(self.lsock, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()
        self._threads.append(t)
        for _ in range(self.num_workers):
            w = threading.Thread(target=self._worker, daemon=True)
            w.start()
            self._threads.append(w)
        for _ in range(self.num_coordinators):
            c = threading.Thread(target=self._coordinator, daemon=True)
            c.start()
            self._threads.append(c)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful quiesce: stop accepting NEW connections, then wait for
        every queued/executing request to finish (requests already in the
        pipe on parked connections still get answers). True when the
        server went quiet, False on timeout — callers proceed to a hard
        shutdown either way."""
        self._accepting = False
        deadline = time.monotonic() + timeout_s
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cv.wait(remaining)
        return True

    def _inflight_inc(self):
        with self._inflight_cv:
            self._inflight += 1

    def _inflight_dec(self):
        with self._inflight_cv:
            self._inflight -= 1
            self._inflight_cv.notify_all()

    def shutdown(self):
        self._stop.set()
        self._wake_w.send(b"x")  # unblock the selector
        for _ in range(self.num_workers):
            self._jobs.put(None)  # unblock workers
        for _ in range(self.num_coordinators):
            self._coord_jobs.put(None)

    def server_close(self):
        self.lsock.close()
        self._wake_r.close()
        self._wake_w.close()
        # close every live connection: a worker blocked in read_frame on an
        # idle-but-open client socket only returns when the peer hangs up,
        # so without this the shutdown sentinels are never consumed and
        # connection sockets leak until process exit
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _close_conn(self, conn):
        with self._conns_lock:
            self._conns.discard(conn)
        try:
            conn.close()
        except OSError:
            pass

    # -- selector thread ---------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            for key, _ in self._sel.select(timeout=0.5):
                if key.data == "accept":
                    try:
                        conn, _ = self.lsock.accept()
                    except OSError:
                        continue
                    if not self._accepting:
                        # draining: refuse new connections immediately so
                        # clients fail over instead of queueing behind a
                        # server that is on its way out
                        try:
                            conn.close()
                        except OSError:
                            pass
                        continue
                    conn.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    conn.setblocking(True)
                    with self._conns_lock:
                        self._conns.add(conn)
                    self._sel.register(conn, selectors.EVENT_READ, "conn")
                elif key.data == "wake":
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                    while True:  # re-register connections workers parked
                        try:
                            conn = self._park.get_nowait()
                        except queue.Empty:
                            break
                        try:
                            self._sel.register(
                                conn, selectors.EVENT_READ, "conn"
                            )
                        except (OSError, ValueError):
                            self._close_conn(conn)
                else:  # a parked connection has a request pending
                    self._sel.unregister(key.fileobj)
                    self._inflight_inc()
                    self._jobs.put(key.fileobj)

    # -- worker threads ------------------------------------------------------

    def _worker(self):
        while True:
            conn = self._jobs.get()
            if conn is None:
                return
            try:
                disposition = self._serve_one(conn)
            except Exception:
                # a malformed frame must cost the CONNECTION, not the
                # worker — a dead worker would silently shrink the pool
                disposition = "close"
            self._finish(conn, disposition)

    def _coordinator(self):
        while True:
            job = self._coord_jobs.get()
            if job is None:
                return
            conn, op, args, deadline = job
            try:
                disposition = self._respond(conn, op, args, deadline)
            except Exception:
                disposition = "close"
            self._finish(conn, disposition)

    def _finish(self, conn, disposition: str):
        if disposition == "park":
            self._inflight_dec()
            self._park.put(conn)
            try:
                self._wake_w.send(b"x")
            except OSError:
                pass
        elif disposition == "close":
            self._inflight_dec()
            self._close_conn(conn)
        # "detached": the coordinator pool owns the connection (and the
        # in-flight count) now

    def _serve_one(self, sock: socket.socket) -> str:
        try:
            payload = wire.read_frame(sock)
        except (ConnectionError, OSError):
            return "close"
        if payload is None:
            return "close"
        op, args = wire.decode(payload)
        # deadline envelope: the client shipped its REMAINING budget in
        # relative ms (clocks are never compared); anchor it here, at
        # frame receipt, so queueing delay inside this server counts
        op, budget_ms = wire.unwrap_deadline(op)
        counters = getattr(self.service, "wire_bytes_in", None)
        if counters is not None:
            counters[op] += 4 + len(payload)
        deadline = (
            time.monotonic() + budget_ms / 1e3
            if budget_ms is not None
            else None
        )
        if self.service.is_coordinator(op):
            self._coord_jobs.put((sock, op, args, deadline))
            return "detached"
        return self._respond(sock, op, args, deadline)

    def _respond(self, sock: socket.socket, op, args, deadline=None) -> str:
        # already-expired work is rejected with a typed err frame BEFORE
        # dispatch: the client gave up waiting, so the answer would only
        # burn a worker the live requests need
        if deadline is not None and time.monotonic() > deadline:
            return self._send(
                sock,
                wire.encode(
                    "err",
                    [f"DeadlineExceeded: {op!r} expired before dispatch"],
                ),
            )
        plan = chaos.active_plan()
        corrupt = truncate = False
        if plan is not None:
            decisions = plan.decisions(
                "server", op, shard=getattr(self.service, "shard", None)
            )
            for d in decisions:
                if d.kind == "delay":
                    time.sleep(d.delay_s)
                elif d.kind == "err":
                    return self._send(sock, wire.encode("err", [d.message]))
                elif d.kind == "eof":
                    return "close"
                elif d.kind == "reset":
                    self._rst(sock)
                    return "close"
                elif d.kind == "blackhole":
                    time.sleep(d.hold_s)
                    return "close"
                elif d.kind == "corrupt":
                    corrupt = True
                elif d.kind == "truncate":
                    truncate = True
        _REQUEST.deadline = deadline
        try:
            result = self.service.dispatch(op, args)
            # vectored response: big result arrays leave as iovecs
            # straight from the store's buffers, never staged into a
            # flat frame copy
            frame = wire.encode_vectored("ok", result)
        except Exception as e:  # report (typed by class name), keep serving
            frame = wire.encode("err", [f"{type(e).__name__}: {e}"])
        finally:
            _REQUEST.deadline = None
        if truncate or corrupt:
            # chaos paths need a flat mutable frame to tear/flip
            flat = bytearray().join(
                frame if isinstance(frame, list) else [frame]
            )
            if truncate:
                # torn frame: correct length prefix, then the stream dies
                try:
                    sock.sendall(flat[: max(5, len(flat) // 2)])
                except (ConnectionError, OSError):
                    pass
                return "close"
            for i in range(4, len(flat), max(1, len(flat) // 8)):
                flat[i] ^= 0xFF
            frame = flat
        counters = getattr(self.service, "wire_bytes_out", None)
        if counters is not None:
            counters[op] += wire.frame_nbytes(frame)
        return self._send(sock, frame)

    def _send(self, sock: socket.socket, frame) -> str:
        try:
            wire.send_frame(sock, frame)
        except (ConnectionError, OSError):
            return "close"
        return "park"

    @staticmethod
    def _rst(sock: socket.socket) -> None:
        """Arrange for close() to RST instead of FIN (SO_LINGER 0)."""
        try:
            sock.setsockopt(
                socket.SOL_SOCKET,
                socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
        except OSError:
            pass


class GraphService:
    """Serves one GraphStore shard over the wire protocol."""

    def __init__(
        self,
        store: GraphStore,
        meta: GraphMeta,
        shard: int,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: Registry | None = None,
        workers: int | None = None,
        wal_dir: str | None = None,
        replica: int | None = None,
        group_size: int = 1,
        lease_ttl: float | None = None,
        generation: int = 0,
        topology_epoch: int = 0,
    ):
        self.store = store
        self.meta = meta
        self.shard = shard
        # elastic resharding (PR 19): `generation` is this member's
        # membership generation — heartbeats carry it so registry lookups
        # can hide mid-reshard destinations until the topology commit;
        # `topology_epoch` rides `stats` so client read caches fully
        # flush across a reshard (row-keyed blocks encode the OLD row
        # space, a graph_epoch bump alone cannot express that). `_fenced`
        # is the cutover write barrier: a non-None token refuses
        # mutations/publishes with the typed ReshardFencedError.
        self.generation = int(generation)
        self.topology_epoch = int(topology_epoch)
        self._fenced: str | None = None
        self._fence_term = 0
        # streaming-mutation state (graph/delta.py): staged writes are
        # invisible to readers until publish_epoch merges them and swaps
        # self.store in ONE reference assignment (dispatch binds
        # `s = self.store` once per request, so reads are never torn).
        # _applied is the bounded idempotency-key window that makes
        # retried writer batches apply-once, across publishes included;
        # all three fields are guarded by _delta_lock.
        self._delta = None
        self._applied: collections.OrderedDict = collections.OrderedDict()
        self._delta_lock = threading.Lock()
        # durability (graph/wal.py): with a wal_dir, every acked mutation
        # is fsync'd to the WAL before its response leaves, snapshots run
        # on the publish cadence, and construction FIRST recovers the
        # store from snapshot + WAL-suffix replay — the socket only binds
        # (below) once the shard serves the recovered epoch.
        self.wal_dir = wal_dir
        self._wal = None
        self.recovering = False
        self.recovery_report: dict | None = None
        self._last_snapshot_epoch: int | None = None
        self._publish_count = 0
        # (store, applied-window copy, wal position) captured atomically
        # at each publish — the only states a snapshot may persist (a
        # mid-delta snapshot would trim acked-but-unpublished records)
        self._snap_state: tuple | None = None
        self._snap_busy = threading.Lock()
        # at-rest integrity (graph/backup.py, PR 15): scrub pass /
        # corruption / repair counters plus the degraded flag, surfaced
        # through `stats` and `repl_status` → fleet_stats. The scrubber
        # daemon itself starts in start() when EULER_TPU_SCRUB_S > 0.
        self.scrub_passes = 0
        self.scrub_corruptions = 0
        self.scrub_repairs = 0
        self.degraded: str | None = None
        self.last_scrub: dict | None = None
        self._scrubber = None
        if wal_dir is not None:
            from euler_tpu.graph import wal as walmod

            self.recovering = True
            rec = walmod.recover(
                meta, shard, wal_dir, store,
                applied_keys_max=self.APPLIED_KEYS_MAX,
                publish_result_cap=self.PUBLISH_RESULT_CAP,
            )
            self.store = rec.store
            self._delta = rec.delta
            self._applied = rec.applied
            self._wal = rec.wal
            self.recovery_report = rec.report
            self.recovering = False
            # a fence set by a reshard cutover survives kill -9: the
            # marker re-arms it before the socket binds, so a respawned
            # source can never accept a write the migration missed
            try:
                with open(os.path.join(wal_dir, self.FENCE_MARKER)) as f:
                    m = json.load(f)
                self._fenced = str(m.get("token", "resharded"))
                self._fence_term = int(m.get("term", 0))
            except (OSError, ValueError, json.JSONDecodeError):
                pass
        # _PoolServer reads this before spawning coordinator threads
        self.may_coordinate = meta.num_partitions > 1
        self.server = _PoolServer((host, port), self, workers)
        self.host, self.port = self.server.server_address
        self.registry = registry
        self._beat = None
        self._cluster_g = None
        self._cluster_lock = threading.Lock()
        # per-op request counter (read in-process by tests, over the wire
        # via the "stats" op, and by the bench's RPC-count lane). Counter
        # updates race benignly across pool workers — it is telemetry,
        # not an invariant.
        self.op_counts: collections.Counter = collections.Counter()
        # per-verb wire byte counters (server side of the byte-budget
        # story): _PoolServer adds 4+len(payload) on receipt and the
        # encoded frame size on send. Same benign-race telemetry stance
        # as op_counts.
        self.wire_bytes_in: collections.Counter = collections.Counter()
        self.wire_bytes_out: collections.Counter = collections.Counter()
        # replication (distributed/replication.py): with replica=,
        # this shard is one member of a replica group — a coordinator
        # runs the lease/tail/promotion state machine, mutations gate on
        # primaryship, and acks honor EULER_TPU_REPL_ACK. Solo shards
        # (replica=None) keep every pre-PR-13 behavior byte-for-byte.
        self._repl = None
        # the pristine construction-time store: a follower whose history
        # diverged past the primary's oldest snapshot re-syncs from here
        # (identical across replicas — same dataset partition)
        self._source_store = store
        if replica is not None:
            if registry is None or wal_dir is None:
                raise ValueError(
                    "replication needs registry= (leases/membership)"
                    " and wal_dir= (the shipped log)"
                )
            from euler_tpu.distributed.replication import (
                ReplicaCoordinator,
            )

            self._repl = ReplicaCoordinator(
                self, registry, replica_id=int(replica),
                group_size=int(group_size), lease_ttl=lease_ttl,
            )

    # -- lifecycle -------------------------------------------------------

    def start(self):
        self.server.start()
        if self.registry is not None:
            # a replicated shard heartbeats the coordinator's live meta
            # dict (replica id, role, shipped position, term) — what
            # peers read during promotion
            hb = (
                self._repl.heartbeat_meta
                if self._repl is not None else None
            )
            if self.generation:
                # non-zero generations ride the heartbeat so lookups can
                # filter; gen-0 members keep pre-reshard heartbeat bytes.
                # A replicated shard's live heartbeat dict is mutated in
                # place (peers read it through the beat), a solo shard
                # gets a fresh one.
                if self._repl is not None:
                    hb["gen"] = self.generation
                else:
                    hb = {"gen": self.generation}
            self._beat = self.registry.register(
                self.shard, self.host, self.port, meta=hb,
            )
        if self._repl is not None:
            self._repl.start()
        if self._wal is not None:
            from euler_tpu.graph.backup import (
                IntegrityScrubber,
                scrub_cadence_s,
            )

            cadence = scrub_cadence_s()
            if cadence > 0:
                self._scrubber = IntegrityScrubber(self, cadence).start()
        return self

    def stop(self, drain_s: float | None = None):
        """Shut down; with drain_s, gracefully: deregister from the
        registry FIRST (clients stop routing here), refuse new
        connections, finish in-flight work (bounded by drain_s), then
        close. drain_s=None keeps the immediate-stop behavior."""
        if self._scrubber is not None:
            self._scrubber.stop()
        if self._repl is not None:
            self._repl.stop()
        if self._beat is not None:
            self._beat.set()
        if drain_s:
            self.server.drain(drain_s)
        self.server.shutdown()
        self.server.server_close()
        if self._wal is not None:
            self._wal.close()

    # -- cluster facade (worker-to-worker fan-out) -----------------------

    def _cluster(self):
        """Graph facade over the whole cluster: this server's local store
        plus RemoteShard clients to its peers — the worker-to-worker path
        that lets one client RPC cover a multi-shard, multi-hop query
        (the reference workers issue remote ops to peer shards the same
        way, remote_op.cc:31-36)."""
        with self._cluster_lock:
            if self._cluster_g is None:
                from euler_tpu.graph.store import Graph

                num_parts = self.meta.num_partitions
                if num_parts == 1:
                    self._cluster_g = Graph(self.meta, [self.store])
                else:
                    if self.registry is None:
                        raise RuntimeError(
                            "multi-shard fan-out needs a registry so peers"
                            " can be discovered"
                        )
                    from euler_tpu.distributed.client import RemoteShard

                    cluster = self.registry.wait_for(num_parts)
                    shards = []
                    for idx in sorted(cluster):
                        if idx == self.shard:
                            shards.append(self.store)
                        else:
                            shards.append(RemoteShard(idx, cluster[idx]))
                    self._cluster_g = Graph(self.meta, shards)
            return self._cluster_g

    # -- dispatch --------------------------------------------------------

    COORDINATOR_OPS = ("sample_fanout", "sage_minibatch", "exec_plan")

    # Load-bearing verb table: dispatch() gates on it, graftlint's
    # wire-protocol checker diffs it against the `op ==` chain below and
    # against the clients' WIRE_VERBS, and tests/test_wire_parity.py
    # asserts client/server parity at runtime.
    HANDLED_VERBS = frozenset({
        "condition_mask",
        "condition_weight",
        "degree_sum",
        "delete_edges",
        "dense_feature_udf",
        "edges_by_rows",
        "exec_plan",
        "fence",
        "frontier_exchange",
        "get_binary_feature",
        "get_dense_by_rows",
        "get_dense_feature",
        "get_edge_binary_feature",
        "get_edge_dense_feature",
        "get_edge_sparse_feature",
        "get_full_neighbor",
        "get_graph_by_label",
        "get_meta",
        "get_sparse_feature",
        "get_top_k_neighbor",
        "ids_by_rows",
        "lookup",
        "node2vec_step",
        "node_ids_by_condition",
        "node_type",
        "num_nodes",
        "ping",
        "publish_epoch",
        "random_walk",
        "repl_status",
        "sage_minibatch",
        "sample_edge",
        "sample_edge_with_condition",
        "sample_fanout",
        "sample_nb_rows",
        "sample_neighbor",
        "sample_neighbor_layerwise",
        "sample_node",
        "sample_node_with_condition",
        "scrub",
        "stats",
        "unfence",
        "unit_edge_weights",
        "upsert_edges",
        "upsert_nodes",
        "wal_pos",
        "wal_ship",
    })

    def is_coordinator(self, op: str) -> bool:
        """True for ops that fan out to peer shards (blocking leaf RPCs);
        these must not consume main-pool workers or two mutually-dependent
        servers can deadlock with every worker waiting on the other."""
        return op in self.COORDINATOR_OPS and self.meta.num_partitions > 1

    def dispatch(self, op: str, a: list) -> list:
        if op not in self.HANDLED_VERBS:
            # same message older clients' degrade paths already match on
            raise ValueError(f"unknown op {op!r}")
        s = self.store
        self.op_counts[op] += 1
        if op == "get_meta":
            return [json.dumps(self.meta.to_dict())]
        if op == "ping":
            return [self.shard]
        if op == "stats":
            # graph_epoch versions the shard's data for client read
            # caches: bump it on any mutation and every client flushes on
            # its next observation. Old clients ignore the field; old
            # SERVERS omit it, which clients read as 0 = cache-forever.
            delta = self._delta
            return [json.dumps({
                "shard": self.shard,
                "op_counts": dict(self.op_counts),
                "graph_epoch": int(getattr(s, "graph_epoch", 0)),
                # staged-but-unpublished writes (the delta overlay);
                # readers never see them, operators want to
                "delta_pending": (
                    0 if delta is None else delta.pending()["rows"]
                ),
                # durability lag (graph/wal.py): bytes of acked-but-not-
                # yet-snapshotted WAL, the epoch the newest snapshot
                # covers (null = none yet / WAL off), and whether the
                # shard is mid-recovery. Old clients ignore the fields.
                # elastic resharding (PR 19): the topology epoch versions
                # the SHARD LAYOUT the way graph_epoch versions the data.
                # A change means row spaces moved — clients must fully
                # flush row-keyed cache blocks, not just invalidate rows.
                # Old clients ignore the field; old servers omit it.
                "topology_epoch": int(self.topology_epoch),
                "fenced": self._fenced is not None,
                "wal_bytes": self._wal.size() if self._wal else 0,
                "last_snapshot_epoch": self._last_snapshot_epoch,
                "recovering": bool(self.recovering),
                # at-rest integrity (PR 15): scrub counters, the
                # degraded flag (null = healthy), and any snapshot
                # corpses recovery quarantined at boot
                "scrub_passes": int(self.scrub_passes),
                "scrub_corruptions": int(self.scrub_corruptions),
                "scrub_repairs": int(self.scrub_repairs),
                "degraded": self.degraded,
                "snapshots_quarantined": (
                    (self.recovery_report or {}).get(
                        "snapshots_quarantined", []
                    )
                ),
                # per-verb wire bytes (PR 16): what this server received
                # / sent per op, counted at the socket seam. Old clients
                # ignore the fields.
                "wire_bytes_in": dict(self.wire_bytes_in),
                "wire_bytes_out": dict(self.wire_bytes_out),
            })]
        if op == "scrub":
            # one synchronous at-rest integrity pass (graph/backup.py):
            # verify snapshot crc manifests + re-parse the WAL,
            # quarantine/repair, return the report. a[0] (optional)
            # False = detect-only, no repair attempts.
            repair = bool(a[0]) if a else True
            return [json.dumps(self.scrub_now(repair=repair))]
        if op == "repl_status":
            # replication introspection: role/term/position/primary —
            # the writer's primary-discovery verb and the ops dashboard
            # row. Deterministic given the coordinator's state; solo
            # (un-replicated) shards answer role="solo".
            return [json.dumps(self.repl_status())]
        if op == "wal_pos":
            # [term, wal_base, wal_end, graph_epoch] — the cheap
            # position probe promotion and catch-up monitoring poll
            return self._wal_pos()
        if op == "wal_ship":
            # the follower tail verb: [from_pos, max_bytes, replica_id,
            # want, tail_crc, tail_len, poll_ms] → raw record bytes (or
            # snapshot state for bootstrap). The from_pos doubles as the
            # follower's durable-ack position (quorum accounting).
            return self._wal_ship(a)
        if op == "fence":
            return self._fence(a)
        if op == "unfence":
            return self._unfence(a)
        if op == "upsert_nodes":
            return self._stage_mutation(op, a)
        if op == "upsert_edges":
            return self._stage_mutation(op, a)
        if op == "delete_edges":
            return self._stage_mutation(op, a)
        if op == "publish_epoch":
            return self._publish_epoch(a[0] if a else None)
        if op == "num_nodes":
            return [int(s.num_nodes)]
        if op == "ids_by_rows":
            # the inverse of lookup: local rows → (id, weight, type) —
            # what remote device-resident staging sweeps to enumerate the
            # shard's node table (out-of-range rows → DEFAULT_ID/0/-1,
            # the standard missing-row triple). Deterministic, so client
            # read caches may serve it.
            from euler_tpu.graph.store import DEFAULT_ID

            rows = np.asarray(a[0], np.int64)
            ok = (rows >= 0) & (rows < s.num_nodes)
            safe = np.clip(rows, 0, max(s.num_nodes - 1, 0))
            if s.num_nodes == 0:
                return [
                    np.full(len(rows), DEFAULT_ID, np.uint64),
                    np.zeros(len(rows), np.float64),
                    np.full(len(rows), -1, np.int32),
                ]
            return [
                np.where(ok, np.asarray(s.node_ids)[safe], DEFAULT_ID),
                np.where(
                    ok, np.asarray(s.node_weights, np.float64)[safe], 0.0
                ),
                np.where(
                    ok, np.asarray(s.node_types, np.int32)[safe], -1
                ).astype(np.int32),
            ]
        if op == "edges_by_rows":
            # bulk CSR export for the whole-graph analytics engine
            # (ISSUE 12): local rows → ragged out-adjacency (counts,
            # dst ids, weights, types), type-major per row in storage
            # order — deterministic, so the response is a pure function
            # of the published epoch. Out-of-range rows export degree 0.
            rows = np.asarray(a[0], np.int64)
            etypes = None if len(a) < 2 or a[1] is None else [
                int(t) for t in np.asarray(a[1]).ravel()
            ]
            n = int(s.num_nodes)
            ok = (rows >= 0) & (rows < n)
            safe = np.clip(rows, 0, max(n - 1, 0))
            types = (
                range(len(s.adj)) if etypes is None
                else [t for t in etypes if 0 <= t < len(s.adj)]
            )
            row_pos, dst, w, tt = [], [], [], []
            for t in types:
                c = s.adj[t]
                indptr = np.asarray(c.indptr, np.int64)
                lens = np.where(ok, indptr[safe + 1] - indptr[safe], 0)
                total = int(lens.sum())
                idx = np.repeat(indptr[safe], lens)
                if total:
                    step = np.arange(total, dtype=np.int64)
                    step -= np.repeat(
                        np.cumsum(lens, dtype=np.int64) - lens, lens
                    )
                    idx = idx + step
                row_pos.append(
                    np.repeat(np.arange(len(rows), dtype=np.int64), lens)
                )
                dst.append(np.asarray(c.dst, np.uint64)[idx])
                w.append(np.asarray(c.w, np.float32)[idx])
                tt.append(np.full(total, t, np.int32))
            if not row_pos:
                out = [
                    np.zeros(len(rows), np.int64),
                    np.empty(0, np.uint64),
                    np.empty(0, np.float32),
                    np.empty(0, np.int32),
                ]
            else:
                row_pos = np.concatenate(row_pos)
                order = np.lexsort((np.concatenate(tt), row_pos))
                out = [
                    np.bincount(row_pos, minlength=len(rows)).astype(
                        np.int64
                    ),
                    np.concatenate(dst)[order],
                    np.concatenate(w)[order],
                    np.concatenate(tt)[order],
                ]
            if len(a) > 2 and a[2] == "delta":
                # offered compact dst plane (PR 16): per-row sorted CSR
                # runs delta-compress well. Exact after decode; old
                # clients send 2 args and keep the raw u64 plane.
                from euler_tpu.distributed import codec

                out[1] = np.frombuffer(
                    codec.encode_u64_delta(np.asarray(out[1], np.uint64)),
                    np.uint8,
                )
            return out
        if op == "frontier_exchange":
            # boundary-vertex message reduction for the analytics BSP
            # step: (rows, keys, vals, mode) → per-row reduction in THE
            # canonical order (primitives.reduce_messages — the same
            # function the client's in-process path runs, so local and
            # remote execution agree bit-for-bit). Stateless and pure.
            from euler_tpu.analytics.primitives import reduce_messages

            u, v, k = reduce_messages(a[0], a[1], a[2], str(a[3]))
            return [u, v, k]
        if op == "exec_plan":
            # fused per-shard sub-plan (SPLIT → REMOTE → MERGE parity,
            # optimizer.h:49-86): the whole compiled chain for this
            # shard's root subset runs here, next to the data; off-shard
            # hops scatter worker-to-worker through the cluster facade
            from euler_tpu.query.plan import execute_plan, pack_results

            return pack_results(execute_plan(
                self._cluster(),
                json.loads(a[0]),
                np.asarray(a[1], np.uint64),
                int(a[2]),
            ))
        if op == "sample_fanout":
            res = self._cluster().fanout_with_rows(
                a[0], a[1], a[2], _rng_from(a[3])
            )
            if res is None:
                raise RuntimeError("fused fanout unsupported on this shard")
            hop_ids, hop_w, hop_tt, hop_mask, hop_rows = res
            return [
                np.concatenate(hop_ids),
                np.concatenate(hop_w),
                np.concatenate(hop_tt),
                np.concatenate(hop_mask).astype(np.uint8),
                np.concatenate(hop_rows),
            ]
        if op == "sage_minibatch":
            return self._sage_minibatch(*a)
        if op == "lookup":
            return [s.lookup(a[0])]
        if op == "node_type":
            return [s.node_type(a[0])]
        if op == "sample_node":
            return [s.sample_node(a[0], a[1], _rng_from(a[2]))]
        if op == "sample_edge":
            return [s.sample_edge(a[0], a[1], _rng_from(a[2]))]
        if op == "sample_neighbor":
            out = s.sample_neighbor(a[0], a[1], a[2], _rng_from(a[3]), a[4])
            return list(out)
        if op == "sample_nb_rows":
            nbr, mask, rows = s.sample_neighbor_rows(
                a[0], a[1], a[2], _rng_from(a[3])
            )
            # local rows always fit int32 (engine caps shards at 2^31
            # nodes) — half the bytes of the biggest lean-leaf column
            return [nbr, mask.astype(np.uint8), rows.astype(np.int32)]
        if op == "unit_edge_weights":
            return [bool(s.unit_edge_weights(a[0]))]
        if op == "dense_feature_udf":
            # server-side UDF aggregation: runs UDFs registered in THIS
            # process (register_udf), like the reference's server-side
            # kernel registry; unknown names raise back to the client,
            # which falls back to client-side aggregation
            from euler_tpu.query.gql import dense_feature_udf

            out, w = dense_feature_udf(s, a[0], a[1], a[2])
            return [out, w]
        if op == "get_full_neighbor":
            out = list(s.get_full_neighbor(a[0], a[1], a[2], a[3], a[4]))
            if len(a) > 5 and a[5] == "delta":
                # offered compact encoding (PR 16): the padded neighbor-id
                # plane — mostly DEFAULT_ID and locally sorted runs —
                # collapses under zigzag-delta varints. Exact after
                # decode; old clients never send a[5].
                from euler_tpu.distributed import codec

                nbr = np.asarray(out[0], np.uint64)
                out[0] = np.frombuffer(
                    codec.encode_u64_delta(nbr.reshape(-1)), np.uint8
                )
            return out
        if op == "get_top_k_neighbor":
            return list(s.get_top_k_neighbor(a[0], a[1], a[2], a[3]))
        if op == "degree_sum":
            return [s.degree_sum(a[0], a[1], a[2])]
        if op == "sample_neighbor_layerwise":
            return list(
                s.sample_neighbor_layerwise(a[0], a[1], a[2], _rng_from(a[3]))
            )
        if op == "get_dense_feature":
            return self._quant_wire(
                s.get_dense_feature(a[0], a[1]),
                a[2] if len(a) > 2 else None,
            )
        if op == "get_dense_by_rows":
            return self._quant_wire(
                s.get_dense_by_rows(np.asarray(a[0], np.int64), a[1]),
                a[2] if len(a) > 2 else None,
            )
        if op == "get_sparse_feature":
            pairs = s.get_sparse_feature(a[0], a[1], a[2])
            return [x for pair in pairs for x in pair]
        if op == "get_binary_feature":
            outs = s.get_binary_feature(a[0], a[1])
            # bytes → u8 arrays with per-name offsets
            result = []
            for vals in outs:
                blob = b"".join(vals)
                offs = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
                result.append(offs)
                result.append(np.frombuffer(blob, dtype=np.uint8))
            return result
        if op == "get_edge_dense_feature":
            return [s.get_edge_dense_feature(a[0], a[1])]
        if op == "get_edge_sparse_feature":
            pairs = s.get_edge_sparse_feature(a[0], a[1], a[2])
            return [x for pair in pairs for x in pair]
        if op == "get_edge_binary_feature":
            outs = s.get_edge_binary_feature(a[0], a[1])
            result = []
            for vals in outs:
                blob = b"".join(vals)
                offs = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
                result.append(offs)
                result.append(np.frombuffer(blob, dtype=np.uint8))
            return result
        if op == "get_graph_by_label":
            return [list(s.get_graph_by_label(a[0]))]
        if op == "condition_weight":
            # DNF conditions ride the wire as JSON (values are plain
            # str/int/float); the matched weight lets the client run the
            # shard-weighted conditioned root draw (index pushdown parity,
            # compiler.h:37-41)
            res = s.search_condition(json.loads(a[0]), node=a[1])
            return [float(res.total_weight)]
        if op == "sample_node_with_condition":
            return [
                s.sample_node_with_condition(
                    a[0], json.loads(a[1]), a[2], _rng_from(a[3])
                )
            ]
        if op == "sample_edge_with_condition":
            return [
                s.sample_edge_with_condition(
                    a[0], json.loads(a[1]), a[2], _rng_from(a[3])
                )
            ]
        if op == "condition_mask":
            return [
                s.condition_mask(a[0], json.loads(a[1]), node=a[2]).astype(
                    np.uint8
                )
            ]
        if op == "node_ids_by_condition":
            return [s.get_node_ids_by_condition(json.loads(a[0]))]
        if op == "random_walk":
            return [s.random_walk(a[0], a[1], a[2], a[3], a[4], _rng_from(a[5]))]
        if op == "node2vec_step":
            return [
                s._node2vec_step(a[0], a[1], a[2], a[3], a[4], _rng_from(a[5]))
            ]
        raise RuntimeError(
            f"op {op!r} is in HANDLED_VERBS but has no dispatch arm"
        )

    @staticmethod
    def _quant_wire(vals, kind) -> list:
        """Dense-feature reply under an OFFERED trailing wire dtype
        (PR 16): "bf16" halves the payload (one bf16 array), "int8"
        quarters it ([q u8, scale f32, lo f32] per-row affine). No
        offer / "f32" keeps the exact single-f32-array reply old
        clients expect. The error bound lives in codec.quant_error_
        budget and is pinned in PARITY.md."""
        if kind is None or str(kind) == "f32":
            return [vals]
        from euler_tpu.distributed import codec

        return codec.quantize(
            str(kind), np.asarray(vals, np.float32)
        )

    # -- streaming mutation (graph/delta.py) -----------------------------

    # bounded idempotency window: far wider than any writer's in-flight
    # batch count, evicted FIFO so it can never grow without bound
    APPLIED_KEYS_MAX = 4096
    # a publish whose stale set is bigger than this answers retries with
    # rows=None (full-invalidate) instead of caching huge arrays
    PUBLISH_RESULT_CAP = 65536

    # -- reshard fencing (PR 19) -----------------------------------------

    # durable fence marker (inside wal_dir): a fenced source that is
    # kill -9'd and respawned boots fenced again — see _fence
    FENCE_MARKER = "reshard_fence.json"

    def _check_fenced(self) -> None:
        """Refuse mutations/publishes while a reshard cutover holds the
        fence. The typed error subclasses NotPrimaryError with
        `primary=?`, so pre-reshard writers ride their existing
        redirect/backoff loop while the topology watch re-routes them."""
        if self._fenced is not None:
            raise ReshardFencedError(
                NotPrimaryError.format(
                    self.shard, "fenced", self._fence_term, None
                )
            )

    def _fence(self, a: list) -> list:
        """Cutover write barrier: set the fence (new mutations refuse
        from here on), then take the delta lock once — any mutation that
        passed the gate before the flag landed has committed and
        released by the time the lock is ours, so the returned WAL end
        is stable until unfence. args [token, term]; replies
        [term, wal_end, graph_epoch]. Idempotent per token.

        The fence is DURABLE when the shard has a wal_dir: a marker file
        survives kill -9 + supervised respawn, so a source that crashes
        mid-cutover comes back still refusing writes — without it, a
        restarted source would silently accept (and lose) writes that
        the committed cutover already migrated past."""
        token = str(a[0]) if a and a[0] is not None else "fenced"
        term = int(a[1]) if len(a) > 1 and a[1] is not None else 0
        self._fenced = token
        self._fence_term = max(self._fence_term, term)
        if self.wal_dir is not None:
            marker = os.path.join(self.wal_dir, self.FENCE_MARKER)
            tmp = marker + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"token": token, "term": int(self._fence_term)}, f
                )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, marker)
        with self._delta_lock:
            wal_end = int(self._wal.tell()) if self._wal is not None else 0
            epoch = int(getattr(self.store, "graph_epoch", 0))
        return [
            int(self._repl.term) if self._repl is not None else 0,
            wal_end,
            epoch,
        ]

    def _unfence(self, a: list) -> list:
        """Lift the fence (reshard abort / rollback). An empty token
        lifts unconditionally; a token only lifts its own fence, so a
        stale aborting coordinator cannot unfence a newer reshard.
        Removes the durable marker. Replies [unfenced_bool]."""
        token = str(a[0]) if a and a[0] is not None else ""
        if self._fenced is not None and token in ("", self._fenced):
            self._fenced = None
            if self.wal_dir is not None:
                try:
                    os.remove(os.path.join(self.wal_dir, self.FENCE_MARKER))
                except OSError:
                    pass
        return [self._fenced is None]

    def _stage_mutation(self, op: str, a: list) -> list:
        """Stage one writer batch into the shard's delta overlay.

        [n_staged, applied] — applied=False means the idempotency key
        was already seen (the writer's transport retry of a batch whose
        response got lost): the batch is NOT re-staged, so a retried
        upsert never double-applies. Overflow past the delta's row bound
        raises the typed OverloadError (never transport-retried) BEFORE
        anything is buffered or logged.

        Durability: the WAL record is written under the delta lock (so
        log order == staging order and replay can never diverge) and
        fsync'd AFTER the lock drops but BEFORE this returns — the ack
        never races ahead of the disk, and concurrent stagers share one
        group-committed fsync."""
        from euler_tpu.graph import wal as walmod

        # replica groups: only the live-leased primary may stage. A
        # follower (or a fenced ex-primary past its lease) answers the
        # typed NotPrimaryError naming the current primary — the
        # writer's redirect signal. The gate sits BEFORE any state
        # changes, so a rejected write leaves nothing behind.
        self._check_fenced()
        if self._repl is not None:
            self._repl.check_primary()
        key = str(a[0])
        seq = None
        pos = None
        with self._delta_lock:
            hit = self._applied.get(key)
            if hit is not None:
                return [0, False]
            delta = self._delta
            if delta is None:
                from euler_tpu.graph.delta import DeltaStore

                delta = self._delta = DeltaStore(
                    self.shard, self.meta.num_partitions
                )
            n = walmod.stage_record(delta, op, a)
            if self._wal is not None:
                try:
                    # records carry the primary's term — replay unwraps
                    # it, and fencing proofs read it back
                    seq, pos = self._wal.write(
                        op, a,
                        term=(
                            self._repl.term
                            if self._repl is not None else 0
                        ),
                    )
                except OSError:
                    # disk full/IO error AFTER the rows staged (no roll
                    # back): record the key so a retry can't double-apply
                    # in THIS process, then surface the typed failure —
                    # the batch is applied in memory but NOT durable
                    # (OPERATIONS.md disk-full row)
                    self._applied[key] = True
                    raise
            self._applied[key] = True
            while len(self._applied) > self.APPLIED_KEYS_MAX:
                self._applied.popitem(last=False)
        if seq is not None:
            self._wal.commit(seq)
        if self._repl is not None and pos is not None:
            # quorum mode: the ack leaves only after ⌈R/2⌉ followers
            # have durably shipped past this record (async/off: the
            # notify still wakes long-polling shippers, no wait)
            self._repl.after_commit(pos)
        return [n, True]

    def _publish_epoch(self, key) -> list:
        """Merge the staged delta at an epoch boundary and swap
        self.store in one reference assignment (readers bind the store
        once per request — no torn snapshot, in-flight reads finish on
        the old immutable arrays). Returns
        [epoch, mutated_local_rows|None, touched_ids|None, num_nodes];
        None row/id sets tell the client to fully flush its cache (used
        for oversized stale sets and for retried publishes whose first
        response was lost)."""
        self._check_fenced()
        if self._repl is not None:
            self._repl.check_primary()
        seq = None
        pos = None
        snapshot_due = False
        with self._delta_lock:
            if key is not None:
                hit = self._applied.get(f"pub:{key}")
                if hit is not None:
                    # retried publish: the merge already happened; replay
                    # the recorded outcome instead of merging again
                    return list(hit)
            result = self._merge_delta_locked(key)
            if self._wal is not None:
                seq, pos = self._wal.write(
                    "publish_epoch", [key],
                    term=(
                        self._repl.term if self._repl is not None else 0
                    ),
                )
                snapshot_due = self._note_publish_locked(pos)
        if seq is not None:
            self._wal.commit(seq)
        if self._repl is not None and pos is not None:
            self._repl.after_commit(pos)
        if snapshot_due:
            self._spawn_snapshot()
        return result

    def _merge_delta_locked(self, key) -> list:
        """Merge the staged delta and swap self.store in one reference
        assignment (caller holds _delta_lock). Shared by the primary
        publish path and the follower's shipped-publish replay, so both
        compute the identical store and record the identical outcome
        under `pub:<key>` — a publish retried across a failover replays
        the same answer on the new primary."""
        # graftlint: disable=lock-mixed-write -- every caller holds self._delta_lock (the _locked suffix contract)
        delta, self._delta = self._delta, None
        store = self.store
        if delta is None or delta.empty:
            result = [
                int(getattr(store, "graph_epoch", 0)),
                np.empty(0, np.int64),
                np.empty(0, np.uint64),
                int(store.num_nodes),
            ]
        else:
            new_store, rows, ids = store.merge_delta(delta)
            # graftlint: disable=lock-mixed-write -- every caller holds self._delta_lock (the _locked suffix contract)
            self.store = new_store
            # the cluster facade binds the old store object; patch it
            # so coordinator ops (exec_plan/sample_fanout) serve the
            # new epoch too
            self._swap_cluster_store(store)
            if len(rows) + len(ids) > self.PUBLISH_RESULT_CAP:
                rows = ids = None  # client falls back to a full flush
            result = [
                int(self.store.graph_epoch),
                rows,
                ids,
                int(self.store.num_nodes),
            ]
        if key is not None:
            # graftlint: disable=lock-mixed-write -- every caller holds self._delta_lock (the _locked suffix contract)
            self._applied[f"pub:{key}"] = tuple(result)
            while len(self._applied) > self.APPLIED_KEYS_MAX:
                # graftlint: disable=lock-mixed-write -- every caller holds self._delta_lock (the _locked suffix contract)
                self._applied.popitem(last=False)
        return result

    def _swap_cluster_store(self, old_store) -> None:
        """Re-point the cluster facade's local-shard slot at the current
        self.store (the facade bound the old object at build time)."""
        with self._cluster_lock:
            g = self._cluster_g
            if g is not None:
                for i, sh in enumerate(g.shards):
                    if sh is old_store:
                        g.shards[i] = self.store
                g.refresh_shard_weights()

    def _note_publish_locked(self, pos: int) -> bool:
        """Record a publish at WAL position `pos` (caller holds
        _delta_lock): capture the snapshot-eligible state and answer
        whether the snapshot cadence is due."""
        self._publish_count += 1
        # the ONLY WAL positions a snapshot may cover: here the
        # store, the applied window, and the log position agree
        # (staged-but-unpublished records all sit past `pos`)
        # graftlint: disable=lock-mixed-write -- every caller holds self._delta_lock (the _locked suffix contract)
        self._snap_state = (
            self.store,
            collections.OrderedDict(self._applied),
            pos,
        )
        from euler_tpu.graph.wal import snapshot_every

        every = snapshot_every()
        return bool(every and self._publish_count % every == 0)

    # -- snapshots (graph/wal.py) ----------------------------------------

    def _spawn_snapshot(self) -> bool:
        """Kick one background snapshot of the last published state; a
        snapshot already in flight skips (the next cadence hit catches
        up). The dispatch path never blocks: the captured store is an
        immutable published object, serialized off-thread."""
        if not self._snap_busy.acquire(blocking=False):
            return False
        t = threading.Thread(
            target=self._snapshot_run, daemon=True,
            name=f"shard{self.shard}-snapshot",
        )
        t.start()
        return True

    def _snapshot_run(self) -> None:
        # _snap_busy is held (acquired by the caller); release on exit
        try:
            with self._delta_lock:
                state = self._snap_state
            if state is None:
                return
            store, applied, pos = state
            from euler_tpu.graph import wal as walmod

            walmod.write_snapshot(
                self.wal_dir, int(store.graph_epoch), store.arrays,
                applied, pos,
            )
            self._wal.trim(pos)
            self._last_snapshot_epoch = int(store.graph_epoch)
        except Exception as e:  # snapshot failure must not cost serving:
            # the WAL still holds everything, recovery just replays more
            import sys

            print(
                f"# shard {self.shard}: snapshot failed ({e!r}); WAL"
                " retained",
                file=sys.stderr,
            )
        finally:
            self._snap_busy.release()

    def snapshot_now(self) -> bool:
        """Synchronous snapshot of the last published state (operators,
        bench, tests). False when the WAL is off or nothing has been
        published yet."""
        if self._wal is None or self._snap_state is None:
            return False
        self._snap_busy.acquire()
        self._snapshot_run()
        return True

    def scrub_now(self, repair: bool = True) -> dict:
        """One synchronous integrity pass over this shard's at-rest
        artifacts (operators, tests, the `scrub` verb). No-op report
        when the shard has no WAL dir."""
        from euler_tpu.graph import backup as backupmod

        return backupmod.scrub_service(self, repair=repair)

    # -- replication (distributed/replication.py) ------------------------

    def repl_status(self) -> dict:
        """Role/term/position view of this replica — the `repl_status`
        verb body. Solo (un-replicated) shards answer role="solo" so
        writers know there is no primary to discover."""
        st = {
            "shard": self.shard,
            "role": "solo",
            "term": 0,
            "replica": None,
            "group_size": 1,
            "primary": None,
            "ack_mode": None,
            "wal_base": int(self._wal.base) if self._wal else 0,
            "wal_end": int(self._wal.tell()) if self._wal else 0,
            "graph_epoch": int(getattr(self.store, "graph_epoch", 0)),
            # elastic resharding (PR 19): operators watch the fence and
            # membership generation off the same dashboard row
            "fenced": self._fenced is not None,
            "generation": int(self.generation),
            "topology_epoch": int(self.topology_epoch),
            # at-rest integrity (PR 15): ops dashboards read the
            # degraded flag and scrub counters off the same row
            "degraded": self.degraded,
            "scrub_passes": int(self.scrub_passes),
            "scrub_corruptions": int(self.scrub_corruptions),
            "scrub_repairs": int(self.scrub_repairs),
        }
        if self._repl is not None:
            st.update(self._repl.status())
        return st

    def _wal_pos(self) -> list:
        """[term, wal_base, wal_end, graph_epoch]."""
        return [
            int(self._repl.term) if self._repl is not None else 0,
            int(self._wal.base) if self._wal is not None else 0,
            int(self._wal.tell()) if self._wal is not None else 0,
            int(getattr(self.store, "graph_epoch", 0)),
        ]

    def wal_tail_probe(self, window: int = 4096) -> tuple[int, int, int]:
        """(end_pos, tail_crc, tail_len) of this replica's own log — the
        continuity handshake a follower offers with each ship request."""
        if self._wal is None:
            return 0, 0, 0
        pos = self._wal.tell()
        n = min(int(window), pos - self._wal.base)
        if n <= 0:
            return pos, 0, 0
        return pos, self._wal.crc_range(pos - n, pos), n

    def _wal_ship(self, a: list) -> list:
        """Serve one follower tail request.

        args: [from_pos, max_bytes, replica_id, want, tail_crc,
        tail_len, poll_ms] (trailing args optional). Log mode answers
        [term, record_bytes(u8), end_pos, need_snapshot]; snapshot mode
        ([.., want="snapshot"]) answers the newest publish-consistent
        state for bootstrap. `from_pos` is also the follower's durable
        position — the primary's quorum accounting reads it from here.
        need_snapshot=True when the prefix was trimmed, the follower is
        AHEAD of this log, or the tail checksum mismatches (divergent
        history — an ex-primary carrying never-replicated records)."""
        from euler_tpu.distributed import codec

        from_pos = int(a[0])
        max_bytes = int(a[1]) if len(a) > 1 and a[1] is not None else 1 << 20
        rid = int(a[2]) if len(a) > 2 and a[2] is not None else None
        want = str(a[3]) if len(a) > 3 and a[3] is not None else "log"
        # trailing PR-16 args (old clients simply omit them): a[7] is the
        # follower's codec OFFER, a[8] its explicit durable-ack position
        # — a pipelined follower's speculative from_pos runs AHEAD of its
        # fsync, so the ack must travel separately or quorum accounting
        # would count unfsync'd bytes
        offer = str(a[7]) if len(a) > 7 and a[7] is not None else None
        use = (
            offer
            if offer in codec.available_codecs()
            else (codec.IDENTITY if offer is not None else None)
        )
        ack_pos = int(a[8]) if len(a) > 8 and a[8] is not None else from_pos
        if rid is not None and self._repl is not None:
            self._repl.note_follower(rid, ack_pos)
        if want == "snapshot":
            return self._ship_snapshot(use)
        if self._wal is None:
            raise RpcError("wal_ship: this shard has no WAL (wal_dir)")
        term = int(self._repl.term) if self._repl is not None else 0
        tail_crc = int(a[4]) if len(a) > 4 and a[4] is not None else -1
        tail_len = int(a[5]) if len(a) > 5 and a[5] is not None else 0
        poll_ms = float(a[6]) if len(a) > 6 and a[6] is not None else 0.0
        need = False
        if from_pos < self._wal.base or from_pos > self._wal.tell():
            need = True
        elif tail_len > 0:
            try:
                mine = self._wal.crc_range(from_pos - tail_len, from_pos)
                need = mine != (tail_crc & 0xFFFFFFFF)
            except ValueError:
                pass  # window partially trimmed here: snapshot covers it
        if need:
            out = [term, np.empty(0, np.uint8), from_pos, True]
            if use is not None:
                out += [codec.IDENTITY, 0, int(self._wal.tell())]
            return out
        data, end = self._wal.read_raw(from_pos, max_bytes)
        if not data and poll_ms > 0 and self._repl is not None:
            # server-side long poll: wait briefly for the next commit so
            # follower lag (and quorum ack latency) is ~one RTT + fsync,
            # not a client polling interval. EXCEPT when a quorum
            # committer is already parked waiting for an ack newer than
            # this request carried — then answer empty at once so the
            # (pipelined) follower can come back with a fresh ack
            # instead of stalling the commit a full poll interval.
            if rid is None or not self._repl.ack_wanted(ack_pos):
                self._repl.wait_for_append(from_pos, poll_ms / 1e3)
                data, end = self._wal.read_raw(from_pos, max_bytes)
        if use is None:
            # old client: raw 4-tuple, byte-identical to the pre-codec
            # reply (a fifth item would still be ignored, but keeping the
            # shape pinned is what the degrade tests assert)
            return [
                term,
                np.frombuffer(data, np.uint8)
                if data
                else np.empty(0, np.uint8),
                int(end),
                False,
            ]
        # new shape: [.., codec, raw_len, log_end] — log_end tells the
        # follower whether more records are pending behind this batch
        # (throughput mode: overlap + deferred fsync) or it is caught up
        # (latency mode: fsync, then park a fresh-ack request). Tiny
        # batches (steady-state commit tailing) skip compression: the
        # codec rides in the reply, so the choice is per-batch, and
        # putting zlib on a ~2KB commit's serial path only adds latency
        if len(data) < 4096:
            use = codec.IDENTITY
        blob = codec.compress(use, data) if data else b""
        return [
            term,
            np.frombuffer(blob, np.uint8) if blob else np.empty(0, np.uint8),
            int(end),
            False,
            use,
            len(data),
            int(self._wal.tell()),
        ]

    def _ship_snapshot(self, use: str | None = None) -> list:
        """Bootstrap payload: [term, epoch, wal_pos, applied_blob(u8),
        names_json, *arrays] — the newest publish-consistent state (the
        in-memory _snap_state when one exists, else the newest on-disk
        snapshot). When the follower offered a codec (`use` is not
        None), item 4 becomes a v2 JSON header dict and the applied
        blob plus every array ship as compressed u8 blobs — bootstrap
        is the single largest transfer in the system and compresses
        well (sorted ids, zero-padded planes)."""
        from euler_tpu.distributed import codec
        from euler_tpu.graph import wal as walmod

        term = int(self._repl.term) if self._repl is not None else 0
        with self._delta_lock:
            state = self._snap_state
        if state is not None:
            store, applied, pos = state
            epoch, arrays = int(store.graph_epoch), store.arrays
        else:
            if self._wal is None or self.wal_dir is None:
                raise RpcError("wal_ship: no snapshot state to ship")
            snap = walmod.load_snapshot(self.wal_dir, self._wal.base)
            if snap is None:
                raise RpcError(
                    "wal_ship: no usable snapshot (log starts at"
                    f" {self._wal.base})"
                )
            epoch, arrays, applied, pos = snap
            epoch = int(epoch)
        names = sorted(arrays)
        blob = bytes(walmod._applied_blob(applied))
        if use is None:
            return [
                term, epoch, int(pos),
                np.frombuffer(blob, np.uint8),
                json.dumps(names),
            ] + [np.ascontiguousarray(arrays[n]) for n in names]
        mats = [np.ascontiguousarray(arrays[n]) for n in names]
        head = {
            "v": 2,
            "codec": use,
            "names": names,
            "dtypes": [m.dtype.str for m in mats],
            "shapes": [list(m.shape) for m in mats],
        }
        return [
            term, epoch, int(pos),
            np.frombuffer(codec.compress(use, blob), np.uint8),
            json.dumps(head),
        ] + [
            np.frombuffer(codec.compress(use, m.tobytes()), np.uint8)
            for m in mats
        ]

    def apply_shipped(
        self, data: bytes, from_pos: int, durable: bool = True,
        acked=None,
    ) -> int:
        """Follower apply: verbatim-append a shipped record suffix and
        replay it through the SAME staging/merge code the primary ran —
        byte-identical logs and deterministic merges make every replica
        bit-identical by construction. Returns the new durable position
        (the implicit ack the next ship request carries). durable=False
        defers the fsync (pipelined catch-up streaming); the caller must
        wal-sync() before advancing its reported ack. `acked(end)` fires
        right after the durable append, BEFORE the staging replay —
        durability is what a quorum ack certifies, so the shipper sends
        the ack with the replay still pending (it must not raise; the
        replay runs regardless)."""
        from euler_tpu.graph import wal as walmod

        records, valid_end = walmod.parse_records(data, from_pos)
        if valid_end == from_pos:
            return from_pos
        blob = data[: valid_end - from_pos]
        snapshot_due = False
        with self._delta_lock:
            have = self._wal.tell()
            if have != from_pos:
                raise RuntimeError(
                    f"apply_shipped: log at {have}, shipped suffix"
                    f" starts at {from_pos}"
                )
            # durable FIRST (fsync inside), apply second: a crash
            # mid-apply replays the appended records from our own WAL
            self._wal.append_raw(blob, durable=durable)
            if acked is not None:
                acked(valid_end)
            for op, a, end, _term in records:
                if op == "publish_epoch":
                    key = a[0] if a else None
                    if not (
                        key is not None
                        and self._applied.get(f"pub:{key}") is not None
                    ):
                        self._merge_delta_locked(key)
                        snapshot_due = (
                            self._note_publish_locked(end) or snapshot_due
                        )
                    continue
                key = str(a[0])
                if self._applied.get(key) is not None:
                    continue
                if self._delta is None:
                    from euler_tpu.graph.delta import DeltaStore

                    self._delta = DeltaStore(
                        self.shard, self.meta.num_partitions
                    )
                walmod.stage_record(self._delta, op, a)
                self._applied[key] = True
                while len(self._applied) > self.APPLIED_KEYS_MAX:
                    self._applied.popitem(last=False)
        if snapshot_due:
            self._spawn_snapshot()
        return valid_end

    def install_snapshot(self, epoch, arrays, applied, wal_pos) -> None:
        """Follower bootstrap: adopt a shipped publish-consistent state
        wholesale and restart the local log at its position. A local
        snapshot is written synchronously so a restart of THIS replica
        recovers without re-bootstrapping over the wire."""
        from euler_tpu.graph.store import GraphStore

        with self._delta_lock:
            old = self.store
            store = GraphStore(self.meta, dict(arrays), self.shard)
            store.graph_epoch = int(epoch)
            self.store = store
            self._swap_cluster_store(old)
            self._delta = None
            self._applied = collections.OrderedDict(applied)
            self._wal.reset(int(wal_pos))
            self._snap_state = (
                store, collections.OrderedDict(self._applied), int(wal_pos)
            )
        self.snapshot_now()

    def reset_to_source(self) -> None:
        """Last-resort follower re-sync: back to the construction-time
        dataset partition with an empty log — correct only when the
        primary's log still starts at 0 (the caller checks)."""
        with self._delta_lock:
            old = self.store
            self.store = self._source_store
            self._swap_cluster_store(old)
            self._delta = None
            self._applied = collections.OrderedDict()
            self._wal.reset(0)
            self._snap_state = None

    def _sage_minibatch(
        self, batch_size, edge_types, counts, label, node_type, seed, lean
    ) -> list:
        """One-RPC training minibatch: root sampling + fused multi-hop
        fanout + label fetch, coordinated next to the data.

        The reference's SampleFanoutWithFeature kernel plays the same role
        (one Execute RPC carries the whole sampled subgraph + features,
        tf_euler/kernels/sample_fanout_with_feature_op.cc); here the
        response is additionally LEAN when the batch satisfies the lean
        invariants (unit edge weights, no dangling feature rows): just the
        root ids, one int32 feature-row array covering every hop, and the
        root labels — the minimum bytes a rows-mode trainer needs.
        """
        from euler_tpu.graph.store import lean_feats, lean_wire_ok

        g = self._cluster()
        rng = _rng_from(seed)
        counts = [int(c) for c in counts]
        roots = g.sample_node(int(batch_size), int(node_type), rng)

        def labels_of(hop0_rows):
            return (
                g.get_dense_by_rows(np.asarray(hop0_rows, np.int64), [label])
                if label
                else None
            )

        if lean and g.num_shards > 1:
            # lean leaf protocol: per hop ship only ids+mask+rows between
            # shards (no weights/types/edge-ids — 2/3 of the leaf bytes),
            # with rows pre-resolved by each sampler's dst_row cache and
            # one batched round for the rest. hop_w=None: unit weights
            # were verified cluster-wide. Single-shard clusters stay on
            # the one-call native fused fanout below (it beats per-hop
            # Python rounds); peers predating the lean leaf ops drop to
            # the generic path the same way.
            try:
                res = (
                    g.fanout_rows_lean(roots, edge_types, counts, rng)
                    if g.unit_edge_weights(edge_types)
                    else None
                )
            except RuntimeError as e:
                if "unknown op" not in str(e):
                    raise
                res = None
            if res is not None:
                _, hop_mask, hop_rows = res
                if lean_wire_ok(roots, None, hop_mask, hop_rows):
                    return [
                        roots,
                        lean_feats(hop_rows),
                        labels_of(hop_rows[0]),
                        True,
                    ]
        res = g.fanout_with_rows(roots, edge_types, counts, rng)
        if res is None:
            raise RuntimeError("fused fanout unsupported on this cluster")
        hop_ids, hop_w, hop_tt, hop_mask, hop_rows = res
        labels = labels_of(hop_rows[0])
        # lean flavor is a GRAPH-level property (unit weights or not), not
        # per-batch: a coincidentally all-unit batch of a weighted graph
        # must still ship weighted-lean so the client's pytree structure
        # stays stable across the run
        unit = g.unit_edge_weights(edge_types)
        if lean and unit and lean_wire_ok(roots, hop_w, hop_mask, hop_rows):
            return [roots, lean_feats(hop_rows), labels, True]
        if lean and not unit and lean_wire_ok(
            roots, hop_w, hop_mask, hop_rows, require_unit_w=False
        ):
            # weighted-lean (VERDICT r3 #5): int32 rows + bf16 edge
            # weights (hops 1..); ids/masks still rebuilt device-side —
            # ~1.5x lean bytes instead of the ~6x full-wire downgrade
            import ml_dtypes

            w16 = np.concatenate(
                [np.asarray(w).reshape(-1) for w in hop_w[1:]]
            ).astype(ml_dtypes.bfloat16)
            return [roots, lean_feats(hop_rows), w16, labels, True]
        return [
            roots,
            np.concatenate(hop_ids),
            np.concatenate(hop_w),
            np.concatenate(hop_tt),
            np.concatenate(hop_mask).astype(np.uint8),
            np.concatenate(hop_rows),
            labels,
            False,
        ]


def serve_shard(
    data_dir: str,
    shard: int,
    host: str = "127.0.0.1",
    port: int = 0,
    registry_path: str | None = None,
    native: bool | None = None,
    workers: int | None = None,
    wal_dir: str | None = None,
    replica: int | None = None,
    group_size: int = 1,
    lease_ttl: float | None = None,
    generation: int = 0,
    topology_epoch: int = 0,
) -> GraphService:
    """Load shard `shard` of the dataset at data_dir and serve it.

    With `wal_dir`, the shard is DURABLE: boot first recovers from the
    newest snapshot + WAL-suffix replay (bit-identical to the pre-crash
    published epoch), then serves; every acked mutation is WAL-logged
    before its response and snapshots run on the publish cadence.

    With `replica=` (+ registry + wal_dir), this process is one member
    of shard's replica group: it contends for the group lease, serves
    writes only as primary, and tails the primary's WAL otherwise."""
    meta = GraphMeta.load(data_dir)
    part_dir = os.path.join(data_dir, f"part_{shard}")
    arrays = tformat.read_arrays(part_dir)
    store: GraphStore
    if native is None or native:
        try:
            from euler_tpu.graph.native import NativeGraphStore, engine_available

            if engine_available():
                store = NativeGraphStore(meta, arrays, shard, part_dir)
            else:
                raise RuntimeError("engine unavailable")
        except Exception:
            if native:
                raise
            store = GraphStore(meta, arrays, shard)
    else:
        store = GraphStore(meta, arrays, shard)
    registry = make_registry(registry_path) if registry_path else None
    return GraphService(
        store, meta, shard, host, port, registry, workers=workers,
        wal_dir=wal_dir, replica=replica, group_size=group_size,
        lease_ttl=lease_ttl, generation=generation,
        topology_epoch=topology_epoch,
    ).start()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", required=True)
    ap.add_argument("--shard", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--registry", default=None)
    ap.add_argument("--no-native", action="store_true")
    ap.add_argument("--wal-dir", default=None,
                    help="durability dir (WAL + snapshots); boot recovers"
                         " from it, mutations fsync to it before the ack")
    ap.add_argument("--replica", type=int, default=None,
                    help="replica id within this shard's group (requires"
                         " --registry and --wal-dir)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="replica group size R (quorum = ⌈R/2⌉ follower"
                         " acks under EULER_TPU_REPL_ACK=quorum)")
    ap.add_argument("--lease-ttl", type=float, default=None,
                    help="primary lease TTL seconds (default from"
                         " EULER_TPU_LEASE_TTL_S, else 5)")
    ap.add_argument("--generation", type=int, default=0,
                    help="membership generation carried in the registry"
                         " heartbeat (reshard destinations boot at gen+1"
                         " and stay invisible to clients until the"
                         " topology commit)")
    ap.add_argument("--topology-epoch", type=int, default=0,
                    help="topology epoch surfaced via stats (client read"
                         " caches fully flush when it changes)")
    args = ap.parse_args(argv)
    svc = serve_shard(
        args.data,
        args.shard,
        args.host,
        args.port,
        args.registry,
        native=False if args.no_native else None,
        wal_dir=args.wal_dir,
        replica=args.replica,
        group_size=args.replicas,
        lease_ttl=args.lease_ttl,
        generation=args.generation,
        topology_epoch=args.topology_epoch,
    )
    if svc.recovery_report and svc.recovery_report.get("recovered"):
        print(
            f"shard {args.shard} recovered: "
            f"{json.dumps(svc.recovery_report)}",
            flush=True,
        )
    print(f"serving shard {args.shard} on {svc.host}:{svc.port}", flush=True)

    # SIGTERM (orchestrator-initiated shutdown) drains: deregister, stop
    # accepting, finish in-flight work, then exit — clients fail over to
    # the surviving replicas instead of seeing torn responses
    import signal

    drain_s = float(os.environ.get("EULER_TPU_DRAIN_S", 5.0))
    done = threading.Event()

    def _term(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _term)
    try:
        done.wait()
        svc.stop(drain_s=drain_s)
    except KeyboardInterrupt:
        svc.stop(drain_s=drain_s)


if __name__ == "__main__":
    main()
