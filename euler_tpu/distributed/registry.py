"""Shared-filesystem membership registry — the ZooKeeper replacement.

The reference registers graph servers as ephemeral ZK znodes
`<path>/<shard>#<host:port>` with shard metadata and re-registers on session
loss (euler/common/zk_server_register.cc:96-161); clients watch children and
get add/remove callbacks (server_monitor.h:33-40). TPU-VM pods share a
filesystem (NFS/GCS-fuse) far more often than they run ZK, so membership
here is heartbeat files in a directory: servers rewrite
`shard_<i>@<host>_<port>.json` every interval; entries whose heartbeat is
stale are treated as removed. Static cluster specs bypass the registry
entirely.
"""

from __future__ import annotations

import json
import os
import threading
import time


class Registry:
    def __init__(self, path: str, ttl: float = 10.0):
        self.path = path
        self.ttl = ttl
        os.makedirs(path, exist_ok=True)

    def _entry_path(self, shard: int, host: str, port: int) -> str:
        return os.path.join(self.path, f"shard_{shard}@{host}_{port}.json")

    # -- server side -----------------------------------------------------

    def register(self, shard: int, host: str, port: int, meta: dict | None = None):
        """Write a heartbeat entry now; returns a stop() handle that keeps
        re-registering in the background (ZK session keep-alive parity)."""
        stop = threading.Event()

        def beat():
            while not stop.is_set():
                entry = {
                    "shard": shard,
                    "host": host,
                    "port": port,
                    "ts": time.time(),
                    "meta": meta or {},
                }
                tmp = self._entry_path(shard, host, port) + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(entry, f)
                os.replace(tmp, self._entry_path(shard, host, port))
                stop.wait(self.ttl / 3)
            try:
                os.remove(self._entry_path(shard, host, port))
            except OSError:
                pass

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        return stop

    # -- client side -----------------------------------------------------

    def lookup(self, num_shards: int) -> dict[int, list[tuple[str, int]]]:
        """shard → [(host, port), ...] with live heartbeats."""
        now = time.time()
        out: dict[int, list[tuple[str, int]]] = {
            s: [] for s in range(num_shards)
        }
        for name in sorted(os.listdir(self.path)):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.path, name)) as f:
                    e = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if now - e.get("ts", 0) > self.ttl:
                continue
            s = int(e["shard"])
            if s in out:
                out[s].append((e["host"], int(e["port"])))
        return out

    def wait_for(self, num_shards: int, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            table = self.lookup(num_shards)
            if all(table[s] for s in range(num_shards)):
                return table
            time.sleep(0.2)
        raise TimeoutError(
            f"registry at {self.path}: not all {num_shards} shards present"
        )
