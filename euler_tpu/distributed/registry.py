"""Shared-filesystem membership registry — the ZooKeeper replacement.

The reference registers graph servers as ephemeral ZK znodes
`<path>/<shard>#<host:port>` with shard metadata and re-registers on session
loss (euler/common/zk_server_register.cc:96-161); clients watch children and
get add/remove callbacks (server_monitor.h:33-40). TPU-VM pods share a
filesystem (NFS/GCS-fuse) far more often than they run ZK, so membership
here is heartbeat files in a directory: servers rewrite
`shard_<i>@<host>_<port>.json` every interval; entries whose heartbeat is
stale are treated as removed. Static cluster specs bypass the registry
entirely.
"""

from __future__ import annotations

import json
import os
import threading
import time


class Registry:
    def __init__(self, path: str, ttl: float = 10.0):
        self.path = path
        self.ttl = ttl
        os.makedirs(path, exist_ok=True)

    def _entry_path(self, shard: int, host: str, port: int) -> str:
        return os.path.join(self.path, f"shard_{shard}@{host}_{port}.json")

    # -- server side -----------------------------------------------------

    def register(self, shard: int, host: str, port: int, meta: dict | None = None):
        """Write a heartbeat entry now; returns a stop() handle that keeps
        re-registering in the background (ZK session keep-alive parity)."""
        stop = threading.Event()

        def beat():
            while not stop.is_set():
                entry = {
                    "shard": shard,
                    "host": host,
                    "port": port,
                    "ts": time.time(),
                    "meta": meta or {},
                }
                tmp = self._entry_path(shard, host, port) + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(entry, f)
                os.replace(tmp, self._entry_path(shard, host, port))
                stop.wait(self.ttl / 3)
            try:
                os.remove(self._entry_path(shard, host, port))
            except OSError:
                pass

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        return stop

    # -- leases (PR 13 replication) --------------------------------------
    #
    # One term-numbered, TTL'd lease per replica group ("shard_<i>"): the
    # holder string is the primary's "host:port", so observing the lease
    # IS primary discovery. A new holder bumps the term — the fencing
    # token every WAL record and wal_ship response carries. File backend:
    # read-modify-write of `lease_<group>.json` under a short-lived
    # O_EXCL lock file (stale locks — a holder killed mid-mutate — are
    # broken after a few seconds).

    def _lease_path(self, group: str) -> str:
        return os.path.join(self.path, f"lease_{group}.json")

    def _lease_mutate(self, group: str, fn):
        """Run fn(current_lease_or_None) -> (new_lease_or_None, result)
        atomically; writes the new lease when one is returned."""
        lock = self._lease_path(group) + ".lock"
        deadline = time.time() + 5.0
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                break
            except FileExistsError:
                try:
                    if time.time() - os.path.getmtime(lock) > 2.0:
                        os.remove(lock)  # break a stale lock
                        continue
                except OSError:
                    continue
                if time.time() > deadline:
                    raise TimeoutError(f"lease lock stuck: {lock}")
                time.sleep(0.01)
        try:
            cur = None
            try:
                with open(self._lease_path(group)) as f:
                    cur = json.load(f)
            except (OSError, json.JSONDecodeError):
                cur = None
            new, result = fn(cur)
            if new is not None:
                tmp = self._lease_path(group) + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(new, f)
                os.replace(tmp, self._lease_path(group))
            return result
        finally:
            try:
                os.remove(lock)
            except OSError:
                pass

    @staticmethod
    def _lease_view(lease: dict | None) -> dict | None:
        if lease is None:
            return None
        return {
            "term": int(lease["term"]),
            "holder": lease["holder"],
            "expires_in": float(lease["expires"]) - time.time(),
            "meta": lease.get("meta") or {},
        }

    def acquire_lease(
        self,
        group: str,
        holder: str,
        ttl: float,
        meta: dict | None = None,
        min_term: int = 0,
    ) -> dict | None:
        """Take the group's lease if it is free, expired, or already
        ours. A NEW holder bumps the term (the fencing token); the same
        holder re-acquiring keeps it. `min_term` floors the resulting
        term — a promotion passes its last-observed term + 1 so a lease
        file lost to a registry wipe can never rewind the fencing clock.
        Returns the lease view ({term, holder, expires_in, meta}) on
        success, None when another holder's lease is still live."""

        def fn(cur):
            now = time.time()
            if (
                cur is not None
                and cur["holder"] != holder
                and float(cur["expires"]) > now
            ):
                return None, None
            term = int(cur["term"]) if cur is not None else 0
            if cur is None or cur["holder"] != holder:
                term += 1
            term = max(term, int(min_term))
            new = {
                "group": group,
                "term": term,
                "holder": holder,
                "expires": now + ttl,
                "meta": meta or {},
            }
            return new, self._lease_view(new)

        return self._lease_mutate(group, fn)

    def renew(
        self, group: str, holder: str, term: int, ttl: float
    ) -> bool:
        """Extend the lease — only when holder AND term still match (a
        fenced ex-primary's renew fails, which is how it learns)."""

        def fn(cur):
            if (
                cur is None
                or cur["holder"] != holder
                or int(cur["term"]) != int(term)
            ):
                return None, False
            cur = dict(cur)
            cur["expires"] = time.time() + ttl
            return cur, True

        return self._lease_mutate(group, fn)

    def observe(self, group: str) -> dict | None:
        """Current lease view ({term, holder, expires_in, meta}) or None.
        `expires_in` <= 0 means expired — a follower may try promotion."""
        try:
            with open(self._lease_path(group)) as f:
                return self._lease_view(json.load(f))
        except (OSError, json.JSONDecodeError):
            return None

    # -- topology (PR 19 elastic resharding) ------------------------------
    #
    # One `topology.json` record per registry: {"num_shards", "gen",
    # "epoch"}. `gen` is the membership generation — heartbeat entries
    # carry their generation in meta["gen"] (absent = 0), and client-facing
    # lookup() only returns entries of the CURRENT generation. A reshard
    # boots destination shards at gen+1 (invisible to clients), then
    # commits the whole topology flip with one set_topology() — the atomic
    # cutover point: old-gen sources vanish from routing and new-gen
    # destinations appear in the same read. No topology file means gen 0,
    # so pre-reshard clusters (whose entries carry no gen) are unchanged.

    def _topology_path(self) -> str:
        return os.path.join(self.path, "topology.json")

    def set_topology(self, num_shards: int, gen: int, epoch: int) -> dict:
        """Atomically publish the cluster topology (fsync'd tmp + rename
        — a torn cutover must never be observable)."""
        rec = {
            "num_shards": int(num_shards),
            "gen": int(gen),
            "epoch": int(epoch),
        }
        tmp = self._topology_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._topology_path())
        return rec

    def topology(self) -> dict | None:
        """The committed topology record, or None (pre-reshard cluster)."""
        try:
            with open(self._topology_path()) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _current_gen(self) -> int:
        topo = self.topology()
        return int(topo.get("gen", 0)) if topo else 0

    @staticmethod
    def _entry_gen(meta: dict | None) -> int:
        try:
            return int((meta or {}).get("gen", 0))
        except (TypeError, ValueError):
            return 0

    # -- client side -----------------------------------------------------

    def lookup_meta(
        self, num_shards: int
    ) -> dict[int, list[tuple[str, int, dict]]]:
        """shard → [(host, port, meta), ...] with live heartbeats — the
        meta carries replica ids and shipped WAL positions (replication
        promotion reads peer positions from here)."""
        now = time.time()
        out: dict[int, list[tuple[str, int, dict]]] = {
            s: [] for s in range(num_shards)
        }
        for name in sorted(os.listdir(self.path)):
            if not name.endswith(".json") or name.startswith("lease_"):
                continue
            if name == "topology.json":
                continue
            try:
                with open(os.path.join(self.path, name)) as f:
                    e = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if now - e.get("ts", 0) > self.ttl:
                continue
            s = int(e["shard"])
            if s in out:
                out[s].append((e["host"], int(e["port"]), e.get("meta") or {}))
        return out

    def members(self, shard: int) -> list[tuple[str, int, dict]]:
        """Live (host, port, meta) entries for one shard group — the
        replica-group view promotion reads peer positions from."""
        try:
            return self.lookup_meta(int(shard) + 1)[int(shard)]
        except OSError:
            return []

    def lookup(self, num_shards: int) -> dict[int, list[tuple[str, int]]]:
        """shard → [(host, port), ...] with live heartbeats, restricted
        to the current topology generation (client routing view — a
        mid-reshard destination at gen+1 stays invisible here until
        set_topology commits the flip)."""
        now = time.time()
        gen = self._current_gen()
        out: dict[int, list[tuple[str, int]]] = {
            s: [] for s in range(num_shards)
        }
        for name in sorted(os.listdir(self.path)):
            if not name.endswith(".json") or name.startswith("lease_"):
                continue
            if name == "topology.json":
                continue
            try:
                with open(os.path.join(self.path, name)) as f:
                    e = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if now - e.get("ts", 0) > self.ttl:
                continue
            if self._entry_gen(e.get("meta")) != gen:
                continue
            s = int(e["shard"])
            if s in out:
                out[s].append((e["host"], int(e["port"])))
        return out

    def wait_for(self, num_shards: int, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            table = self.lookup(num_shards)
            if all(table[s] for s in range(num_shards)):
                return table
            time.sleep(0.2)
        raise TimeoutError(
            f"registry at {self.path}: not all {num_shards} shards present"
        )
