"""Compact-bytes codecs: the ONE seam every shrunk byte path rides.

Three independent codec families live here, because they shrink three
different kinds of bytes:

- **Stream codecs** (`compress`/`decompress`): lossless frame-level
  compression for replication and DR streams — `wal_ship` batches,
  snapshot bootstrap payloads, backup archives. `"id"` is the identity
  (and the degrade target against pre-codec peers), `"zlib"` is always
  available, `"zstd"` only when the interpreter already ships it (the
  container never pip-installs; the registry gates on importability).
  Every compressed blob is framed `[u8 version][u32 raw_len]
  [u32 raw_crc32][payload]` so a flipped byte surfaces as a typed
  ValueError — from the header check, the decompressor, the length
  check, or the crc — never as silently-wrong bytes.

- **Integer delta+varint** (`encode_u64_delta`/`decode_u64_delta`):
  exact compaction for neighbor-id planes (`full_nb`, `edges_by_rows`
  hub pages). First-difference zigzag + LEB128 varint over the u64 id
  stream: sorted neighbor lists collapse to ~1-2 bytes/id, and because
  zigzag handles negative deltas the roundtrip is bit-identical for ANY
  order — sortedness is an efficiency assumption, never a correctness
  one. Same corruption framing as the stream codecs.

- **Float quantizers** (`quantize`/`dequantize`): the ONLY lossy path
  in the repo, for dense-feature wire payloads and HBM feature pages.
  `"bf16"` truncates mantissas (rel error <= 2^-8, PARITY.md budget);
  `"int8"` is per-row affine (uint8 + per-row scale/zero-point, abs
  error <= (rowmax-rowmin)/254). `"f32"` is the exact default —
  fp32 bit-parity is relinquished only when a caller opts in.

Knobs:
  EULER_TPU_WIRE_CODEC   — stream codec for negotiated wire paths
                           (default "zlib"; "id" disables)
  EULER_TPU_PAGE_DTYPE   — feature page/wire dtype ("f32" default,
                           "bf16", "int8")
"""

from __future__ import annotations

import os
import struct
import zlib

import numpy as np

try:  # ml_dtypes ships with jax; bf16 wire arrays already ride dtype
    # code 8 in graph/format.py
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None

try:  # zstd is OPTIONAL: never installed, only detected
    import zstandard as _zstd  # type: ignore
except ImportError:  # pragma: no cover - container has no zstd wheel
    _zstd = None

# blob framing: version byte, raw length, crc32 of the RAW bytes — the
# decompress path re-checks all three so malformed input is a typed
# error, never silently-wrong bytes
_FRAME = struct.Struct("<BII")
_FRAME_VERSION = 1

IDENTITY = "id"


def wire_codec() -> str:
    """The negotiated stream codec this process OFFERS on the wire
    (EULER_TPU_WIRE_CODEC; peers that don't speak it degrade to "id")."""
    name = os.environ.get("EULER_TPU_WIRE_CODEC", "zlib").strip() or "id"
    return name if name in available_codecs() else IDENTITY


def page_dtype() -> str:
    """EULER_TPU_PAGE_DTYPE: feature page/wire quantization ("f32"
    exact default / "bf16" / "int8")."""
    name = os.environ.get("EULER_TPU_PAGE_DTYPE", "f32").strip() or "f32"
    if name not in ("f32", "bf16", "int8"):
        raise ValueError(
            f"EULER_TPU_PAGE_DTYPE={name!r}: expected f32, bf16, or int8"
        )
    return name


def available_codecs() -> tuple[str, ...]:
    out = [IDENTITY, "zlib"]
    if _zstd is not None:  # pragma: no cover - optional dependency
        out.append("zstd")
    return tuple(out)


def compress(name: str, data: bytes) -> bytes:
    """`data` -> framed compressed blob under codec `name` ("id" frames
    too, so the decode side always has the crc to check)."""
    data = bytes(data)
    head = _FRAME.pack(
        _FRAME_VERSION, len(data), zlib.crc32(data) & 0xFFFFFFFF
    )
    if name == IDENTITY:
        return head + data
    if name == "zlib":
        return head + zlib.compress(data, 1)
    if name == "zstd" and _zstd is not None:  # pragma: no cover - optional
        return head + _zstd.ZstdCompressor(level=1).compress(data)
    raise ValueError(f"unknown stream codec {name!r}")


def decompress(name: str, blob: bytes) -> bytes:
    """Framed blob -> raw bytes; ANY damage (bad frame, bad stream,
    length or crc mismatch) raises ValueError."""
    blob = bytes(blob)
    if len(blob) < _FRAME.size:
        raise ValueError(
            f"codec {name!r}: blob shorter than its frame header"
        )
    ver, raw_len, raw_crc = _FRAME.unpack_from(blob, 0)
    if ver != _FRAME_VERSION:
        raise ValueError(f"codec {name!r}: unknown frame version {ver}")
    body = blob[_FRAME.size:]
    if name == IDENTITY:
        raw = body
    elif name == "zlib":
        try:
            raw = zlib.decompress(body)
        except zlib.error as e:
            raise ValueError(f"codec zlib: corrupt stream ({e})") from e
    elif name == "zstd" and _zstd is not None:  # pragma: no cover
        try:
            raw = _zstd.ZstdDecompressor().decompress(
                body, max_output_size=max(raw_len, 1)
            )
        except _zstd.ZstdError as e:
            raise ValueError(f"codec zstd: corrupt stream ({e})") from e
    else:
        raise ValueError(f"unknown stream codec {name!r}")
    if len(raw) != raw_len:
        raise ValueError(
            f"codec {name!r}: decoded {len(raw)} bytes, frame declared"
            f" {raw_len}"
        )
    if zlib.crc32(raw) & 0xFFFFFFFF != raw_crc:
        raise ValueError(f"codec {name!r}: decoded bytes fail frame crc")
    return raw


# ---------------------------------------------------------------------------
# exact integer delta + varint (neighbor-id planes)
# ---------------------------------------------------------------------------


def _zigzag(d: np.ndarray) -> np.ndarray:
    # signed first differences -> unsigned, small-magnitude-small codes
    d = d.astype(np.int64)
    return ((d << 1) ^ (d >> 63)).astype(np.uint64)


_VARINT_MAX_BYTES = 10  # ceil(64 / 7)


def _leb128_encode(vals: np.ndarray) -> bytes:
    """Vectorized LEB128 over a u64 array: byte-plane construction in
    numpy, no per-value Python loop — these run on server pool workers
    under the GIL, so a scalar loop over a padded plane of tens of
    thousands of ids would serialize the whole pool."""
    shifts = np.uint64(7) * np.arange(_VARINT_MAX_BYTES, dtype=np.uint64)
    groups = (vals[:, None] >> shifts[None, :]) & np.uint64(0x7F)
    nb = np.ones(vals.size, np.int64)
    for k in range(1, _VARINT_MAX_BYTES):
        nb += vals >= (np.uint64(1) << np.uint64(7 * k))
    cols = np.arange(_VARINT_MAX_BYTES, dtype=np.int64)[None, :]
    emit = cols < nb[:, None]
    cont = cols < (nb[:, None] - 1)
    mat = (groups | (cont.astype(np.uint64) << np.uint64(7))).astype(
        np.uint8
    )
    # row-major selection = per value, little-endian 7-bit groups
    return mat[emit].tobytes()


def _leb128_decode(payload: np.ndarray, count: int) -> np.ndarray:
    """Vectorized inverse of _leb128_encode for exactly `count` values;
    truncation, >64-bit values, and trailing bytes raise ValueError."""
    term = np.flatnonzero((payload & np.uint8(0x80)) == 0)
    if term.size < count:
        raise ValueError(
            f"varint block truncated at value {term.size}/{count}"
        )
    ends = term[:count]
    last = int(ends[-1])
    if last != payload.size - 1:
        raise ValueError(
            f"varint block has {payload.size - 1 - last} trailing bytes"
            f" after {count} values"
        )
    starts = np.empty(count, np.int64)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    lens = ends - starts + 1
    if int(lens.max()) > _VARINT_MAX_BYTES:
        raise ValueError("varint value overruns 64 bits")
    wide = lens == _VARINT_MAX_BYTES
    # a 10-byte varint's terminal group may only carry bit 63
    if wide.any() and int(payload[ends[wide]].max()) > 1:
        raise ValueError("varint value overruns 64 bits")
    pos_in = np.arange(payload.size, dtype=np.int64) - starts[
        np.repeat(np.arange(count), lens)
    ]
    contrib = (payload.astype(np.uint64) & np.uint64(0x7F)) << (
        np.uint64(7) * pos_in.astype(np.uint64)
    )
    return np.bitwise_or.reduceat(contrib, starts)


def encode_u64_delta(arr) -> bytes:
    """u64 array -> framed zigzag-delta LEB128 varint bytes. Exact for
    ANY value order (zigzag absorbs negative deltas); sorted runs are
    where the bytes shrink. Frame carries count + crc of the raw ids so
    decode can type-check damage."""
    arr = np.ascontiguousarray(arr, dtype=np.uint64)
    flat = arr.reshape(-1)
    raw = flat.tobytes()
    head = struct.pack(
        "<BQI", _FRAME_VERSION, flat.size, zlib.crc32(raw) & 0xFFFFFFFF
    )
    if flat.size == 0:
        return head
    # first value verbatim-varint; the rest zigzag first differences.
    # int64 wraparound on the diff is fine: zigzag/unzigzag is a
    # bijection on the 64-bit ring, so decode adds the same wrapped
    # delta back.
    vals = np.empty(flat.size, np.uint64)
    vals[0] = flat[0]
    vals[1:] = _zigzag(
        (flat[1:].astype(np.int64) - flat[:-1].astype(np.int64))
    )
    return head + _leb128_encode(vals)


def decode_u64_delta(blob) -> np.ndarray:
    """Inverse of encode_u64_delta; malformed input (truncated varint,
    trailing garbage, count/crc mismatch) raises ValueError."""
    blob = bytes(blob)
    head = struct.Struct("<BQI")
    if len(blob) < head.size:
        raise ValueError("varint block shorter than its header")
    ver, count, crc = head.unpack_from(blob, 0)
    if ver != _FRAME_VERSION:
        raise ValueError(f"varint block: unknown version {ver}")
    payload = np.frombuffer(blob, np.uint8, offset=head.size)
    # every value takes >= 1 byte: a corrupt count cannot be allowed to
    # size the allocation (a flipped header byte would ask for TiB)
    if count > payload.size:
        raise ValueError(
            f"varint block declares {count} values but carries only"
            f" {payload.size} payload bytes"
        )
    if count == 0:
        if payload.size:
            raise ValueError(
                f"varint block has {payload.size} trailing bytes after"
                " 0 values"
            )
        vals = np.empty(0, np.uint64)
    else:
        vals = _leb128_decode(payload, count)
    if count:
        # un-zigzag the delta tail, then prefix-sum on the u64 ring
        d = vals[1:]
        sd = ((d >> np.uint64(1)) ^ (-(d & np.uint64(1)).astype(np.int64))
              .astype(np.uint64))
        vals[1:] = sd
        vals = np.cumsum(vals.astype(np.uint64), dtype=np.uint64)
    raw = vals.tobytes()
    if zlib.crc32(raw) & 0xFFFFFFFF != crc:
        raise ValueError("varint block decodes to bytes failing its crc")
    return vals


# ---------------------------------------------------------------------------
# float quantizers (the one lossy path; budgets pinned in PARITY.md)
# ---------------------------------------------------------------------------


def _row_range(vals: np.ndarray):
    # per-row (min, max); zero-width rows quantize exactly to their lo
    if vals.shape[1] == 0:
        zero = np.zeros(len(vals), np.float32)
        return zero, zero
    return vals.min(axis=1), vals.max(axis=1)


def quantize(kind: str, vals: np.ndarray):
    """f32 [n, F] -> list of wire arrays for `kind`:
    "f32" -> [vals] (exact); "bf16" -> [bf16 vals]; "int8" ->
    [uint8 q, f32 scale [n], f32 zero [n]] per-row affine."""
    vals = np.ascontiguousarray(vals, np.float32)
    if kind == "f32":
        return [vals]
    if kind == "bf16":
        if _BF16 is None:  # pragma: no cover - ml_dtypes ships with jax
            raise ValueError("bf16 pages need ml_dtypes (ships with jax)")
        return [vals.astype(_BF16)]
    if kind == "int8":
        if vals.ndim != 2:
            vals = vals.reshape(len(vals), -1)
        # true per-row min/max: widening the range to include 0 (an
        # `initial=` clamp) would blow the documented (rowmax-rowmin)/254
        # PARITY budget for rows living far from the origin
        lo, hi = _row_range(vals)
        scale = np.maximum((hi - lo) / 255.0, np.float32(1e-30)).astype(
            np.float32
        )
        q = np.clip(
            np.rint((vals - lo[:, None]) / scale[:, None]), 0, 255
        ).astype(np.uint8)
        return [q, scale, lo.astype(np.float32)]
    raise ValueError(f"unknown page dtype {kind!r}")


def dequantize(kind: str, parts) -> np.ndarray:
    """Inverse of quantize back to f32 (exact for f32, budgeted for
    bf16/int8). Malformed part lists raise ValueError."""
    if kind == "f32":
        (vals,) = parts
        return np.ascontiguousarray(vals, np.float32)
    if kind == "bf16":
        (vals,) = parts
        return np.asarray(vals).astype(np.float32)
    if kind == "int8":
        if len(parts) != 3:
            raise ValueError(
                f"int8 payload needs [q, scale, zero], got {len(parts)}"
                " arrays"
            )
        q, scale, zero = parts
        q = np.asarray(q)
        if q.dtype != np.uint8:
            raise ValueError(f"int8 payload q plane has dtype {q.dtype}")
        return (
            q.astype(np.float32) * np.asarray(scale, np.float32)[:, None]
            + np.asarray(zero, np.float32)[:, None]
        )
    raise ValueError(f"unknown page dtype {kind!r}")


def quant_error_budget(kind: str, vals: np.ndarray) -> np.ndarray:
    """Per-row max-abs-error budget the PARITY.md contract pins: the
    tests assert |dequant(quant(x)) - x| stays under this, elementwise."""
    vals = np.ascontiguousarray(vals, np.float32)
    if vals.ndim != 2:
        vals = vals.reshape(len(vals), -1)
    if kind == "f32":
        return np.zeros(len(vals), np.float32)
    if kind == "bf16":
        # one bf16 rounding: rel error <= 2^-9 of the magnitude; budget
        # 2^-8 leaves headroom for subnormal edges
        return np.abs(vals).max(axis=1, initial=0.0) * np.float32(2**-8)
    if kind == "int8":
        lo, hi = _row_range(vals)
        return ((hi - lo) / 254.0).astype(np.float32)
    raise ValueError(f"unknown page dtype {kind!r}")
