"""Shard supervisor — spawn, monitor, and restart durable graph shards.

PR 4's chaos discipline made a dead shard SURVIVABLE (readers fail over,
retries stop, errors stay typed) but never brought it back: a `kill -9`'d
shard stayed dead forever. With the WAL + snapshot layer (graph/wal.py)
a restart is cheap and LOSSLESS, so the supervisor closes the loop:

- `start()` spawns one `python -m euler_tpu.distributed.service` process
  per shard with a per-shard `--wal-dir`. Ports are FIXED by default
  (clients holding static replica lists get the restart back on the
  address they already know); `dynamic_ports=True` drops that
  assumption — every (re)spawn binds a fresh OS-assigned port and
  clients discover it through the registry heartbeat (connect()'s
  watch), the same contract replica groups already use. `cluster()`
  always reports the LIVE port map.
- A monitor thread polls the children; an exited shard (crash, OOM-kill,
  `kill -9`) is respawned with exponential backoff, bounded by
  `max_restarts` within the backoff window (a healthy stretch of uptime
  resets the counter — crash loops stop, one-off crashes do not).
- The restarted process recovers from its WAL dir (newest snapshot +
  log-suffix replay — bit-identical to the pre-crash published epoch),
  re-registers its heartbeat, and resumes serving. Clients un-quarantine
  on their normal timed revival and re-run the ReadCache epoch handshake
  (transport faults void `_epoch_checked`), so readers resume without a
  restart on their side.

CLI (start a whole durable cluster under supervision):

    python -m euler_tpu.distributed.supervisor --data DIR --shards 2 \
        --registry /path/reg --wal-root /path/wal
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from euler_tpu.distributed import wire


def _free_port(host: str) -> int:
    """An OS-assigned free port (released immediately — the standard
    pick-then-bind race, narrowed by SO_REUSEADDR on the server side)."""
    s = socket.socket()
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _ping(host: str, port: int, timeout_s: float = 1.0):
    """One raw ping RPC; the shard index on success, None otherwise."""
    try:
        with socket.create_connection((host, port), timeout=timeout_s) as s:
            s.settimeout(timeout_s)
            wire.send_frame(s, wire.encode("ping", []))
            payload = wire.read_frame(s)
            if payload is None:
                return None
            status, result = wire.decode(payload)
            if status == "ok":
                return int(result[0])
    except (OSError, ValueError):
        return None
    return None


class _Shard:
    """Supervision state for one shard process."""

    def __init__(self, shard: int, port: int, wal_dir: str):
        self.shard = shard
        self.port = port
        self.wal_dir = wal_dir
        self.proc: subprocess.Popen | None = None
        self.restarts = 0
        self.window_restarts = 0  # restarts inside the current crash loop
        self.started_at = 0.0
        self.next_spawn_at = 0.0  # backoff gate
        self.failed = False  # crash loop exceeded max_restarts
        self.log_path: str | None = None


class ShardSupervisor:
    """Process supervisor for a durable multi-shard graph service."""

    def __init__(
        self,
        data_dir: str,
        num_shards: int,
        registry_path: str,
        wal_root: str,
        host: str = "127.0.0.1",
        ports: list[int] | None = None,
        max_restarts: int = 8,
        backoff_s: float = 0.25,
        backoff_max_s: float = 5.0,
        healthy_uptime_s: float = 30.0,
        poll_s: float = 0.1,
        native: bool = False,
        env: dict | None = None,
        scrub_s: float | None = None,
        dynamic_ports: bool = False,
    ):
        self.data_dir = data_dir
        self.num_shards = int(num_shards)
        self.registry_path = registry_path
        self.wal_root = wal_root
        self.host = host
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.healthy_uptime_s = float(healthy_uptime_s)
        self.poll_s = float(poll_s)
        self.native = native
        self.env = dict(env) if env else None
        # at-rest integrity cadence for every child (EULER_TPU_SCRUB_S;
        # None inherits the supervisor's environment, 0 disables)
        self.scrub_s = scrub_s
        # dynamic_ports drops the fixed-port assumption: every (re)spawn
        # binds a fresh OS-assigned port and the registry heartbeat is
        # how clients (and cluster()) learn the live address — required
        # for elastic reshard flows where shard counts change and no
        # static replica list can stay valid anyway
        if dynamic_ports and ports is not None:
            raise ValueError("dynamic_ports is incompatible with ports=")
        self.dynamic_ports = bool(dynamic_ports)
        os.makedirs(wal_root, exist_ok=True)
        if dynamic_ports:
            ports = [0] * self.num_shards  # allocated per spawn
        else:
            ports = (
                list(ports)
                if ports is not None
                else [_free_port(host) for _ in range(self.num_shards)]
            )
        if len(ports) != self.num_shards:
            raise ValueError("need one port per shard")
        self.shards = [
            _Shard(i, int(ports[i]), os.path.join(wal_root, f"shard_{i}"))
            for i in range(self.num_shards)
        ]
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._monitor: threading.Thread | None = None

    # -- process control -------------------------------------------------

    def _spawn(self, sh: _Shard) -> None:
        # callers (start(), the monitor loop) hold self._lock across this
        os.makedirs(sh.wal_dir, exist_ok=True)
        if self.dynamic_ports:
            # fresh port every spawn — the registry heartbeat (not a
            # static list) is the contract clients route by
            # graftlint: disable=lock-unguarded-write -- every caller holds self._lock around _spawn
            sh.port = _free_port(self.host)
        cmd = [
            sys.executable, "-m", "euler_tpu.distributed.service",
            "--data", self.data_dir,
            "--shard", str(sh.shard),
            "--host", self.host,
            "--port", str(sh.port),
            "--registry", self.registry_path,
            "--wal-dir", sh.wal_dir,
        ]
        if not self.native:
            cmd.append("--no-native")
        sh.log_path = os.path.join(self.wal_root, f"shard_{sh.shard}.log")
        env = dict(os.environ if self.env is None else self.env)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.scrub_s is not None:
            env["EULER_TPU_SCRUB_S"] = str(self.scrub_s)
        log = open(sh.log_path, "ab")
        try:
            # its own session: a Ctrl-C to the supervisor's group must
            # not take the children down uncontrolled — stop() drains
            # graftlint: disable=lock-unguarded-write -- every caller holds self._lock around _spawn
            sh.proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT, env=env,
                start_new_session=True,
            )
        finally:
            log.close()
        # graftlint: disable=lock-unguarded-write -- every caller holds self._lock around _spawn
        sh.started_at = time.monotonic()

    def start(self) -> "ShardSupervisor":
        # under the lock: _spawn writes per-shard state the monitor and
        # stats() read under it (sh.proc / sh.started_at)
        with self._lock:
            for sh in self.shards:
                self._spawn(sh)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="shard-supervisor"
        )
        self._monitor.start()
        return self

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                for sh in self.shards:
                    p = sh.proc
                    if sh.failed or p is None:
                        continue
                    if p.poll() is None:
                        # a healthy stretch closes the crash-loop window
                        if (
                            sh.window_restarts
                            and now - sh.started_at > self.healthy_uptime_s
                        ):
                            sh.window_restarts = 0
                        continue
                    if sh.next_spawn_at == 0.0:
                        # just observed the exit: schedule the respawn
                        sh.window_restarts += 1
                        if sh.window_restarts > self.max_restarts:
                            sh.failed = True
                            print(
                                f"# supervisor: shard {sh.shard} crash-"
                                f"looped past max_restarts="
                                f"{self.max_restarts}; giving up on it"
                                f" (exit {p.returncode})",
                                file=sys.stderr, flush=True,
                            )
                            continue
                        pause = min(
                            self.backoff_s * 2 ** (sh.window_restarts - 1),
                            self.backoff_max_s,
                        )
                        sh.next_spawn_at = now + pause
                    elif now >= sh.next_spawn_at:
                        sh.next_spawn_at = 0.0
                        sh.restarts += 1
                        print(
                            f"# supervisor: restarting shard {sh.shard}"
                            f" (exit {p.returncode},"
                            f" restart #{sh.restarts})",
                            file=sys.stderr, flush=True,
                        )
                        self._spawn(sh)
            self._stop.wait(self.poll_s)

    # -- operator surface ------------------------------------------------

    def kill(self, shard: int, sig: int = signal.SIGKILL) -> None:
        """Send `sig` to one shard process (chaos harness + tests: the
        seeded `kill -9` the recovery proof injects)."""
        with self._lock:
            p = self.shards[shard].proc
        if p is not None and p.poll() is None:
            os.kill(p.pid, sig)

    def wait_healthy(self, timeout_s: float = 60.0) -> bool:
        """Block until EVERY shard answers ping on its fixed port (and
        with it has re-registered its heartbeat). False on timeout."""
        deadline = time.monotonic() + timeout_s
        pending = set(range(self.num_shards))
        while pending and time.monotonic() < deadline:
            for i in sorted(pending):
                sh = self.shards[i]
                if _ping(self.host, sh.port) == sh.shard:
                    pending.discard(i)
            if pending:
                time.sleep(0.1)
        return not pending

    def stats(self) -> dict:
        with self._lock:
            return {
                "shards": {
                    sh.shard: {
                        "port": sh.port,
                        "alive": bool(
                            sh.proc is not None and sh.proc.poll() is None
                        ),
                        "restarts": sh.restarts,
                        "failed": sh.failed,
                        "pid": getattr(sh.proc, "pid", None),
                    }
                    for sh in self.shards
                },
            }

    def cluster(self) -> dict[int, list[tuple[str, int]]]:
        """LIVE cluster spec for `distributed.connect(cluster=...)`.
        Fixed-port mode: stable across restarts. dynamic_ports mode: the
        map as of NOW — a respawn moves ports, so long-lived clients
        should connect through the registry instead and treat this as a
        point-in-time snapshot (registry heartbeats confirm it)."""
        with self._lock:
            return {
                sh.shard: [(self.host, sh.port)] for sh in self.shards
            }

    def stop(self, term_timeout_s: float = 10.0) -> None:
        """Stop supervising, then the children: SIGTERM (the service
        drains: deregister → finish in-flight → exit), SIGKILL
        stragglers."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        with self._lock:
            procs = [sh.proc for sh in self.shards if sh.proc is not None]
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + term_timeout_s
        for p in procs:
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                    p.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass


class _Member:
    """Supervision state for one replica process of one shard group."""

    def __init__(self, shard: int, rid: int, wal_dir: str):
        self.shard = shard
        self.rid = rid
        self.wal_dir = wal_dir
        self.port = 0  # fresh port at every (re)spawn
        self.proc: subprocess.Popen | None = None
        self.restarts = 0
        self.window_restarts = 0
        self.started_at = 0.0
        self.next_spawn_at = 0.0
        self.failed = False
        self.log_path: str | None = None


class ReplicaGroupSupervisor:
    """Supervise R replicas per shard as lease-coordinated groups.

    Where ShardSupervisor restarts ONE process per shard on a FIXED
    port (clients hold static replica lists), this spawns `replication`
    processes per shard, each a member of the shard's replica group
    (`--replica i --replicas R`): one holds the lease and serves
    writes, the rest tail its WAL. A respawned member comes back on a
    FRESH port — clients discover it through the registry topology
    watch (connect()'s `sync_replicas`), so the fixed-port constraint
    is gone. Per-member WAL dirs live at
    `wal_root/shard_<s>/replica_<r>`; a restarted member recovers from
    its own snapshot + log and rejoins the group (bootstrapping over
    the wire only when its log diverged or fell behind the primary's
    retained base).
    """

    def __init__(
        self,
        data_dir: str,
        num_shards: int,
        registry_path: str,
        wal_root: str,
        replication: int = 2,
        host: str = "127.0.0.1",
        lease_ttl: float | None = None,
        max_restarts: int = 8,
        backoff_s: float = 0.25,
        backoff_max_s: float = 5.0,
        healthy_uptime_s: float = 30.0,
        poll_s: float = 0.1,
        native: bool = False,
        env: dict | None = None,
        scrub_s: float | None = None,
    ):
        self.data_dir = data_dir
        self.num_shards = int(num_shards)
        self.registry_path = registry_path
        self.wal_root = wal_root
        self.replication = int(replication)
        if self.replication < 1:
            raise ValueError("replication must be >= 1")
        self.host = host
        self.lease_ttl = lease_ttl
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.healthy_uptime_s = float(healthy_uptime_s)
        self.poll_s = float(poll_s)
        self.native = native
        self.env = dict(env) if env else None
        # integrity-scrub cadence forwarded to children as EULER_TPU_SCRUB_S
        self.scrub_s = scrub_s
        os.makedirs(wal_root, exist_ok=True)
        self.members = [
            _Member(
                s, r,
                os.path.join(wal_root, f"shard_{s}", f"replica_{r}"),
            )
            for s in range(self.num_shards)
            for r in range(self.replication)
        ]
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._monitor: threading.Thread | None = None

    def _registry(self):
        from euler_tpu.distributed.rendezvous import make_registry

        return make_registry(self.registry_path)

    # -- process control -------------------------------------------------

    def _spawn(self, m: _Member) -> None:
        # callers hold self._lock (same discipline as ShardSupervisor)
        os.makedirs(m.wal_dir, exist_ok=True)
        # graftlint: disable=lock-unguarded-write -- every caller holds self._lock around _spawn
        m.port = _free_port(self.host)
        cmd = [
            sys.executable, "-m", "euler_tpu.distributed.service",
            "--data", self.data_dir,
            "--shard", str(m.shard),
            "--host", self.host,
            "--port", str(m.port),
            "--registry", self.registry_path,
            "--wal-dir", m.wal_dir,
            "--replica", str(m.rid),
            "--replicas", str(self.replication),
        ]
        if self.lease_ttl is not None:
            cmd += ["--lease-ttl", str(self.lease_ttl)]
        if not self.native:
            cmd.append("--no-native")
        m.log_path = os.path.join(
            self.wal_root, f"shard_{m.shard}_r{m.rid}.log"
        )
        env = dict(os.environ if self.env is None else self.env)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.scrub_s is not None:
            env["EULER_TPU_SCRUB_S"] = str(self.scrub_s)
        log = open(m.log_path, "ab")
        try:
            # graftlint: disable=lock-unguarded-write -- every caller holds self._lock around _spawn
            m.proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT, env=env,
                start_new_session=True,
            )
        finally:
            log.close()
        # graftlint: disable=lock-unguarded-write -- every caller holds self._lock around _spawn
        m.started_at = time.monotonic()

    def start(self) -> "ReplicaGroupSupervisor":
        with self._lock:
            for m in self.members:
                self._spawn(m)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="replica-group-supervisor",
        )
        self._monitor.start()
        return self

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                for m in self.members:
                    p = m.proc
                    if m.failed or p is None:
                        continue
                    if p.poll() is None:
                        if (
                            m.window_restarts
                            and now - m.started_at > self.healthy_uptime_s
                        ):
                            m.window_restarts = 0
                        continue
                    if m.next_spawn_at == 0.0:
                        m.window_restarts += 1
                        if m.window_restarts > self.max_restarts:
                            m.failed = True
                            print(
                                f"# supervisor: shard {m.shard} replica"
                                f" {m.rid} crash-looped past max_restarts"
                                f"={self.max_restarts}; giving up on it"
                                f" (exit {p.returncode})",
                                file=sys.stderr, flush=True,
                            )
                            continue
                        pause = min(
                            self.backoff_s * 2 ** (m.window_restarts - 1),
                            self.backoff_max_s,
                        )
                        m.next_spawn_at = now + pause
                    elif now >= m.next_spawn_at:
                        m.next_spawn_at = 0.0
                        m.restarts += 1
                        print(
                            f"# supervisor: restarting shard {m.shard}"
                            f" replica {m.rid} (exit {p.returncode},"
                            f" restart #{m.restarts})",
                            file=sys.stderr, flush=True,
                        )
                        self._spawn(m)
            self._stop.wait(self.poll_s)

    # -- operator surface ------------------------------------------------

    def member(self, shard: int, rid: int) -> _Member:
        for m in self.members:
            if m.shard == shard and m.rid == rid:
                return m
        raise KeyError(f"no member shard={shard} replica={rid}")

    def kill(self, shard: int, rid: int, sig: int = signal.SIGKILL) -> None:
        """Send `sig` to one replica process (the chaos harness's
        seeded `kill -9`)."""
        with self._lock:
            p = self.member(shard, rid).proc
        if p is not None and p.poll() is None:
            os.kill(p.pid, sig)

    def primary_of(self, shard: int) -> int | None:
        """Replica id of the shard's current lease holder, or None.
        Matches the lease holder's `host:port` against live member
        processes — the port changes across respawns, so this is read
        fresh every call."""
        lease = self._registry().observe(f"shard_{shard}")
        if lease is None or lease["expires_in"] <= 0:
            return None
        holder = str(lease["holder"])
        with self._lock:
            for m in self.members:
                if (
                    m.shard == shard
                    and f"{self.host}:{m.port}" == holder
                    and m.proc is not None
                    and m.proc.poll() is None
                ):
                    return m.rid
        return None

    def kill_primary(
        self, shard: int, sig: int = signal.SIGKILL, timeout_s: float = 30.0
    ) -> int:
        """kill -9 the shard's CURRENT primary (whichever replica holds
        the lease right now); returns the replica id killed."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            rid = self.primary_of(shard)
            if rid is not None:
                self.kill(shard, rid, sig)
                return rid
            time.sleep(0.1)
        raise TimeoutError(f"shard {shard}: no live primary to kill")

    def wait_healthy(self, timeout_s: float = 60.0) -> bool:
        """Block until every shard group has ALL its replicas answering
        ping AND a live lease (a primary elected). False on timeout."""
        deadline = time.monotonic() + timeout_s
        reg = self._registry()
        while time.monotonic() < deadline:
            with self._lock:
                ports = {
                    (m.shard, m.rid): m.port
                    for m in self.members
                    if m.proc is not None and m.proc.poll() is None
                }
            ok = len(ports) == len(self.members) and all(
                _ping(self.host, port) == shard
                for (shard, _r), port in ports.items()
            )
            if ok:
                for s in range(self.num_shards):
                    lease = reg.observe(f"shard_{s}")
                    if lease is None or lease["expires_in"] <= 0:
                        ok = False
                        break
            if ok:
                return True
            time.sleep(0.1)
        return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "members": {
                    f"{m.shard}/{m.rid}": {
                        "port": m.port,
                        "alive": bool(
                            m.proc is not None and m.proc.poll() is None
                        ),
                        "restarts": m.restarts,
                        "failed": m.failed,
                        "pid": getattr(m.proc, "pid", None),
                    }
                    for m in self.members
                },
            }

    def stop(self, term_timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        with self._lock:
            procs = [m.proc for m in self.members if m.proc is not None]
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + term_timeout_s
        for p in procs:
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                    p.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass


class TrainerSupervisor:
    """Supervise ONE durable trainer process (`tools/train.py`).

    The trainer side of the shard story above: exit 0 means the run
    reached its target step — done, no respawn. ANY other exit (crash,
    OOM-kill, `kill -9`) respawns the trainer with `--resume` appended,
    under the same exponential backoff + crash-loop cap as shards; the
    respawned process restores the newest COMPLETE retained checkpoint
    (euler_tpu/training/checkpoint.py) and continues bit-exactly, so a
    trainer kill under live traffic is a non-event. Exit 3 (SIGTERM
    preemption drain) is treated as done-for-now and NOT respawned —
    preemption is an operator/scheduler decision, not a crash."""

    DONE_CODES = (0, 3)

    def __init__(
        self,
        train_args: list[str],
        log_path: str,
        max_restarts: int = 8,
        backoff_s: float = 0.25,
        backoff_max_s: float = 5.0,
        healthy_uptime_s: float = 30.0,
        poll_s: float = 0.1,
        env: dict | None = None,
    ):
        self.train_args = list(train_args)
        self.log_path = log_path
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.healthy_uptime_s = float(healthy_uptime_s)
        self.poll_s = float(poll_s)
        self.env = dict(env) if env else None
        self.proc: subprocess.Popen | None = None
        self.restarts = 0
        self.exit_code: int | None = None
        self.failed = False  # crash loop exceeded max_restarts
        self._window_restarts = 0
        self._started_at = 0.0
        self._next_spawn_at = 0.0
        self._stop = threading.Event()
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._monitor: threading.Thread | None = None

    def _spawn(self, resume: bool) -> None:
        # callers hold self._lock (same discipline as _Shard._spawn)
        argv = list(self.train_args)
        if resume and "--resume" not in argv:
            argv.append("--resume")
        cmd = [sys.executable, "-m", "euler_tpu.tools.train", *argv]
        env = dict(os.environ if self.env is None else self.env)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log = open(self.log_path, "ab")
        try:
            # graftlint: disable=lock-unguarded-write -- callers hold self._lock around _spawn
            self.proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT, env=env,
                start_new_session=True,
            )
        finally:
            log.close()
        # graftlint: disable=lock-unguarded-write -- callers hold self._lock around _spawn
        self._started_at = time.monotonic()

    def start(self, resume: bool = False) -> "TrainerSupervisor":
        with self._lock:
            self._spawn(resume)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="trainer-supervisor"
        )
        self._monitor.start()
        return self

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                p = self.proc
                if self.failed or self._done.is_set() or p is None:
                    return
                rc = p.poll()
                if rc is None:
                    if (
                        self._window_restarts
                        and now - self._started_at > self.healthy_uptime_s
                    ):
                        self._window_restarts = 0
                elif rc in self.DONE_CODES:
                    self.exit_code = rc
                    self._done.set()
                    return
                elif self._next_spawn_at == 0.0:
                    self._window_restarts += 1
                    if self._window_restarts > self.max_restarts:
                        self.failed = True
                        self.exit_code = rc
                        print(
                            f"# supervisor: trainer crash-looped past "
                            f"max_restarts={self.max_restarts}; giving up"
                            f" (exit {rc})",
                            file=sys.stderr, flush=True,
                        )
                        self._done.set()
                        return
                    pause = min(
                        self.backoff_s * 2 ** (self._window_restarts - 1),
                        self.backoff_max_s,
                    )
                    self._next_spawn_at = now + pause
                elif now >= self._next_spawn_at:
                    self._next_spawn_at = 0.0
                    self.restarts += 1
                    print(
                        f"# supervisor: restarting trainer with --resume"
                        f" (exit {rc}, restart #{self.restarts})",
                        file=sys.stderr, flush=True,
                    )
                    self._spawn(resume=True)
            self._stop.wait(self.poll_s)

    def kill(self, sig: int = signal.SIGKILL) -> None:
        """Chaos entry point: the seeded `kill -9` the resume proof
        injects."""
        with self._lock:
            p = self.proc
        if p is not None and p.poll() is None:
            os.kill(p.pid, sig)

    def wait(self, timeout_s: float = 300.0) -> bool:
        """Block until the run completes (exit 0/3) or crash-loops out;
        True iff the trainer finished rather than failed."""
        if not self._done.wait(timeout_s):
            return False
        return not self.failed

    def stats(self) -> dict:
        with self._lock:
            return {
                "alive": bool(
                    self.proc is not None and self.proc.poll() is None
                ),
                "restarts": self.restarts,
                "failed": self.failed,
                "done": self._done.is_set(),
                "exit_code": self.exit_code,
                "pid": getattr(self.proc, "pid", None),
            }

    def stop(self, term_timeout_s: float = 10.0) -> None:
        """Stop supervising, then SIGTERM the trainer (it drains: final
        checkpoint flush, exit 3); SIGKILL a straggler."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        with self._lock:
            p = self.proc
        if p is None:
            return
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
        try:
            p.wait(timeout=term_timeout_s)
        except subprocess.TimeoutExpired:
            try:
                p.kill()
                p.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", required=True)
    ap.add_argument("--shards", type=int, required=True)
    ap.add_argument("--registry", required=True)
    ap.add_argument("--wal-root", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--ports", default=None,
                    help="comma-separated fixed ports (default: auto)")
    ap.add_argument("--dynamic-ports", action="store_true",
                    help="fresh OS-assigned port per (re)spawn; clients"
                         " route via the registry heartbeat")
    ap.add_argument("--max-restarts", type=int, default=8)
    ap.add_argument("--native", action="store_true")
    ap.add_argument("--replication", type=int, default=1,
                    help="replicas per shard; >1 supervises lease-"
                         "coordinated replica groups on dynamic ports")
    ap.add_argument("--lease-ttl", type=float, default=None)
    args = ap.parse_args(argv)
    ports = (
        [int(p) for p in args.ports.split(",")] if args.ports else None
    )
    if args.replication > 1:
        if ports is not None:
            raise SystemExit("--ports is incompatible with --replication"
                             " (replica groups respawn on fresh ports)")
        sup = ReplicaGroupSupervisor(
            args.data, args.shards, args.registry, args.wal_root,
            replication=args.replication, host=args.host,
            lease_ttl=args.lease_ttl, max_restarts=args.max_restarts,
            native=args.native,
        ).start()
    else:
        sup = ShardSupervisor(
            args.data, args.shards, args.registry, args.wal_root,
            host=args.host, ports=ports, max_restarts=args.max_restarts,
            native=args.native, dynamic_ports=args.dynamic_ports,
        ).start()
    healthy = sup.wait_healthy(timeout_s=120.0)
    print(json.dumps({"healthy": healthy, **sup.stats()}), flush=True)
    done = threading.Event()

    def _term(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _term)
    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
