"""TCP membership rendezvous — shared-filesystem-free registry.

The reference registers graph servers as ephemeral ZooKeeper znodes with a
session keep-alive and clients watch children for add/remove
(euler/common/zk_server_register.cc:96-161, zk_server_monitor.cc). The
shared-dir `Registry` covers single-host and NFS/GCS-fuse pods; real
multi-host TPU pods often share nothing, so this module serves the same
membership table from one TCP endpoint:

  server:  RendezvousServer(port)  — in-memory {(shard, host, port): ts},
           entries expire after `ttl` seconds without a heartbeat
           (ephemeral-znode parity). Run standalone via
           `python -m euler_tpu.distributed.rendezvous --port N`,
           or colocated with any shard service.
  client:  TcpRegistry("host:port") — same register()/lookup()/wait_for()
           surface as Registry, so service.py and client.py stay agnostic.

`make_registry(spec)` picks the backend: "tcp://host:port" → TcpRegistry,
anything else → shared-dir Registry. The rendezvous uses the same
length-prefixed wire frames as the graph service (distributed/wire.py), so
it inherits the fuzz-hardened framing.
"""

from __future__ import annotations

import argparse
import json
import socket
import threading
import time

from euler_tpu.distributed import wire


class RendezvousServer:
    """In-memory membership table served over TCP.

    Ops (one frame in, one frame out):
      reg   (shard, host, port, meta_json) → ("ok",)   upsert + heartbeat
      unreg (shard, host, port)            → ("ok",)   immediate removal
      lookup ()                            → (table_json,)  live entries
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 ttl: float = 10.0):
        self.ttl = ttl
        # (shard, host, port) → (last-heartbeat ts, meta_json)
        self._entries: dict[tuple[int, str, int], tuple[float, str]] = {}
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "RendezvousServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                frame = wire.read_frame(conn)
                if frame is None:
                    return
                try:
                    op, vals = wire.decode(frame)
                    reply = self._dispatch(op, vals)
                except Exception as e:  # malformed-frame containment
                    reply = wire.encode("err", [f"{type(e).__name__}: {e}"])
                wire.send_frame(conn, reply)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, op: str, vals: list) -> bytes:
        if op == "reg":
            shard, host, port = int(vals[0]), str(vals[1]), int(vals[2])
            meta_json = str(vals[3]) if len(vals) > 3 else "{}"
            with self._lock:
                self._entries[(shard, host, port)] = (time.time(), meta_json)
            return wire.encode("ok", [])
        if op == "unreg":
            shard, host, port = int(vals[0]), str(vals[1]), int(vals[2])
            with self._lock:
                self._entries.pop((shard, host, port), None)
            return wire.encode("ok", [])
        if op == "lookup":
            now = time.time()
            with self._lock:
                dead = [
                    k for k, (ts, _) in self._entries.items()
                    if now - ts > self.ttl
                ]
                for k in dead:
                    del self._entries[k]
                table = [
                    [s, h, p, self._entries[(s, h, p)][1]]
                    for (s, h, p) in sorted(self._entries)
                ]
            return wire.encode("table", [json.dumps(table)])
        return wire.encode("err", [f"unknown op {op!r}"])


class TcpRegistry:
    """Registry backed by a RendezvousServer endpoint.

    Same surface as registry.Registry: register() heartbeats in the
    background and returns a stop Event; lookup()/wait_for() read the
    live table. Connections are per-request (the rendezvous is low-QPS
    control plane; reconnects double as liveness probes)."""

    def __init__(self, address: str, ttl: float = 10.0,
                 timeout: float = 5.0):
        if address.startswith("tcp://"):
            address = address[len("tcp://"):]
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.ttl = ttl
        self.timeout = timeout

    def _call(self, op: str, vals: list) -> list:
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        ) as sock:
            wire.send_frame(sock, wire.encode(op, vals))
            frame = wire.read_frame(sock)
        if frame is None:
            raise ConnectionError("rendezvous closed connection")
        rop, rvals = wire.decode(frame)
        if rop == "err":
            raise RuntimeError(f"rendezvous error: {rvals[0]}")
        return rvals

    # -- server side -----------------------------------------------------

    def register(self, shard: int, host: str, port: int,
                 meta: dict | None = None):
        """Heartbeat `reg` until the returned Event is set, then `unreg`
        (ephemeral-znode + session keep-alive parity)."""
        stop = threading.Event()

        meta_json = json.dumps(meta or {})

        def beat():
            while not stop.is_set():
                try:
                    self._call("reg", [shard, host, port, meta_json])
                except (OSError, RuntimeError):
                    # rendezvous briefly away or replying err frames
                    # (e.g. mid-restart): keep beating — a dead heartbeat
                    # thread would silently expire a healthy shard
                    pass
                stop.wait(self.ttl / 3)
            try:
                self._call("unreg", [shard, host, port])
            except (OSError, RuntimeError):
                pass

        threading.Thread(target=beat, daemon=True).start()
        return stop

    # -- client side -----------------------------------------------------

    def lookup(self, num_shards: int) -> dict[int, list[tuple[str, int]]]:
        out: dict[int, list[tuple[str, int]]] = {
            s: [] for s in range(num_shards)
        }
        try:
            (table_json,) = self._call("lookup", [])
        except OSError:
            return out
        for s, h, p, *_meta in json.loads(table_json):
            if int(s) in out:
                out[int(s)].append((str(h), int(p)))
        return out

    def lookup_meta(self) -> dict[tuple[int, str, int], dict]:
        """Full live table including per-entry meta (the shared-dir
        Registry persists meta in its heartbeat files; this is the tcp://
        equivalent)."""
        (table_json,) = self._call("lookup", [])
        return {
            (int(s), str(h), int(p)): json.loads(m[0]) if m else {}
            for s, h, p, *m in json.loads(table_json)
        }

    def wait_for(self, num_shards: int, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            table = self.lookup(num_shards)
            if all(table[s] for s in range(num_shards)):
                return table
            time.sleep(0.2)
        raise TimeoutError(
            f"rendezvous at {self.host}:{self.port}: not all "
            f"{num_shards} shards present"
        )


def make_registry(spec: str, ttl: float = 10.0):
    """spec "tcp://host:port" → TcpRegistry; anything else → shared-dir
    Registry (the two deployment modes: bare TCP pods vs NFS/GCS pods)."""
    if spec.startswith("tcp://"):
        return TcpRegistry(spec, ttl=ttl)
    from euler_tpu.distributed.registry import Registry

    return Registry(spec, ttl=ttl)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="standalone membership rendezvous server"
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=10.0)
    args = ap.parse_args(argv)
    srv = RendezvousServer(args.host, args.port, ttl=args.ttl).start()
    print(f"rendezvous on {srv.address}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
