"""TCP membership rendezvous — shared-filesystem-free registry.

The reference registers graph servers as ephemeral ZooKeeper znodes with a
session keep-alive and clients watch children for add/remove
(euler/common/zk_server_register.cc:96-161, zk_server_monitor.cc). The
shared-dir `Registry` covers single-host and NFS/GCS-fuse pods; real
multi-host TPU pods often share nothing, so this module serves the same
membership table from one TCP endpoint:

  server:  RendezvousServer(port)  — in-memory {(shard, host, port): ts},
           entries expire after `ttl` seconds without a heartbeat
           (ephemeral-znode parity). Run standalone via
           `python -m euler_tpu.distributed.rendezvous --port N`,
           or colocated with any shard service.
  client:  TcpRegistry("host:port") — same register()/lookup()/wait_for()
           surface as Registry, so service.py and client.py stay agnostic.

`make_registry(spec)` picks the backend: "tcp://host:port" → TcpRegistry,
anything else → shared-dir Registry. The rendezvous uses the same
length-prefixed wire frames as the graph service (distributed/wire.py), so
it inherits the fuzz-hardened framing.
"""

from __future__ import annotations

import argparse
import json
import socket
import threading
import time

from euler_tpu.distributed import wire


class RendezvousServer:
    """In-memory membership table served over TCP.

    Ops (one frame in, one frame out):
      reg   (shard, host, port, meta_json) → ("ok",)   upsert + heartbeat
      unreg (shard, host, port)            → ("ok",)   immediate removal
      lookup ()                            → (table_json,)  live entries
      lease_acquire (group, holder, ttl, min_term, meta_json)
                                           → (lease_json|"null",)
      lease_renew (group, holder, term, ttl) → (ok_bool,)
      lease_observe (group)                → (lease_json|"null",)
      topo_set (num_shards, gen, epoch)    → ("ok",)   reshard cutover
      topo_get ()                          → (topo_json|"null",)

    Leases are the replication fencing primitive (PR 13): one
    term-numbered TTL'd lease per replica group, holder = the primary's
    "host:port". The table is in-memory — a rendezvous restart loses it —
    so `lease_acquire` takes a `min_term` floor: a primary re-asserting
    after a registry restart keeps its term instead of rewinding the
    fencing clock.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 ttl: float = 10.0):
        self.ttl = ttl
        # (shard, host, port) → (last-heartbeat ts, meta_json)
        self._entries: dict[tuple[int, str, int], tuple[float, str]] = {}
        # group → {"term", "holder", "expires", "meta"}
        self._leases: dict[str, dict] = {}
        # committed cluster topology (PR 19 resharding): {"num_shards",
        # "gen", "epoch"} or None. Entries carry their generation in
        # meta["gen"]; lookup filters to the committed gen, making
        # topo_set the atomic cutover flip (registry.py parity).
        self._topology: dict | None = None
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "RendezvousServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                frame = wire.read_frame(conn)
                if frame is None:
                    return
                try:
                    op, vals = wire.decode(frame)
                    reply = self._dispatch(op, vals)
                except Exception as e:  # malformed-frame containment
                    reply = wire.encode("err", [f"{type(e).__name__}: {e}"])
                wire.send_frame(conn, reply)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, op: str, vals: list) -> bytes:
        if op == "reg":
            shard, host, port = int(vals[0]), str(vals[1]), int(vals[2])
            meta_json = str(vals[3]) if len(vals) > 3 else "{}"
            with self._lock:
                self._entries[(shard, host, port)] = (time.time(), meta_json)
            return wire.encode("ok", [])
        if op == "unreg":
            shard, host, port = int(vals[0]), str(vals[1]), int(vals[2])
            with self._lock:
                self._entries.pop((shard, host, port), None)
            return wire.encode("ok", [])
        if op == "lookup":
            now = time.time()
            with self._lock:
                dead = [
                    k for k, (ts, _) in self._entries.items()
                    if now - ts > self.ttl
                ]
                for k in dead:
                    del self._entries[k]
                table = [
                    [s, h, p, self._entries[(s, h, p)][1]]
                    for (s, h, p) in sorted(self._entries)
                ]
                topo = self._topology
            # the committed gen rides the reply so TcpRegistry.lookup can
            # filter client routing without a second round trip
            gen = int(topo.get("gen", 0)) if topo else 0
            return wire.encode("table", [json.dumps(table), gen])
        if op == "topo_set":
            rec = {
                "num_shards": int(vals[0]),
                "gen": int(vals[1]),
                "epoch": int(vals[2]),
            }
            with self._lock:
                self._topology = rec
            return wire.encode("ok", [])
        if op == "topo_get":
            with self._lock:
                topo = self._topology
            return wire.encode(
                "topo", ["null" if topo is None else json.dumps(topo)]
            )
        if op == "lease_acquire":
            group, holder = str(vals[0]), str(vals[1])
            ttl, min_term = float(vals[2]), int(vals[3])
            meta = json.loads(str(vals[4])) if len(vals) > 4 else {}
            now = time.time()
            with self._lock:
                cur = self._leases.get(group)
                if (
                    cur is not None
                    and cur["holder"] != holder
                    and float(cur["expires"]) > now
                ):
                    return wire.encode("lease", ["null"])
                term = int(cur["term"]) if cur is not None else 0
                if cur is None or cur["holder"] != holder:
                    term += 1
                term = max(term, min_term)
                new = {"term": term, "holder": holder,
                       "expires": now + ttl, "meta": meta}
                self._leases[group] = new
                return wire.encode("lease", [self._lease_json(new)])
        if op == "lease_renew":
            group, holder = str(vals[0]), str(vals[1])
            term, ttl = int(vals[2]), float(vals[3])
            with self._lock:
                cur = self._leases.get(group)
                ok = (
                    cur is not None
                    and cur["holder"] == holder
                    and int(cur["term"]) == term
                )
                if ok:
                    cur["expires"] = time.time() + ttl
            return wire.encode("ok", [bool(ok)])
        if op == "lease_observe":
            with self._lock:
                cur = self._leases.get(str(vals[0]))
                out = "null" if cur is None else self._lease_json(cur)
            return wire.encode("lease", [out])
        return wire.encode("err", [f"unknown op {op!r}"])

    @staticmethod
    def _lease_json(lease: dict) -> str:
        # expires_in is RELATIVE — client and server clocks never compared
        return json.dumps({
            "term": int(lease["term"]),
            "holder": lease["holder"],
            "expires_in": float(lease["expires"]) - time.time(),
            "meta": lease.get("meta") or {},
        })


class TcpRegistry:
    """Registry backed by a RendezvousServer endpoint.

    Same surface as registry.Registry: register() heartbeats in the
    background and returns a stop Event; lookup()/wait_for() read the
    live table. Connections are per-request (the rendezvous is low-QPS
    control plane; reconnects double as liveness probes)."""

    def __init__(self, address: str, ttl: float = 10.0,
                 timeout: float = 5.0):
        if address.startswith("tcp://"):
            address = address[len("tcp://"):]
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.ttl = ttl
        self.timeout = timeout

    def _call(self, op: str, vals: list) -> list:
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        ) as sock:
            wire.send_frame(sock, wire.encode(op, vals))
            frame = wire.read_frame(sock)
        if frame is None:
            raise ConnectionError("rendezvous closed connection")
        rop, rvals = wire.decode(frame)
        if rop == "err":
            raise RuntimeError(f"rendezvous error: {rvals[0]}")
        return rvals

    # -- server side -----------------------------------------------------

    def register(self, shard: int, host: str, port: int,
                 meta: dict | None = None):
        """Heartbeat `reg` until the returned Event is set, then `unreg`
        (ephemeral-znode + session keep-alive parity)."""
        stop = threading.Event()

        def beat():
            while not stop.is_set():
                try:
                    # meta is re-serialized EVERY beat (file-backend
                    # parity): replication coordinators mutate the dict
                    # in place so peers see live WAL positions/roles
                    self._call(
                        "reg", [shard, host, port, json.dumps(meta or {})]
                    )
                except (OSError, RuntimeError):
                    # rendezvous briefly away or replying err frames
                    # (e.g. mid-restart): keep beating — a dead heartbeat
                    # thread would silently expire a healthy shard
                    pass
                stop.wait(self.ttl / 3)
            try:
                self._call("unreg", [shard, host, port])
            except (OSError, RuntimeError):
                pass

        threading.Thread(target=beat, daemon=True).start()
        return stop

    # -- client side -----------------------------------------------------

    def lookup(self, num_shards: int) -> dict[int, list[tuple[str, int]]]:
        out: dict[int, list[tuple[str, int]]] = {
            s: [] for s in range(num_shards)
        }
        try:
            vals = self._call("lookup", [])
        except OSError:
            return out
        # reply is [table_json] pre-reshard, [table_json, gen] after: the
        # gen filters client routing to the committed topology generation
        gen = int(vals[1]) if len(vals) > 1 else 0
        for s, h, p, *m in json.loads(vals[0]):
            try:
                entry_gen = int(json.loads(m[0]).get("gen", 0)) if m else 0
            except (ValueError, AttributeError, json.JSONDecodeError):
                entry_gen = 0
            if entry_gen != gen:
                continue
            if int(s) in out:
                out[int(s)].append((str(h), int(p)))
        return out

    def lookup_meta(self) -> dict[tuple[int, str, int], dict]:
        """Full live table including per-entry meta (the shared-dir
        Registry persists meta in its heartbeat files; this is the tcp://
        equivalent)."""
        vals = self._call("lookup", [])
        return {
            (int(s), str(h), int(p)): json.loads(m[0]) if m else {}
            for s, h, p, *m in json.loads(vals[0])
        }

    def members(self, shard: int) -> list[tuple[str, int, dict]]:
        """Live (host, port, meta) entries for one shard group — the
        replica-group view promotion reads peer positions from. Empty on
        a transport fault (the rendezvous mid-restart): callers treat
        that as 'membership unknown', not 'everyone is dead'."""
        try:
            table = self.lookup_meta()
        except (OSError, RuntimeError):
            return []
        return [
            (h, p, meta)
            for (s, h, p), meta in sorted(table.items())
            if s == int(shard)
        ]

    # -- leases (PR 13 replication) --------------------------------------

    def acquire_lease(self, group: str, holder: str, ttl: float,
                      meta: dict | None = None,
                      min_term: int = 0) -> dict | None:
        """Take the group's lease (free/expired/already ours); a NEW
        holder bumps the term. `min_term` floors the granted term so a
        rendezvous restart (in-memory lease lost) cannot rewind the
        fencing clock. None when another holder's lease is live.
        Transport faults raise (OSError/ConnectionError) — the caller's
        lease logic must not mistake a dead registry for a free lease."""
        (lease_json,) = self._call(
            "lease_acquire",
            [group, holder, float(ttl), int(min_term),
             json.dumps(meta or {})],
        )
        lease = json.loads(lease_json)
        return lease if lease else None

    def renew(self, group: str, holder: str, term: int,
              ttl: float) -> bool:
        """Extend the lease — only while holder AND term still match."""
        (ok,) = self._call(
            "lease_renew", [group, holder, int(term), float(ttl)]
        )
        return bool(ok)

    def observe(self, group: str) -> dict | None:
        """Current lease ({term, holder, expires_in, meta}) or None."""
        (lease_json,) = self._call("lease_observe", [group])
        lease = json.loads(lease_json)
        return lease if lease else None

    # -- topology (PR 19 elastic resharding) ------------------------------

    def set_topology(self, num_shards: int, gen: int, epoch: int) -> dict:
        """Atomically publish the cluster topology — the reshard cutover
        commit point (registry.Registry.set_topology parity)."""
        self._call(
            "topo_set", [int(num_shards), int(gen), int(epoch)]
        )
        return {
            "num_shards": int(num_shards),
            "gen": int(gen),
            "epoch": int(epoch),
        }

    def topology(self) -> dict | None:
        """The committed topology record, or None (pre-reshard cluster
        or a pre-reshard rendezvous server)."""
        try:
            (topo_json,) = self._call("topo_get", [])
        except RuntimeError:
            return None  # pre-reshard rendezvous: unknown op
        topo = json.loads(topo_json)
        return topo if topo else None

    def wait_for(self, num_shards: int, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            table = self.lookup(num_shards)
            if all(table[s] for s in range(num_shards)):
                return table
            time.sleep(0.2)
        raise TimeoutError(
            f"rendezvous at {self.host}:{self.port}: not all "
            f"{num_shards} shards present"
        )


def make_registry(spec: str, ttl: float = 10.0):
    """spec "tcp://host:port" → TcpRegistry; anything else → shared-dir
    Registry (the two deployment modes: bare TCP pods vs NFS/GCS pods)."""
    if spec.startswith("tcp://"):
        return TcpRegistry(spec, ttl=ttl)
    from euler_tpu.distributed.registry import Registry

    return Registry(spec, ttl=ttl)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="standalone membership rendezvous server"
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=10.0)
    args = ap.parse_args(argv)
    srv = RendezvousServer(args.host, args.port, ttl=args.ttl).start()
    print(f"rendezvous on {srv.address}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
