"""Typed RPC errors — the failure vocabulary of the distributed layer.

The reference's RPC status codes make every failure machine-dispatchable
(rpc_client.h:32-66 retries transport faults, surfaces server verdicts);
here the same split is a small exception hierarchy that crosses the wire
as an err-frame name prefix ("DeadlineExceeded: ..."):

  RpcError          — deterministic server-side failure. NEVER
                      transport-retried: the server computed this answer,
                      a replica failover would just recompute it.
    DeadlineExceeded — the call's time budget ran out (client-side retry
                      loop, or server-side rejection of already-expired
                      work before dispatch).
    OverloadError    — admission control refused the request (bounded
                      queue full). Retrying amplifies the overload it
                      signals; callers own backoff.

Transport faults (OSError/ConnectionError/timeout/torn frame) are NOT in
this hierarchy on purpose — those are the retryable class.

This module imports nothing so every layer (wire, client, server,
serving, chaos) can depend on it without cycles.
"""

from __future__ import annotations


class RpcError(RuntimeError):
    """Deterministic server-side error — do not failover-retry."""


class DeadlineExceeded(RpcError):
    """The call's time budget expired (client loop or server reject)."""


class OverloadError(RpcError):
    """Admission control refused the request (bounded queue full)."""


class NotPrimaryError(RpcError):
    """A mutation landed on a replica that is not the group's primary
    (follower, or a fenced ex-primary whose lease term went stale).

    The detail carries the group's current coordinates so a writer can
    re-route its keyed outbox without a registry round trip:

        "NotPrimaryError: shard=3 role=follower term=7 primary=host:port"

    `primary=?` when the rejecting replica does not know one (election in
    flight) — the writer falls back to observing the lease."""

    @staticmethod
    def format(shard: int, role: str, term: int, primary) -> str:
        addr = f"{primary[0]}:{primary[1]}" if primary else "?"
        return f"shard={shard} role={role} term={term} primary={addr}"

    @staticmethod
    def parse_primary(message: str):
        """(host, port) named in a NotPrimaryError detail, else None."""
        for tok in message.split():
            if tok.startswith("primary="):
                addr = tok[len("primary="):]
                if addr == "?" or ":" not in addr:
                    return None
                host, _, port = addr.rpartition(":")
                try:
                    return host, int(port)
                except ValueError:
                    return None
        return None


class ReshardFencedError(NotPrimaryError):
    """A mutation landed on a source shard fenced for a reshard cutover.

    Subclasses NotPrimaryError so writers that predate resharding treat
    it with the redirect machinery they already have: the detail carries
    `primary=?`, which makes them drop their primary pin, back off, and
    re-discover — by which time `connect()`'s topology watch has re-routed
    them to the new shard set. The fencing window is bounded by the
    cutover (a few lease TTLs), so the bounded redirect loop rides it out.

        "ReshardFencedError: shard=1 role=fenced term=7 primary=?"
    """


# pre-PR-4 serving name; same class, so except-clauses written against
# either name keep working and the wire prefix stays one canonical string
DeadlineExceededError = DeadlineExceeded

# err-frame name prefix -> exception class. "DeadlineExceededError" stays
# for frames from pre-PR-4 servers whose batcher raised under the old name.
WIRE_ERRORS = {
    "RpcError": RpcError,
    "DeadlineExceeded": DeadlineExceeded,
    "DeadlineExceededError": DeadlineExceeded,
    "OverloadError": OverloadError,
    "NotPrimaryError": NotPrimaryError,
    "ReshardFencedError": ReshardFencedError,
}


def from_wire(message: str) -> RpcError:
    """Typed exception for an err-frame payload.

    Server frames carry "<TypeName>: <detail>"; unknown names degrade to
    plain RpcError so new server-side error types never crash old
    clients — they just lose retry-exemption specificity (all RpcErrors
    are exempt anyway)."""
    name = message.split(":", 1)[0].strip()
    cls = WIRE_ERRORS.get(name, RpcError)
    return cls(message)
