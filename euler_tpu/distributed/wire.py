"""Wire protocol for the graph service: length-prefixed binary frames.

Replaces the reference's TensorProto-over-gRPC encoding
(euler/core/framework/tensor_util.h, proto/worker.proto:137-152) with a
minimal self-describing format — no proto toolchain needed, arrays travel as
raw little-endian buffers, and the C++ engine could emit the same frames.

Frame:   [u32 payload_len][payload]
Payload: [u16 op_len][op utf8][u16 n_values][value...]
Value:   [u8 tag] + tag-specific body
  0 array: [u8 dtype_code][u8 ndim][i64 shape...]["raw bytes"]
  1 int:   [i64]
  2 float: [f64]
  3 str:   [u32 len][utf8]
  4 none:  —
  5 bool:  [u8]
  6 list of values: [u16 n][value...]

Deadline propagation rides the op string, not the frame layout: a call
with a time budget ships op "@dl:<remaining_ms>:<op>" (see
`wrap_deadline`/`unwrap_deadline`). The budget is RELATIVE milliseconds —
client and server clocks are never compared — and servers reject
already-expired work with a typed err frame before dispatch. A pre-PR-4
server answers the envelope with "unknown op '@dl:...'", which clients
treat as a degrade signal: drop the envelope for that shard and resend
(deadlines then only bound the client side). Frame layout is untouched,
so every other verb stays byte-compatible in both directions.

Zero-copy I/O discipline (the hot-path contract):

- send: `encode_vectored` keeps large array payloads as memoryviews of
  the source arrays and `send_frame` scatter-gathers them with
  `sendmsg`, so a multi-MB feature block is never copied into a staging
  buffer; small values coalesce into one header buffer whose first four
  bytes are the length prefix (packed in place — no header + payload
  concatenation copy).
- recv: `_read_exact` recv_into's ONE exact-size bytearray (no chunk
  list, no b"".join copy, no 1 MiB recv cap forcing extra syscalls on
  multi-MB frames).
- decode: `borrow=True` makes decoded arrays SLICE the frame buffer
  instead of copying it. Safe because every frame gets a fresh buffer
  that nothing mutates; consumers that retain per-id blocks (the client
  read cache) copy just the rows they keep, so a few cached rows never
  pin a whole frame.
"""

from __future__ import annotations

import socket
import struct

import numpy as np

from euler_tpu.graph.format import _CODE_DTYPES, _DTYPE_CODES

MAX_FRAME = 1 << 31

DEADLINE_PREFIX = "@dl:"


def wrap_deadline(op: str, budget_ms: float) -> str:
    """Envelope `op` with a remaining-time budget in milliseconds."""
    return f"{DEADLINE_PREFIX}{budget_ms:.1f}:{op}"


def unwrap_deadline(op: str) -> tuple[str, float | None]:
    """(inner op, remaining budget ms) — (op, None) when no envelope."""
    if not op.startswith(DEADLINE_PREFIX):
        return op, None
    _, ms, inner = op.split(":", 2)
    return inner, float(ms)


# arrays at least this big ride as their own iovec in the vectored
# encode (below it, appending to the header buffer beats iovec overhead)
_VECTOR_MIN_BYTES = 4096


def _tail(parts: list) -> bytearray:
    """The bytearray small values accumulate into — a fresh one after
    every zero-copy iovec so wire order is preserved."""
    if not isinstance(parts[-1], bytearray):
        parts.append(bytearray())
    return parts[-1]


def _pack_value(parts: list, v, vectored: bool) -> None:
    buf = _tail(parts)
    if isinstance(v, np.ndarray):
        v = np.ascontiguousarray(v)
        if v.dtype == np.bool_:
            v = v.astype(np.uint8)
        buf += struct.pack("<BBB", 0, _DTYPE_CODES[v.dtype], v.ndim)
        for d in v.shape:
            buf += struct.pack("<q", d)
        if vectored and v.nbytes >= _VECTOR_MIN_BYTES:
            # zero-copy: the array's own buffer becomes an iovec; the
            # memoryview keeps the (contiguous) source alive until sent
            parts.append(memoryview(v.reshape(-1).view(np.uint8)))
        else:
            buf += v.tobytes()
    elif isinstance(v, bool):
        buf += struct.pack("<BB", 5, int(v))
    elif isinstance(v, (int, np.integer)):
        buf += struct.pack("<Bq", 1, int(v))
    elif isinstance(v, (float, np.floating)):
        buf += struct.pack("<Bd", 2, float(v))
    elif isinstance(v, str):
        raw = v.encode()
        buf += struct.pack("<BI", 3, len(raw))
        buf += raw
    elif v is None:
        buf += struct.pack("<B", 4)
    elif isinstance(v, (list, tuple)):
        buf += struct.pack("<BH", 6, len(v))
        for item in v:
            _pack_value(parts, item, vectored)
    else:
        raise TypeError(f"cannot encode {type(v)}")


def _unpack_value(view: memoryview, off: int, borrow: bool = False):
    (tag,) = struct.unpack_from("<B", view, off)
    off += 1
    if tag == 0:
        code, ndim = struct.unpack_from("<BB", view, off)
        off += 2
        # hot path (every array of every RPC and WAL record): one
        # unpack for all dims, plain-int product (np.prod dominated
        # decode cost), and no frombuffer/copy churn for empty arrays
        if ndim:
            shape = struct.unpack_from("<%dq" % ndim, view, off)
            off += 8 * ndim
            n = 1
            for d in shape:
                n *= d
        else:
            shape, n = (), 1
        dt = _CODE_DTYPES[code]
        nbytes = dt.itemsize * n
        if n == 0:
            return np.empty(shape, dt), off + nbytes
        arr = np.frombuffer(view[off : off + nbytes], dtype=dt)
        if not borrow:
            arr = arr.copy()
        if ndim != 1:
            arr = arr.reshape(shape)
        return arr, off + nbytes
    if tag == 1:
        (v,) = struct.unpack_from("<q", view, off)
        return int(v), off + 8
    if tag == 2:
        (v,) = struct.unpack_from("<d", view, off)
        return float(v), off + 8
    if tag == 3:
        (n,) = struct.unpack_from("<I", view, off)
        off += 4
        return bytes(view[off : off + n]).decode(), off + n
    if tag == 4:
        return None, off
    if tag == 5:
        (v,) = struct.unpack_from("<B", view, off)
        return bool(v), off + 1
    if tag == 6:
        (n,) = struct.unpack_from("<H", view, off)
        off += 2
        items = []
        for _ in range(n):
            item, off = _unpack_value(view, off, borrow)
            items.append(item)
        return items, off
    raise ValueError(f"bad tag {tag}")


def _encode_parts(op: str, values, vectored: bool) -> list:
    head = bytearray(4)  # length-prefix placeholder, packed in place
    parts: list = [head]
    raw = op.encode()
    head += struct.pack("<H", len(raw))
    head += raw
    head += struct.pack("<H", len(values))
    for v in values:
        _pack_value(parts, v, vectored)
    total = sum(len(p) for p in parts) - 4
    if total > MAX_FRAME:
        raise ValueError(f"frame too large: {total}")
    struct.pack_into("<I", head, 0, total)
    return parts


def encode(op: str, values) -> bytearray:
    """One flat frame (length prefix included). Built in place — no
    header + payload concatenation copy."""
    parts = _encode_parts(op, values, vectored=False)
    return parts[0]  # vectored=False keeps everything in the head buffer


def encode_vectored(op: str, values) -> list:
    """Frame as an ordered buffer list for sendmsg scatter-gather: large
    arrays stay views of their source buffers (zero copies), small values
    coalesce around them. `b"".join(parts)` equals `encode(op, values)`."""
    return _encode_parts(op, values, vectored=True)


def decode(payload, borrow: bool = False) -> tuple[str, list]:
    # any malformed payload (truncated, corrupted, garbage) surfaces as
    # ValueError — ONE exception type for "this frame is broken", which
    # clients treat as a transport fault (failover) and servers as a
    # connection-costing error, never a hang or a dead worker.
    # borrow=True: decoded arrays are views of `payload` (no copy) —
    # callers must hand each frame its own buffer and never mutate it.
    try:
        return _decode(payload, borrow)
    except ValueError:
        raise
    except (struct.error, IndexError, UnicodeDecodeError, KeyError) as e:
        raise ValueError(f"malformed frame: {type(e).__name__}: {e}") from e


def _decode(payload, borrow: bool) -> tuple[str, list]:
    view = memoryview(payload)
    (op_len,) = struct.unpack_from("<H", view, 0)
    off = 2
    op = bytes(view[off : off + op_len]).decode()
    off += op_len
    (n,) = struct.unpack_from("<H", view, off)
    off += 2
    values = []
    for _ in range(n):
        v, off = _unpack_value(view, off, borrow)
        values.append(v)
    return op, values


def frame_nbytes(data) -> int:
    """Total wire bytes of one frame — flat buffer or `encode_vectored`
    part list (the per-verb bytes_in/bytes_out counter seam; counting
    here keeps the zero-copy send path free of a join)."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        return len(data)
    return sum(len(p) for p in data)


def read_frame(sock: socket.socket) -> bytearray | None:
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack("<I", header)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return _read_exact(sock, n)


def _read_exact(sock: socket.socket, n: int) -> bytearray | None:
    """Read exactly n bytes into ONE exact-size buffer via recv_into —
    no per-chunk allocations, no b"".join copy, and no artificial recv
    cap adding syscalls on multi-MB frames. The buffer is fresh per
    frame, which is what makes decode's borrow mode safe. None on EOF
    (clean between frames, torn mid-frame — callers treat both as a
    transport fault)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            return None
        got += r
    return buf


def send_frame(sock: socket.socket, data) -> None:
    """Send one frame: flat bytes-like, or an `encode_vectored` buffer
    list scatter-gathered through sendmsg (sequential sendall where
    sendmsg is unavailable). Partial sendmsg results are resumed."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        sock.sendall(data)
        return
    bufs = [memoryview(p).cast("B") for p in data if len(p)]
    if not hasattr(sock, "sendmsg"):
        for b in bufs:
            sock.sendall(b)
        return
    while bufs:
        sent = sock.sendmsg(bufs)
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if sent:
            bufs[0] = bufs[0][sent:]
