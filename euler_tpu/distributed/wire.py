"""Wire protocol for the graph service: length-prefixed binary frames.

Replaces the reference's TensorProto-over-gRPC encoding
(euler/core/framework/tensor_util.h, proto/worker.proto:137-152) with a
minimal self-describing format — no proto toolchain needed, arrays travel as
raw little-endian buffers, and the C++ engine could emit the same frames.

Frame:   [u32 payload_len][payload]
Payload: [u16 op_len][op utf8][u16 n_values][value...]
Value:   [u8 tag] + tag-specific body
  0 array: [u8 dtype_code][u8 ndim][i64 shape...]["raw bytes"]
  1 int:   [i64]
  2 float: [f64]
  3 str:   [u32 len][utf8]
  4 none:  —
  5 bool:  [u8]
  6 list of values: [u16 n][value...]

Deadline propagation rides the op string, not the frame layout: a call
with a time budget ships op "@dl:<remaining_ms>:<op>" (see
`wrap_deadline`/`unwrap_deadline`). The budget is RELATIVE milliseconds —
client and server clocks are never compared — and servers reject
already-expired work with a typed err frame before dispatch. A pre-PR-4
server answers the envelope with "unknown op '@dl:...'", which clients
treat as a degrade signal: drop the envelope for that shard and resend
(deadlines then only bound the client side). Frame layout is untouched,
so every other verb stays byte-compatible in both directions.
"""

from __future__ import annotations

import socket
import struct

import numpy as np

from euler_tpu.graph.format import _CODE_DTYPES, _DTYPE_CODES

MAX_FRAME = 1 << 31

DEADLINE_PREFIX = "@dl:"


def wrap_deadline(op: str, budget_ms: float) -> str:
    """Envelope `op` with a remaining-time budget in milliseconds."""
    return f"{DEADLINE_PREFIX}{budget_ms:.1f}:{op}"


def unwrap_deadline(op: str) -> tuple[str, float | None]:
    """(inner op, remaining budget ms) — (op, None) when no envelope."""
    if not op.startswith(DEADLINE_PREFIX):
        return op, None
    _, ms, inner = op.split(":", 2)
    return inner, float(ms)


def _pack_value(buf: bytearray, v) -> None:
    if isinstance(v, np.ndarray):
        v = np.ascontiguousarray(v)
        if v.dtype == np.bool_:
            v = v.astype(np.uint8)
        buf += struct.pack("<BBB", 0, _DTYPE_CODES[v.dtype], v.ndim)
        for d in v.shape:
            buf += struct.pack("<q", d)
        buf += v.tobytes()
    elif isinstance(v, bool):
        buf += struct.pack("<BB", 5, int(v))
    elif isinstance(v, (int, np.integer)):
        buf += struct.pack("<Bq", 1, int(v))
    elif isinstance(v, (float, np.floating)):
        buf += struct.pack("<Bd", 2, float(v))
    elif isinstance(v, str):
        raw = v.encode()
        buf += struct.pack("<BI", 3, len(raw))
        buf += raw
    elif v is None:
        buf += struct.pack("<B", 4)
    elif isinstance(v, (list, tuple)):
        buf += struct.pack("<BH", 6, len(v))
        for item in v:
            _pack_value(buf, item)
    else:
        raise TypeError(f"cannot encode {type(v)}")


def _unpack_value(view: memoryview, off: int):
    (tag,) = struct.unpack_from("<B", view, off)
    off += 1
    if tag == 0:
        code, ndim = struct.unpack_from("<BB", view, off)
        off += 2
        shape = []
        for _ in range(ndim):
            (d,) = struct.unpack_from("<q", view, off)
            off += 8
            shape.append(d)
        dt = _CODE_DTYPES[code]
        n = int(np.prod(shape)) if shape else 1
        nbytes = dt.itemsize * n
        arr = (
            np.frombuffer(view[off : off + nbytes], dtype=dt)
            .reshape(shape)
            .copy()
        )
        return arr, off + nbytes
    if tag == 1:
        (v,) = struct.unpack_from("<q", view, off)
        return int(v), off + 8
    if tag == 2:
        (v,) = struct.unpack_from("<d", view, off)
        return float(v), off + 8
    if tag == 3:
        (n,) = struct.unpack_from("<I", view, off)
        off += 4
        return bytes(view[off : off + n]).decode(), off + n
    if tag == 4:
        return None, off
    if tag == 5:
        (v,) = struct.unpack_from("<B", view, off)
        return bool(v), off + 1
    if tag == 6:
        (n,) = struct.unpack_from("<H", view, off)
        off += 2
        items = []
        for _ in range(n):
            item, off = _unpack_value(view, off)
            items.append(item)
        return items, off
    raise ValueError(f"bad tag {tag}")


def encode(op: str, values) -> bytes:
    buf = bytearray()
    raw = op.encode()
    buf += struct.pack("<H", len(raw))
    buf += raw
    buf += struct.pack("<H", len(values))
    for v in values:
        _pack_value(buf, v)
    return struct.pack("<I", len(buf)) + bytes(buf)


def decode(payload: bytes) -> tuple[str, list]:
    # any malformed payload (truncated, corrupted, garbage) surfaces as
    # ValueError — ONE exception type for "this frame is broken", which
    # clients treat as a transport fault (failover) and servers as a
    # connection-costing error, never a hang or a dead worker
    try:
        return _decode(payload)
    except ValueError:
        raise
    except (struct.error, IndexError, UnicodeDecodeError, KeyError) as e:
        raise ValueError(f"malformed frame: {type(e).__name__}: {e}") from e


def _decode(payload: bytes) -> tuple[str, list]:
    view = memoryview(payload)
    (op_len,) = struct.unpack_from("<H", view, 0)
    off = 2
    op = bytes(view[off : off + op_len]).decode()
    off += op_len
    (n,) = struct.unpack_from("<H", view, off)
    off += 2
    values = []
    for _ in range(n):
        v, off = _unpack_value(view, off)
        values.append(v)
    return op, values


def read_frame(sock: socket.socket) -> bytes | None:
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack("<I", header)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return _read_exact(sock, n)


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            return None
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_frame(sock: socket.socket, data: bytes) -> None:
    sock.sendall(data)
