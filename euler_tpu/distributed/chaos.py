"""Deterministic fault injection for the RPC substrate.

Failure behavior must be reproducible test input, not folklore: a seeded
`FaultPlan` describes WHICH faults fire WHERE and WHEN, and the client
transport (`_Replica.call`) and server dispatch (`_PoolServer._respond`)
consult it at well-defined points. The same plan + the same call order
replays the same faults, so recovery tests can assert bit-identical
results instead of "it usually survives".

Fault sites and kinds:
  client (before the request leaves the process; matches shard /
  replica address / op):
    reset      — ConnectionResetError, as if the peer RST the socket
    eof        — clean close, as if the server shut down mid-stream
    delay      — fixed (+ per-firing ramp) latency before the call
    blackhole  — the replica never answers: hold, then socket.timeout
  server (inside the worker, around dispatch; matches shard / op):
    delay      — slow handler (fixed + ramp)
    err        — typed err frame (`message`) instead of dispatch
    eof        — close the connection without responding
    reset      — RST the connection (SO_LINGER 0) without responding
    truncate   — send a torn response frame (prefix bytes), then close
    corrupt    — flip bytes inside an otherwise well-framed response
    blackhole  — hold the connection open unanswered, then close

Enable programmatically (`install(FaultPlan(...))`, or pass
`fault_plan=` to a server) or via `EULER_TPU_CHAOS` (JSON spec, picked
up by any process — the bench's spawned shard servers inherit it).
When no plan is installed the hot-path cost is one module-global read.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

# fault kinds valid per site (spec validation: a typo'd kind must fail
# loudly at plan build, not silently never fire)
CLIENT_KINDS = frozenset({"reset", "eof", "delay", "blackhole"})
SERVER_KINDS = frozenset(
    {"delay", "err", "eof", "reset", "truncate", "corrupt", "blackhole"}
)


@dataclass
class Fault:
    """One fault rule: match predicate + firing schedule + action."""

    kind: str
    site: str = "client"  # "client" | "server"
    op: str | None = None  # None = any verb
    shard: int | None = None  # None = any shard
    replica: tuple | None = None  # (host, port); client site only
    after: int = 0  # skip the first `after` matching calls
    count: int | None = None  # fire at most `count` times (None = forever)
    prob: float = 1.0  # seeded coin per eligible call
    delay_s: float = 0.05
    ramp_s: float = 0.0  # delay grows by this much every firing
    hold_s: float = 30.0  # blackhole hold before giving up the socket
    message: str = "RpcError: chaos-injected error"
    # runtime state (owned by the plan's lock)
    matched: int = 0
    fired: int = 0

    def __post_init__(self):
        valid = CLIENT_KINDS if self.site == "client" else SERVER_KINDS
        if self.site not in ("client", "server"):
            raise ValueError(f"bad fault site {self.site!r}")
        if self.kind not in valid:
            raise ValueError(
                f"bad {self.site} fault kind {self.kind!r}"
                f" (valid: {sorted(valid)})"
            )
        if self.replica is not None:
            self.replica = (str(self.replica[0]), int(self.replica[1]))


@dataclass
class FaultDecision:
    """One firing, resolved: what the hook should do."""

    kind: str
    delay_s: float = 0.0
    hold_s: float = 0.0
    message: str = ""


class FaultPlan:
    """Seeded, thread-safe schedule over a list of `Fault` rules.

    Match counters and the probability stream live under one lock, so a
    single-threaded call sequence replays exactly; concurrent callers
    still get a consistent (if interleaving-dependent) schedule.
    """

    def __init__(self, faults, seed: int = 0):
        self.faults = list(faults)
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    # -- spec I/O --------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: str | dict) -> "FaultPlan":
        """Build from the EULER_TPU_CHAOS JSON spec:
        {"seed": 7, "faults": [{"site": "server", "kind": "delay",
         "op": "sample_fanout", "delay_s": 0.05}, ...]}"""
        if isinstance(spec, str):
            spec = json.loads(spec)
        faults = []
        for f in spec.get("faults", []):
            f = dict(f)
            if "replica" in f and f["replica"] is not None:
                f["replica"] = tuple(f["replica"])
            faults.append(Fault(**f))
        return cls(faults, seed=int(spec.get("seed", 0)))

    # -- matching --------------------------------------------------------

    def _decide(self, fault: Fault) -> FaultDecision | None:
        """Firing decision for one matched rule. decisions() holds
        self._lock across every call — the counters never race."""
        idx = fault.matched
        # graftlint: disable=lock-unguarded-write -- caller holds self._lock
        fault.matched += 1
        if idx < fault.after:
            return None
        if fault.count is not None and fault.fired >= fault.count:
            return None
        if fault.prob < 1.0 and float(self._rng.random()) >= fault.prob:
            return None
        n = fault.fired
        # graftlint: disable=lock-unguarded-write -- caller holds self._lock
        fault.fired += 1
        return FaultDecision(
            kind=fault.kind,
            delay_s=fault.delay_s + fault.ramp_s * n,
            hold_s=fault.hold_s,
            message=fault.message,
        )

    def decisions(
        self,
        site: str,
        op: str,
        shard: int | None = None,
        replica: tuple | None = None,
    ) -> list[FaultDecision]:
        out = []
        with self._lock:
            for f in self.faults:
                if f.site != site:
                    continue
                if f.op is not None and f.op != op:
                    continue
                if f.shard is not None and shard is not None and f.shard != shard:
                    continue
                if (
                    f.replica is not None
                    and replica is not None
                    and f.replica != tuple(replica)
                ):
                    continue
                d = self._decide(f)
                if d is not None:
                    out.append(d)
        return out

    def stats(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "site": f.site,
                    "kind": f.kind,
                    "op": f.op,
                    "matched": f.matched,
                    "fired": f.fired,
                }
                for f in self.faults
            ]

    # -- client-side application ----------------------------------------

    def apply_client(
        self,
        shard: int | None,
        replica: tuple,
        op: str,
        timeout_s: float | None,
    ) -> None:
        """Run client-site faults for one attempt; raises the transport
        error the fault models (so the real retry/failover path handles
        it — chaos tests the machinery, it doesn't reimplement it)."""
        import socket as socket_mod

        for d in self.decisions("client", op, shard=shard, replica=replica):
            if d.kind == "delay":
                time.sleep(d.delay_s)
            elif d.kind == "reset":
                raise ConnectionResetError(
                    f"chaos: reset {replica[0]}:{replica[1]} ({op})"
                )
            elif d.kind == "eof":
                raise ConnectionError(
                    f"chaos: peer closed {replica[0]}:{replica[1]} ({op})"
                )
            elif d.kind == "blackhole":
                hold = d.hold_s
                if timeout_s is not None:
                    hold = min(hold, timeout_s)
                time.sleep(hold)
                raise socket_mod.timeout(
                    f"chaos: blackholed {replica[0]}:{replica[1]} ({op})"
                )


# -- process-global plan ----------------------------------------------------

_INSTALL_LOCK = threading.Lock()
_PLAN: FaultPlan | None = None
# env parse cache: (raw spec string, plan) — a changed env var (tests,
# spawned processes) rebuilds; same value reuses counters, which is what
# a long-lived process wants
_ENV_PLAN: tuple[str, FaultPlan] | None = None


def install(plan: FaultPlan | None) -> None:
    """Set (or with None, clear) the process-global fault plan."""
    global _PLAN
    with _INSTALL_LOCK:
        _PLAN = plan


def uninstall() -> None:
    install(None)


def active_plan() -> FaultPlan | None:
    """The installed plan, else one parsed from EULER_TPU_CHAOS, else
    None. The no-chaos fast path is one global read + one env probe."""
    global _ENV_PLAN
    if _PLAN is not None:
        return _PLAN
    spec = os.environ.get("EULER_TPU_CHAOS")
    if not spec:
        return None
    with _INSTALL_LOCK:
        if _ENV_PLAN is None or _ENV_PLAN[0] != spec:
            _ENV_PLAN = (spec, FaultPlan.from_spec(spec))
        return _ENV_PLAN[1]
