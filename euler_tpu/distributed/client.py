"""Remote graph client: per-shard replica pools with failover.

The reference's client stack (euler/client/): `RpcManager` keeps round-robin
replica channels per shard with bad-host quarantine + timed revival
(rpc_manager.h:66-124) and retries calls up to 10× (rpc_client.h:32-66).
`RemoteShard` reproduces that contract over the wire protocol — and adds
the discipline around the retry loop: a per-call deadline that propagates
on the wire (EULER_TPU_RPC_TIMEOUT_S; socket timeouts derive from the
remaining budget), exponential backoff with deterministic seeded jitter,
and a per-shard retry budget that fails fast instead of joining a retry
storm (distributed/retry.py). Typed server verdicts (`RpcError` and its
subclasses) are never transport-retried. `connect` assembles a standard
`Graph` facade whose shards are remote, so every dataflow/estimator works
unchanged against a cluster.
"""

from __future__ import annotations

import collections
import json
import os
import socket
import threading
import time

import numpy as np

from euler_tpu.distributed import chaos, wire
from euler_tpu.distributed.cache import ReadCache, epoch_refresh_s
from euler_tpu.distributed.errors import (  # noqa: F401 (re-exports)
    DeadlineExceeded,
    OverloadError,
    RpcError,
    from_wire,
)
from euler_tpu.distributed.registry import Registry  # noqa: F401 (re-export)
from euler_tpu.distributed.rendezvous import make_registry
from euler_tpu.distributed.retry import (
    RetryBudget,
    RetryPolicy,
    default_timeout_s,
)
from euler_tpu.graph.meta import GraphMeta
from euler_tpu.graph.store import Graph


class _DaemonExecutor:
    """Minimal bounded executor on daemon threads.

    concurrent.futures.ThreadPoolExecutor joins its (non-daemon) workers
    at interpreter exit — a worker stuck in a connect-retry loop against
    torn-down shard servers would stall process exit for minutes. Daemon
    workers + no global join means abandoned in-flight futures die with
    the process, which is exactly right for fire-and-forget RPC overlap."""

    def __init__(self, max_workers: int, name: str):
        import queue as queue_mod

        self._q: "queue_mod.Queue" = queue_mod.Queue()
        self._threads = [
            threading.Thread(
                target=self._work, daemon=True, name=f"{name}-{i}"
            )
            for i in range(max_workers)
        ]
        for t in self._threads:
            t.start()

    def _work(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as e:
                fut.set_exception(e)

    def submit(self, fn, *args):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._q.put((fut, fn, args))
        return fut

    def close(self):
        # cancel still-pending jobs FIRST: a sentinel enqueued behind a
        # pending job would let the worker exit while the job's future
        # stays forever unresolved — a waiter on a submitted-but-unstarted
        # RPC would hang until process exit
        import queue as queue_mod

        while True:
            try:
                item = self._q.get_nowait()
            except queue_mod.Empty:
                break
            if item is None:
                continue
            item[0].cancel()  # pending Future -> CancelledError for waiters
        for _ in self._threads:
            self._q.put(None)


def _seed(rng) -> int:
    rng = rng if rng is not None else np.random.default_rng()
    return int(rng.integers(0, 2**63 - 1))


class _Replica:
    def __init__(
        self,
        host: str,
        port: int,
        shard: int | None = None,
        counters: tuple | None = None,
    ):
        self.host = host
        self.port = port
        self.shard = shard  # chaos-plan matching + diagnostics only
        self.bad_until = 0.0
        # optional (bytes_out Counter, bytes_in Counter) pair shared
        # across the owning shard handle's replicas — per-verb wire
        # bytes, GIL-racy increments fine (telemetry, not an invariant)
        self.counters = counters
        self._local = threading.local()

    def _sock(self, timeout_s: float | None = None) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            sock = socket.create_connection(
                (self.host, self.port),
                timeout=timeout_s if timeout_s is not None
                else default_timeout_s(),
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.sock = sock
        return sock

    def drop(self):
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            self._local.sock = None

    def call(
        self,
        op: str,
        values: list,
        timeout_s: float | None = None,
        budget_ms: float | None = None,
    ) -> list:
        """One attempt: no retries at this layer.

        timeout_s bounds the socket (connect/send/recv) — derived by the
        caller from its remaining deadline; budget_ms (when the peer
        speaks the envelope) ships that remaining budget so the server
        can reject already-expired work before dispatch."""
        plan = chaos.active_plan()
        if plan is not None:
            # may raise the transport error the fault models — BEFORE any
            # bytes move, so the server's state is untouched and the
            # retried call (same client-drawn seed) replays exactly
            plan.apply_client(
                self.shard, (self.host, self.port), op, timeout_s
            )
        sock = self._sock(timeout_s)
        sock.settimeout(
            timeout_s if timeout_s is not None else default_timeout_s()
        )
        wire_op = (
            op if budget_ms is None else wire.wrap_deadline(op, budget_ms)
        )
        # vectored send + borrow decode: request arrays ride as iovecs,
        # response arrays slice the (per-frame, never-mutated) recv
        # buffer — zero staging copies on either direction of the wire
        frame = wire.encode_vectored(wire_op, values)
        if self.counters is not None:
            self.counters[0][op] += wire.frame_nbytes(frame)
        wire.send_frame(sock, frame)
        payload = wire.read_frame(sock)
        if payload is not None and self.counters is not None:
            self.counters[1][op] += 4 + len(payload)
        if payload is None:
            # clean EOF — the server closed this connection (shutdown or
            # restart): a transport failure, so the caller fails over,
            # unlike an "err" status which is deterministic
            raise ConnectionError("connection closed by peer")
        status, result = wire.decode(payload, borrow=True)
        if status == "err":
            raise from_wire(result[0])
        return result


class _RemoteCondition:
    """Client-side handle for a server-side DNF index search.

    The reference keeps IndexResult sets on the serving shard and ships
    only what the client round needs (sample_index.h:49-60); here the
    handle carries the DNF (re-evaluated server-side per call — index
    lookups are hash/range probes, cheap) plus the matched weight used by
    the shard-weighted conditioned root draw.
    """

    def __init__(self, dnf_json: str, node: bool, total_weight: float):
        self.dnf_json = dnf_json
        self.node = node
        self.total_weight = total_weight


class _DenseWireDegraded(Exception):
    """A quantized dense fetch hit a pre-codec replica mid-cache-fetch:
    the batch must be redone on the exact f32 keyspace so the quantized
    cache key never mixes 1-part f32 and 3-part int8 block shapes."""


class RemoteShard:
    """GraphStore-compatible view of one shard served by N replicas."""

    RETRIES = 10
    QUARANTINE_S = 5.0

    # Every graph-protocol verb this client can put on the wire. The
    # table is load-bearing: graftlint's wire-protocol checker diffs it
    # against the verbs the methods below actually send AND against the
    # server's HANDLED_VERBS gate, and tests/test_wire_parity.py asserts
    # the same parity at runtime — adding a verb on one side without the
    # other fails the tier-1 gate, not the first production call.
    WIRE_VERBS = frozenset({
        "condition_mask",
        "condition_weight",
        "degree_sum",
        "dense_feature_udf",
        "edges_by_rows",
        "get_binary_feature",
        "get_dense_by_rows",
        "get_dense_feature",
        "get_edge_binary_feature",
        "get_edge_dense_feature",
        "get_edge_sparse_feature",
        "get_full_neighbor",
        "get_graph_by_label",
        "get_meta",
        "get_sparse_feature",
        "get_top_k_neighbor",
        "ids_by_rows",
        "lookup",
        "node2vec_step",
        "node_ids_by_condition",
        "node_type",
        "num_nodes",
        "ping",
        "random_walk",
        "sage_minibatch",
        "sample_edge",
        "sample_edge_with_condition",
        "sample_fanout",
        "sample_nb_rows",
        "sample_neighbor",
        "sample_neighbor_layerwise",
        "sample_node",
        "sample_node_with_condition",
        "stats",
        "unit_edge_weights",
    })

    def __init__(
        self,
        shard: int,
        replicas: list[tuple[str, int]],
        retry_policy: RetryPolicy | None = None,
    ):
        self.shard = shard
        # per-verb wire bytes this handle put on / read off the socket
        # (client half of the byte-budget story; the server half lives
        # in GraphService.wire_bytes_in/out). Shared by every replica.
        self.wire_bytes_out: collections.Counter = collections.Counter()
        self.wire_bytes_in: collections.Counter = collections.Counter()
        self._counters = (self.wire_bytes_out, self.wire_bytes_in)
        # copy-on-write tuple (same discipline as _Engine/merge_delta):
        # readers grab ONE reference and index it; membership changes
        # build a new tuple and swap it in a single assignment under the
        # lock. The old list form let add_replica .append() into a list
        # that _pick was concurrently indexing — a torn round-robin scan.
        self.replicas = tuple(
            _Replica(h, p, shard, self._counters) for h, p in replicas
        )
        self._rr = 0
        self._lock = threading.Lock()
        self._num_nodes: int | None = None
        self._unit_w: dict[tuple | None, bool] = {}
        self._pool = None  # lazy in-flight request executor
        # per-shard jitter stream seeded by shard index: deterministic
        # backoff schedules per shard, distinct across shards
        self.retry_policy = retry_policy or RetryPolicy.from_env(seed=shard)
        self._budget = RetryBudget(
            cap=float(os.environ.get("EULER_TPU_RPC_RETRY_BUDGET", 16.0))
        )
        # sticky downgrade: peers predating the deadline envelope answer
        # it with unknown-op; after one such answer this shard resends
        # plain ops (deadlines then bound only the client side)
        self._deadline_wire = True
        # same discipline for the bulk analytics CSR export: old servers
        # answer edges_by_rows with unknown-op, after which this handle
        # assembles the export from chunked per-row verbs instead
        self._edges_wire = True
        # sticky dense-wire-dtype downgrade (PR 16): a server predating
        # the trailing wire-dtype arg ignores it and answers the exact
        # f32 block; one such answer pins this handle to f32 (exact,
        # bit-identical old behavior) instead of re-offering every call
        self._dense_wire = True
        # logical RPCs issued through this shard handle (retries count
        # once) — the client half of the planner's L×P → P measurement;
        # GIL-racy increments are fine for telemetry
        self.rpc_count = 0
        # transport faults that triggered a failover retry — with
        # rpc_count, the proof that recovery was failover, not silent
        # skipping (GIL-racy increments fine: telemetry)
        self.retry_count = 0
        # deterministic read cache (EULER_TPU_READ_CACHE=0 disables):
        # hot-node rows are served from here instead of the wire, misses
        # fetch only the residual ids (distributed/cache.py)
        self._cache = ReadCache.from_env()
        # graph_epoch handshake state: checked against the server's
        # `stats` verb before the first cached read (and re-polled every
        # EULER_TPU_READ_CACHE_EPOCH_S seconds when set)
        self._epoch_checked = False
        self._epoch_next = 0.0
        # topology_epoch handshake (PR 19 resharding): versions the shard
        # LAYOUT. Row-keyed cache blocks (ids_by_rows, dense-by-rows)
        # encode this shard's row space — after a reshard the same row
        # index names a DIFFERENT node, so a change here forces a full
        # cache flush (a graph_epoch bump alone cannot express that).
        self._topo_epoch = 0

    def _executor(self) -> _DaemonExecutor:
        """Bounded executor for overlapped requests — the async
        completion-queue client's contract (query_proxy.cc:235-256,
        completion_queue_pool.h): up to EULER_TPU_INFLIGHT (default 4)
        outstanding RPCs per shard, each worker thread on its own
        socket (thread-local in _Replica), retry/quarantine preserved."""
        pool = self._pool  # one read: a concurrent close() nulls the attr
        if pool is None:
            with self._lock:
                pool = self._pool
                if pool is None:
                    import os

                    depth = int(os.environ.get("EULER_TPU_INFLIGHT", "4"))
                    pool = _DaemonExecutor(
                        max(depth, 1), f"shard{self.shard}-rpc"
                    )
                    self._pool = pool
        return pool

    def submit(
        self,
        op: str,
        values: list,
        deadline_s: float | None = None,
        prefer: tuple[str, int] | None = None,
    ):
        """Async call: returns a concurrent.futures.Future of call()'s
        result, overlapping with other in-flight requests to this shard."""
        if deadline_s is None and prefer is None:
            # keep the 2-arg form when unpinned: callers (and tests)
            # that stub call(op, values) keep working
            return self._executor().submit(self.call, op, values)
        return self._executor().submit(
            self.call, op, values, deadline_s, prefer
        )

    def close(self):
        """Stop the in-flight executor workers (idempotent)."""
        # swap under the lock _executor builds under — close() racing a
        # concurrent lazy build must not strand a half-built pool
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.close()

    @property
    def part(self) -> int:
        """Shard index — lets the Graph facade treat remote shards like
        local ones for shard-major row arithmetic."""
        return self.shard

    @property
    def num_nodes(self) -> int:
        if self._num_nodes is None:
            # RPC outside the lock (call() takes self._lock in _pick — a
            # locked fetch would self-deadlock); publish under it so racing
            # readers agree on one value
            n = int(self.call("num_nodes", [])[0])
            with self._lock:
                if self._num_nodes is None:
                    self._num_nodes = n
        return self._num_nodes

    def add_replica(self, host: str, port: int):
        with self._lock:
            # COW: one reference swap, never in-place mutation — _pick
            # indexes whatever tuple it snapshotted without tearing
            self.replicas = self.replicas + (
                _Replica(host, port, self.shard, self._counters),
            )

    def sync_replicas(self, addrs: list[tuple[str, int]]):
        """Registry-driven topology refresh: make the replica set match
        `addrs`. Existing _Replica objects are KEPT for addresses that
        survive (preserving quarantine state and per-thread sockets);
        new addresses get fresh replicas; vanished ones are dropped. One
        COW swap, so in-flight round-robin scans see either the old or
        the new tuple, never a half-synced one."""
        want = [(str(h), int(p)) for h, p in addrs]
        if not want:
            return  # an empty registry read means "membership unknown",
            # not "everyone is dead" — keep the current set
        with self._lock:
            have = {(r.host, r.port): r for r in self.replicas}
            self.replicas = tuple(
                have.get(a)
                or _Replica(a[0], a[1], self.shard, self._counters)
                for a in want
            )
            if set(want) != set(have):
                # actual membership change: this handle may now front a
                # DIFFERENT server (reshard cutover re-pointed the shard
                # index at a new member) — re-run the stats handshake
                # before the next cached read so a topology_epoch bump
                # flushes row-keyed blocks instead of serving them
                # against the wrong row space
                self._epoch_checked = False

    def _pick(self, prefer: tuple[str, int] | None = None) -> _Replica:
        with self._lock:
            reps = self.replicas  # one COW snapshot per pick
            now = time.time()
            if prefer is not None:
                host, port = str(prefer[0]), int(prefer[1])
                for r in reps:
                    if r.host == host and r.port == port:
                        if r.bad_until <= now:
                            return r
                        break  # quarantined primary: fall to round-robin
                else:
                    # a preferred address the registry/redirect told us
                    # about but the pool has never seen — a replacement
                    # replica on a NEW port. Adopt it.
                    r = _Replica(host, port, self.shard, self._counters)
                    self.replicas = reps + (r,)
                    return r
            for _ in range(len(reps)):
                r = reps[self._rr % len(reps)]
                self._rr += 1
                if r.bad_until <= now:
                    return r
            # all quarantined: take the least-recently-failed (timed revival)
            return min(reps, key=lambda r: r.bad_until)

    def call(
        self,
        op: str,
        values: list,
        deadline_s: float | None = None,
        prefer: tuple[str, int] | None = None,
    ) -> list:
        """One logical RPC: failover retries under a deadline.

        `prefer` pins the first attempt to one replica address (the
        writer's primary hint in a replica group); a quarantined or
        failing preferred replica falls back to the normal round-robin,
        and an unknown preferred address is adopted into the pool (how
        replacements on NEW ports get discovered).

        Every attempt derives its socket timeout from the remaining
        budget (capped by the policy's per-attempt timeout so one
        blackholed replica can't eat the whole deadline) and ships the
        remaining budget on the wire. Transport faults quarantine the
        replica, spend a retry-budget token, back off with deterministic
        jitter, and fail over; typed server errors (`RpcError` and
        subclasses) raise immediately — retrying a deterministic verdict
        only recomputes it."""
        policy = self.retry_policy
        budget_s = policy.deadline_budget_s(deadline_s)
        deadline = time.monotonic() + budget_s
        attempts = policy.retries or self.RETRIES
        rng = None  # jitter stream built lazily: only failing calls pay
        err: Exception | None = None
        self.rpc_count += 1
        attempt = 0
        while attempt < attempts:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"shard {self.shard}: {op!r} budget ({budget_s:.3f}s)"
                    f" exhausted after {attempt} attempt(s): {err}"
                )
            r = self._pick(prefer)
            try:
                out = r.call(
                    op,
                    values,
                    timeout_s=min(remaining, policy.attempt_timeout_s),
                    budget_ms=(
                        remaining * 1e3 if self._deadline_wire else None
                    ),
                )
                self._budget.on_success()
                return out
            except RpcError as e:
                if self._deadline_wire and self._envelope_unknown(e):
                    # pre-deadline-wire peer: degrade the envelope
                    # (sticky) and resend plain — not a transport retry
                    self._deadline_wire = False
                    continue
                # server-side error: deterministic, don't failover-retry
                raise
            except (OSError, ConnectionError, ValueError) as e:
                err = e
                self.retry_count += 1
                r.drop()
                # quarantine under the pool lock: _pick reads bad_until
                # under it, and an unguarded write could be reordered
                # against a racing reader's round-robin scan. A transport
                # fault also voids the epoch handshake: the peer may be a
                # SUPERVISED RESTART of a crashed shard, so the next
                # cached read re-learns graph_epoch over `stats` before
                # trusting any cached block (bit-identical recovery makes
                # this a no-op flush; a lossy one flushes stale bytes)
                with self._lock:
                    r.bad_until = time.time() + self.QUARANTINE_S
                    self._epoch_checked = False
                attempt += 1
                if attempt >= attempts:
                    break
                if not self._budget.try_spend():
                    raise RpcError(
                        f"shard {self.shard}: retry budget exhausted"
                        f" (replicas failing systematically): {err}"
                    )
                if attempt == 1:  # first retry builds this call's stream
                    rng = policy.call_rng()
                pause = min(
                    policy.backoff_s(attempt - 1, rng),
                    max(deadline - time.monotonic(), 0.0),
                )
                if pause > 0:
                    time.sleep(pause)
        raise RpcError(
            f"shard {self.shard}: all {attempts} attempts failed: {err}"
        )

    @staticmethod
    def _envelope_unknown(e: Exception) -> bool:
        msg = str(e)
        return "unknown op" in msg and wire.DEADLINE_PREFIX in msg

    # -- GraphStore surface ---------------------------------------------

    def ping(self) -> int:
        """Liveness probe: the serving shard's index (health checks and
        topology debugging — the client half of the server's `ping` verb)."""
        return int(self.call("ping", [])[0])

    def stats(self) -> dict:
        """The server's per-op request counters (the wire twin of reading
        GraphService.op_counts in-process — what the bench's RPC-count
        lane and capacity dashboards poll), with this handle's read-cache
        telemetry attached under "read_cache"."""
        out = json.loads(self.call("stats", [])[0])
        self._observe_topology(out)
        if self._cache is not None:
            # a stats poll doubles as an epoch observation: a bumped
            # graph_epoch invalidates the cache right here
            self._cache.observe_epoch(out.get("graph_epoch", 0))
            out["read_cache"] = self._cache.stats()
        # this handle's view of the same byte streams the server counts
        # in wire_bytes_in/out — client-side so it also covers bytes the
        # server never saw (torn sends, failed-over attempts)
        out["client_wire_bytes_out"] = dict(self.wire_bytes_out)
        out["client_wire_bytes_in"] = dict(self.wire_bytes_in)
        return out

    # -- read cache plumbing --------------------------------------------

    def on_publish(self, epoch, rows=None, ids=None, num_nodes=None):
        """Writer-driven publish notification (`GraphWriter.publish`):
        advance the read cache to the published epoch dropping EXACTLY
        the stale blocks (`rows` for row-keyed verbs, `ids` for
        id-keyed ones; None → full flush, e.g. a retried publish whose
        first response was lost), and refresh the cached num_nodes so
        shard-major row offsets track the merged table."""
        if self._cache is not None:
            self._cache.advance_epoch(epoch, ids=ids, rows=rows)
        with self._lock:
            if num_nodes is not None:
                self._num_nodes = int(num_nodes)
            self._epoch_checked = True

    def refresh_epoch(self) -> int:
        """Re-read the server's graph_epoch; a mismatch flushes the read
        cache (mutable graphs must never serve stale bytes). Returns the
        observed epoch (0 for servers predating the field — immutable
        stores, cache-forever)."""
        epoch = self._fetch_epoch()
        if self._cache is not None:
            self._cache.observe_epoch(epoch)
        return epoch

    def _observe_topology(self, st: dict) -> None:
        """React to the server's topology_epoch (PR 19): a change means
        the shard LAYOUT moved — every row index may now name a
        different node — so row-keyed cache blocks are not merely stale,
        they are wrongly row-mapped. Full flush, and the cached
        num_nodes must be re-learned from the new server."""
        te = int(st.get("topology_epoch", 0))
        if te == self._topo_epoch:
            return
        with self._lock:
            self._topo_epoch = te
            self._num_nodes = None
        if self._cache is not None:
            self._cache.clear()

    def _fetch_epoch(self) -> int:
        try:
            st = json.loads(self.call("stats", [])[0])
        except RpcError as e:
            if "unknown op" in str(e):
                return 0  # pre-`stats` server: immutable era, cache-forever
            raise
        self._observe_topology(st)
        return int(st.get("graph_epoch", 0))

    def _cached(self) -> "ReadCache | None":
        """The read cache, after epoch maintenance: the first cached read
        (and every EULER_TPU_READ_CACHE_EPOCH_S seconds when set) costs
        one `stats` RPC to learn the server's graph_epoch."""
        c = self._cache
        if c is None:
            return None
        now = time.monotonic()
        if self._epoch_checked and (
            self._epoch_next == 0.0 or now < self._epoch_next
        ):
            return c
        # RPC outside the lock (call() takes self._lock in _pick — a
        # locked fetch would self-deadlock); publish under it. Racing
        # first readers fetch twice, observe the same epoch: benign.
        epoch = self._fetch_epoch()
        ttl = epoch_refresh_s()
        with self._lock:
            c.observe_epoch(epoch)
            self._epoch_checked = True
            self._epoch_next = now + ttl if ttl > 0 else 0.0
        return c

    def cached_dense_coverage(self, ids, names) -> bool:
        """True when every id's dense-feature row for `names` is already
        cached — planners then skip the server-side feature step."""
        c = self._cache
        return c is not None and c.covers(
            self._dense_key("dense", names, self._dense_wire_kind()),
            np.asarray(ids, np.uint64),
        )

    def lookup(self, ids):
        ids = np.asarray(ids, np.uint64)
        c = self._cached()
        if c is None:
            return self.call("lookup", [ids])[0]
        return c.fetch(
            ("lookup",), ids, lambda miss: [self.call("lookup", [miss])[0]]
        )[0]

    def node_type(self, ids):
        ids = np.asarray(ids, np.uint64)
        c = self._cached()
        if c is None:
            return self.call("node_type", [ids])[0]
        return c.fetch(
            ("node_type",), ids,
            lambda miss: [self.call("node_type", [miss])[0]],
        )[0]

    def ids_by_rows(self, rows):
        """Local rows → (ids u64, weights f64, types i32): the inverse of
        lookup, swept by remote device-resident staging to enumerate this
        shard's node table. Deterministic per row → cached."""
        rows = np.asarray(rows, np.int64)
        c = self._cached()
        if c is None:
            return tuple(self.call("ids_by_rows", [rows]))
        return tuple(
            c.fetch(
                ("ids_rows",),
                rows,
                lambda miss: self.call("ids_by_rows", [miss]),
            )
        )

    def edges_by_rows(self, rows, edge_types=None):
        """Bulk CSR export for the analytics engine: local rows →
        ragged out-adjacency (counts i64, dst ids u64, weights f32,
        types i32), type-major per row. One frame on current servers;
        old servers answer unknown-op, after which this handle degrades
        (sticky) to assembling the same arrays from chunked
        ids_by_rows + get_full_neighbor calls — identical layout, so
        callers never see the difference."""
        rows = np.asarray(rows, np.int64)
        if self._edges_wire:
            try:
                req = [rows, _types(edge_types)]
                if _delta_wire():
                    # offer the compact dst plane; old servers ignore
                    # the extra arg and answer raw u64 (dtype tells)
                    req.append("delta")
                c, d, w, t = self.call("edges_by_rows", req)
                if np.asarray(d).dtype == np.uint8:
                    from euler_tpu.distributed import codec

                    d = codec.decode_u64_delta(np.asarray(d).tobytes())
                return (
                    np.asarray(c, np.int64), np.asarray(d, np.uint64),
                    np.asarray(w, np.float32), np.asarray(t, np.int32),
                )
            except RpcError as e:
                if "unknown op" not in str(e):
                    raise
                self._edges_wire = False
        # chunked per-row fallback: the padded neighbor verb, compacted
        # back to the ragged layout (row-major, type-major per row —
        # get_full_neighbor fills types in ascending order too)
        counts = np.zeros(len(rows), np.int64)
        dst, w, tt = [], [], []
        chunk = 512
        for lo in range(0, len(rows), chunk):
            sub = rows[lo:lo + chunk]
            ids = np.asarray(self.ids_by_rows(sub)[0], np.uint64)
            nbr, ww, ty, mask, _ = self.get_full_neighbor(ids, edge_types)
            mask = np.asarray(mask, bool)
            counts[lo:lo + chunk] = mask.sum(axis=1)
            dst.append(np.asarray(nbr, np.uint64)[mask])
            w.append(np.asarray(ww, np.float32)[mask])
            tt.append(np.asarray(ty, np.int32)[mask])
        if not dst:
            return (counts, np.empty(0, np.uint64),
                    np.empty(0, np.float32), np.empty(0, np.int32))
        return (counts, np.concatenate(dst), np.concatenate(w),
                np.concatenate(tt))

    def sample_node(self, count, node_type=-1, rng=None):
        return self.call("sample_node", [count, node_type, _seed(rng)])[0]

    def sample_edge(self, count, edge_type=-1, rng=None):
        return self.call("sample_edge", [count, edge_type, _seed(rng)])[0]

    def sample_neighbor(self, ids, edge_types=None, count=10, rng=None, in_edges=False):
        out = self.call(
            "sample_neighbor",
            [
                np.asarray(ids, np.uint64),
                _types(edge_types),
                count,
                _seed(rng),
                in_edges,
            ],
        )
        return _bool_mask(out, 3)

    def sample_neighbor_rows(self, ids, edge_types=None, count=10, rng=None):
        nbr, mask, rows = self.call(
            "sample_nb_rows",
            [np.asarray(ids, np.uint64), _types(edge_types), int(count),
             _seed(rng)],
        )
        return nbr, mask.astype(bool), rows

    def unit_edge_weights(self, edge_types=None) -> bool:
        # None (all types) and [] (no types) answer differently — keep
        # their cache entries distinct
        key = None if edge_types is None else tuple(_types(edge_types))
        if key not in self._unit_w:
            # fetch outside the lock (call() → _pick takes self._lock),
            # publish under it — concurrent misses fetch twice but can't
            # corrupt the dict mid-resize
            val = bool(
                self.call("unit_edge_weights", [_types(edge_types)])[0]
            )
            with self._lock:
                self._unit_w.setdefault(key, val)
        return self._unit_w[key]

    def get_full_neighbor(
        self, ids, edge_types=None, max_degree=None, in_edges=False, sort_by=None
    ):
        ids = np.asarray(ids, np.uint64)
        c = self._cached() if max_degree is not None else None
        if c is None:
            # cap-less responses are padded to the BATCH max degree —
            # per-id rows then depend on their neighbors in the request,
            # so only fixed-cap calls are cacheable
            out = self._full_nb_call(
                ids, edge_types, max_degree, in_edges, sort_by
            )
            return _bool_mask(out, 3)
        key = (
            "full_nb",
            None if edge_types is None else tuple(_types(edge_types)),
            int(max_degree),
            bool(in_edges),
            sort_by,
        )
        out = c.fetch(
            key,
            ids,
            lambda miss: self._full_nb_call(
                miss, edge_types, int(max_degree), in_edges, sort_by
            ),
        )
        return _bool_mask(out, 3)

    def _full_nb_call(self, ids, edge_types, max_degree, in_edges, sort_by):
        """One get_full_neighbor RPC, offering the varint neighbor-id
        plane (PR 16). Old servers ignore the trailing arg and answer
        raw u64; a u8 plane is the compact form, decoded (exact) BEFORE
        the caller's cache sees it — cached blocks stay plain u64."""
        req = [ids, _types(edge_types), max_degree, in_edges, sort_by]
        if _delta_wire():
            req.append("delta")
        out = self.call("get_full_neighbor", req)
        nbr = np.asarray(out[0])
        if nbr.dtype == np.uint8:
            from euler_tpu.distributed import codec

            flat = codec.decode_u64_delta(nbr.tobytes())
            out = list(out)
            out[0] = (
                flat.reshape(len(ids), -1)
                if flat.size
                else flat.reshape(len(ids), 0)
            )
        return out

    def get_top_k_neighbor(self, ids, edge_types=None, k=10, in_edges=False):
        out = self.call(
            "get_top_k_neighbor",
            [np.asarray(ids, np.uint64), _types(edge_types), k, in_edges],
        )
        return _bool_mask(out, 3)

    def degree_sum(self, ids, edge_types=None, in_edges=False):
        ids = np.asarray(ids, np.uint64)
        c = self._cached()
        if c is None:
            return self.call(
                "degree_sum", [ids, _types(edge_types), in_edges]
            )[0]
        key = (
            "deg",
            None if edge_types is None else tuple(_types(edge_types)),
            bool(in_edges),
        )
        return c.fetch(
            key,
            ids,
            lambda miss: [
                self.call("degree_sum", [miss, _types(edge_types), in_edges])[0]
            ],
        )[0]

    def sample_neighbor_layerwise(self, batch_ids, edge_types=None, count=128, rng=None):
        out = self.call(
            "sample_neighbor_layerwise",
            [
                np.asarray(batch_ids, np.uint64),
                _types(edge_types),
                count,
                _seed(rng),
            ],
        )
        return _bool_mask(out, 2)

    # -- index/condition surface (remote GQL has() etc.) -----------------

    def search_condition(self, dnf, node: bool = True) -> _RemoteCondition:
        dnf_json = _dnf_json(dnf)
        w = float(self.call("condition_weight", [dnf_json, bool(node)])[0])
        return _RemoteCondition(dnf_json, node, w)

    def sample_from_result(self, res: _RemoteCondition, count: int, rng=None):
        return self.call(
            "sample_node_with_condition",
            [int(count), res.dnf_json, -1, _seed(rng)],
        )[0]

    def sample_edges_from_result(
        self, res: _RemoteCondition, count: int, rng=None
    ):
        return self.call(
            "sample_edge_with_condition",
            [int(count), res.dnf_json, -1, _seed(rng)],
        )[0]

    def sample_node_with_condition(self, count, dnf, node_type=-1, rng=None):
        return self.call(
            "sample_node_with_condition",
            [int(count), _dnf_json(dnf), node_type, _seed(rng)],
        )[0]

    def sample_edge_with_condition(self, count, dnf, edge_type=-1, rng=None):
        return self.call(
            "sample_edge_with_condition",
            [int(count), _dnf_json(dnf), edge_type, _seed(rng)],
        )[0]

    def condition_mask(self, ids, dnf, node: bool = True):
        ids = np.asarray(ids, dtype=np.uint64)
        out = self.call(
            "condition_mask", [ids, _dnf_json(dnf), bool(node)]
        )[0]
        return out.astype(bool)

    def get_node_ids_by_condition(self, dnf):
        return self.call("node_ids_by_condition", [_dnf_json(dnf)])[0]

    def fanout_with_rows(self, ids, edge_types, counts, rng=None):
        """Fused multi-hop fanout in ONE client RPC (remote_op.cc:31-36
        parity): the server coordinates the per-hop shard scatter next to
        the data and returns every hop's ids/weights/types/masks plus
        shard-major feature-cache rows."""
        ids = np.asarray(ids, dtype=np.uint64)
        counts = [int(c) for c in counts]
        out = self.call(
            "sample_fanout",
            [ids, _types(edge_types), counts, _seed(rng)],
        )
        from euler_tpu.graph.store import split_hops

        ids_h, w_h, tt_h, mask_h, rows_h = split_hops(len(ids), counts, *out)
        return (
            ids_h,
            w_h,
            tt_h,
            [m.astype(bool) for m in mask_h],
            rows_h,
        )

    def sage_minibatch(
        self,
        batch_size,
        edge_types,
        counts,
        label=None,
        node_type=-1,
        rng=None,
        lean=True,
    ):
        """Whole training minibatch in ONE RPC: the server samples roots,
        runs the fused fanout, and fetches labels next to the data
        (SampleFanoutWithFeature parity,
        tf_euler/kernels/sample_fanout_with_feature_op.cc). Returns a dict:
        lean → {"lean": True, "roots", "feats" (int32 rows+1 concat over
        hops), "labels"}; full → {"lean": False, "roots", "hops":
        (ids, w, tt, mask, rows) per-hop lists, "labels"}.
        """
        return self._sage_mb_decode(
            self.call(*self._sage_mb_req(
                batch_size, edge_types, counts, label, node_type,
                _seed(rng), lean,
            )),
            [int(c) for c in counts],
        )

    def sage_minibatch_async(
        self,
        batch_size,
        edge_types,
        counts,
        label=None,
        node_type=-1,
        rng=None,
        lean=True,
    ):
        """Pipelined variant: returns a Future of sage_minibatch's dict.
        The seed is drawn HERE (caller thread) so a shared Generator is
        never touched from executor workers; decode runs in the worker."""
        seed = _seed(rng)
        counts_i = [int(c) for c in counts]
        op, values = self._sage_mb_req(
            batch_size, edge_types, counts, label, node_type, seed, lean
        )
        ex = self._executor()

        def run():
            return self._sage_mb_decode(self.call(op, values), counts_i)

        return ex.submit(run)

    @staticmethod
    def _sage_mb_req(
        batch_size, edge_types, counts, label, node_type, seed, lean
    ):
        return "sage_minibatch", [
            int(batch_size),
            _types(edge_types),
            [int(c) for c in counts],
            label,
            int(node_type),
            seed,
            bool(lean),
        ]

    @staticmethod
    def _sage_mb_decode(out, counts):
        if out[-1]:
            if len(out) == 5:  # weighted-lean: bf16 weights ride along
                return {
                    "lean": True,
                    "roots": out[0],
                    "feats": out[1],
                    "w": out[2],
                    "labels": out[3],
                }
            return {
                "lean": True,
                "roots": out[0],
                "feats": out[1],
                "labels": out[2],
            }
        from euler_tpu.graph.store import split_hops

        roots = out[0]
        ids_h, w_h, tt_h, mask_h, rows_h = split_hops(
            len(roots), counts, *out[1:6]
        )
        return {
            "lean": False,
            "roots": roots,
            "hops": (
                ids_h,
                w_h,
                tt_h,
                [m.astype(bool) for m in mask_h],
                rows_h,
            ),
            "labels": out[6],
        }

    # -- dense features: quantized wire (PR 16) -------------------------

    def _dense_wire_kind(self) -> str:
        """The wire dtype this handle asks dense replies in:
        EULER_TPU_PAGE_DTYPE unless the peer proved old (sticky f32)."""
        if not self._dense_wire:
            return "f32"
        from euler_tpu.distributed import codec

        return codec.page_dtype()

    @staticmethod
    def _dense_key(base: str, names, kind: str) -> tuple:
        # f32 keeps the pre-PR-16 key so warm caches survive the upgrade;
        # quantized blocks get their own keyspace (different structure)
        if kind == "f32":
            return (base, tuple(names))
        return (base, tuple(names), kind)

    def _dense_miss(
        self, verb: str, miss, names: list, kind: str,
        strict: bool = False,
    ) -> list:
        out = self.call(verb, [miss, names, kind])
        if len(out) == 1 and np.asarray(out[0]).dtype == np.float32:
            # a server predating the trailing wire-dtype arg ignored it
            # and answered the exact f32 block: degrade (sticky) and
            # keep the reply verbatim — bit-identical old behavior,
            # never a client-side re-quantization
            self._dense_wire = False
            if strict and kind != "f32":
                # mid-cache-fetch degrade (rolling upgrade: this miss
                # hit an old replica while the quantized key may hold
                # 3-part blocks from a new one): the 1-part block must
                # NOT enter the quantized keyspace — cache.fetch would
                # later assemble mixed tuple shapes. Abort before the
                # cache registers anything; the caller redoes the batch
                # on the exact f32 key.
                raise _DenseWireDegraded(verb)
        return out

    @staticmethod
    def _dense_decode(kind: str, parts: list) -> np.ndarray:
        """Wire/cache dense parts → f32 rows. A lone f32 part under a
        quantized kind is the degrade path's exact block — verbatim."""
        if len(parts) == 1 and np.asarray(parts[0]).dtype == np.float32:
            return parts[0]
        from euler_tpu.distributed import codec

        return codec.dequantize(kind, parts)

    def get_dense_feature(self, ids, names):
        ids = np.asarray(ids, np.uint64)
        kind = self._dense_wire_kind()
        c = self._cached()
        if c is None:
            return self._dense_decode(
                kind, self._dense_miss(
                    "get_dense_feature", ids, list(names), kind
                ) if kind != "f32" else [
                    self.call("get_dense_feature", [ids, list(names)])[0]
                ],
            )
        if kind == "f32":
            return c.fetch(
                ("dense", tuple(names)),
                ids,
                lambda miss: [
                    self.call("get_dense_feature", [miss, list(names)])[0]
                ],
            )[0]
        # the cache stores QUANTIZED blocks (that is the warm-cache byte
        # saving); dequantize after assembly, per fetch
        try:
            parts = c.fetch(
                self._dense_key("dense", names, kind),
                ids,
                lambda miss: self._dense_miss(
                    "get_dense_feature", miss, list(names), kind,
                    strict=True,
                ),
            )
        except _DenseWireDegraded:
            # an old replica answered mid-fetch (sticky downgrade just
            # landed): redo the whole batch on the exact f32 key
            return c.fetch(
                ("dense", tuple(names)),
                ids,
                lambda miss: [
                    self.call("get_dense_feature", [miss, list(names)])[0]
                ],
            )[0]
        return self._dense_decode(kind, parts)

    def get_dense_by_rows(self, rows, names):
        rows = np.asarray(rows, np.int64)
        kind = self._dense_wire_kind()
        c = self._cached()
        if c is None:
            return self._dense_decode(
                kind, self._dense_miss(
                    "get_dense_by_rows", rows, list(names), kind
                ) if kind != "f32" else [
                    self.call("get_dense_by_rows", [rows, list(names)])[0]
                ],
            )
        if kind == "f32":
            return c.fetch(
                ("dense_rows", tuple(names)),
                rows,
                lambda miss: [
                    self.call("get_dense_by_rows", [miss, list(names)])[0]
                ],
            )[0]
        try:
            parts = c.fetch(
                self._dense_key("dense_rows", names, kind),
                rows,
                lambda miss: self._dense_miss(
                    "get_dense_by_rows", miss, list(names), kind,
                    strict=True,
                ),
            )
        except _DenseWireDegraded:
            return c.fetch(
                ("dense_rows", tuple(names)),
                rows,
                lambda miss: [
                    self.call("get_dense_by_rows", [miss, list(names)])[0]
                ],
            )[0]
        return self._dense_decode(kind, parts)

    def get_dense_feature_udf(self, ids, names, udfs):
        """Server-side UDF aggregation (udf.h API_GET_P semantics): the
        owning shard runs the UDF and the wire carries only the
        aggregate columns, not the feature block."""
        out = self.call(
            "dense_feature_udf",
            [np.asarray(ids, np.uint64), list(names), list(udfs)],
        )
        return out[0], out[1]

    def get_sparse_feature(self, ids, names, max_len=None):
        ids = np.asarray(ids, np.uint64)
        c = self._cached() if max_len is not None else None
        if c is None:
            # cap-less responses pad to the batch max length — per-id
            # rows then depend on the rest of the request (same rule as
            # get_full_neighbor): not cacheable
            flat = self.call(
                "get_sparse_feature", [ids, list(names), max_len]
            )
        else:
            flat = c.fetch(
                ("sparse", tuple(names), int(max_len)),
                ids,
                lambda miss: self.call(
                    "get_sparse_feature", [miss, list(names), int(max_len)]
                ),
            )
        return [
            (flat[2 * i], flat[2 * i + 1].astype(bool))
            for i in range(len(names))
        ]

    @staticmethod
    def _binary_from_wire(flat: list, n_names: int) -> list[list[bytes]]:
        """Wire (offsets, u8 blob) pairs → per-name lists of bytes."""
        out = []
        for i in range(n_names):
            offs, blob = flat[2 * i], flat[2 * i + 1].tobytes()
            out.append(
                [bytes(blob[offs[j] : offs[j + 1]]) for j in range(len(offs) - 1)]
            )
        return out

    def get_binary_feature(self, ids, names):
        ids = np.asarray(ids, np.uint64)
        c = self._cached()
        fetch = lambda sub: self._binary_from_wire(
            self.call("get_binary_feature", [sub, list(names)]), len(names)
        )
        if c is None:
            return fetch(ids)
        return c.fetch_objects(("bin", tuple(names)), ids, fetch)

    def get_edge_dense_feature(self, edge_ids, names):
        return self.call(
            "get_edge_dense_feature",
            [np.asarray(edge_ids, np.uint64), list(names)],
        )[0]

    def get_edge_sparse_feature(self, edge_ids, names, max_len=None):
        flat = self.call(
            "get_edge_sparse_feature",
            [np.asarray(edge_ids, np.uint64), list(names), max_len],
        )
        return [
            (flat[2 * i], flat[2 * i + 1].astype(bool))
            for i in range(len(names))
        ]

    def get_edge_binary_feature(self, edge_ids, names):
        flat = self.call(
            "get_edge_binary_feature",
            [np.asarray(edge_ids, np.uint64), list(names)],
        )
        out = []
        for i in range(len(names)):
            offs, blob = flat[2 * i], flat[2 * i + 1].tobytes()
            out.append(
                [blob[offs[j] : offs[j + 1]] for j in range(len(offs) - 1)]
            )
        return out

    def get_graph_by_label(self, label_ids):
        return self.call(
            "get_graph_by_label", [np.asarray(label_ids, np.int64)]
        )[0]

    def random_walk(self, ids, edge_types=None, walk_len=3, p=1.0, q=1.0, rng=None):
        return self.call(
            "random_walk",
            [
                np.asarray(ids, np.uint64),
                _types(edge_types),
                walk_len,
                p,
                q,
                _seed(rng),
            ],
        )[0]

    def _node2vec_step(self, cur, prev, edge_types, p, q, rng):
        return self.call(
            "node2vec_step",
            [
                np.asarray(cur, np.uint64),
                np.asarray(prev, np.uint64),
                _types(edge_types),
                p,
                q,
                _seed(rng),
            ],
        )[0]


def _dnf_json(dnf) -> str:
    """Serialize a DNF condition ([[ (field, op, value), ...], ...]) to
    JSON for the wire; numpy scalars become plain Python values."""
    if dnf is None:
        return json.dumps(None)
    clean = lambda v: v.item() if hasattr(v, "item") else v
    return json.dumps(
        [[[f, o, clean(v)] for f, o, v in clause] for clause in dnf]
    )


def _types(edge_types):
    return None if edge_types is None else [int(t) for t in edge_types]


def _delta_wire() -> bool:
    """Whether this client OFFERS varint neighbor planes — rides the
    stream-codec knob, so EULER_TPU_WIRE_CODEC=id is one switch back to
    raw wire everywhere (the bench's uncompressed A/B leg)."""
    from euler_tpu.distributed import codec

    return codec.wire_codec() != codec.IDENTITY


def _bool_mask(out: list, idx: int):
    out = list(out)
    out[idx] = out[idx].astype(bool)
    return tuple(out)


def connect(
    registry_path: str | None = None,
    cluster: dict[int, list[tuple[str, int]]] | None = None,
    num_shards: int | None = None,
    timeout: float = 30.0,
    watch: bool | None = None,
) -> Graph:
    """Build a Graph whose shards are remote.

    Either `cluster` (static {shard: [(host, port), ...]}) or
    `registry_path` (+ num_shards) must be given — the static-topology and
    ZK-monitor modes of the reference client (query_proxy.cc:60-144).

    Registry mode additionally starts a topology watch (the ZK
    children-watch parity, disable with watch=False): a daemon thread
    re-reads the registry every EULER_TPU_TOPOLOGY_REFRESH_S (default
    2s) and syncs each shard's replica set — dead replicas drop off
    after their heartbeat lapses, replacements on NEW ports join, and
    surviving replicas keep their quarantine state. Supervisors
    therefore no longer need to respawn crashed servers on their old
    fixed ports. `graph.stop_topology_watch()` stops it.
    """
    registry = None
    if cluster is None:
        if registry_path is None or num_shards is None:
            raise ValueError("need cluster= or (registry_path=, num_shards=)")
        registry = make_registry(registry_path)
        cluster = registry.wait_for(num_shards, timeout)
    shards = [
        RemoteShard(s, cluster[s]) for s in sorted(cluster)
    ]
    # any shard can answer get_meta (the meta describes the whole graph):
    # fall through the shard list so cluster bring-up order — shard 0's
    # replicas still booting or already dead — can't wedge the client
    meta_json = None
    err: Exception | None = None
    for sh in shards:
        try:
            meta_json = sh.call("get_meta", [])[0]
            break
        except RpcError as e:
            err = e
    if meta_json is None:
        raise RpcError(
            f"connect: get_meta failed on every shard"
            f" ({len(shards)} tried): {err}"
        )
    meta = GraphMeta.from_dict(json.loads(meta_json))
    g = Graph(meta, shards)
    g.stop_topology_watch = lambda: None  # static clusters: no watch
    if registry is not None and (watch is None or watch):
        stop = threading.Event()
        period = float(
            os.environ.get("EULER_TPU_TOPOLOGY_REFRESH_S", "2.0")
        )
        topo0 = registry.topology() if hasattr(registry, "topology") else None
        state = {
            "shards": shards,
            "gen": int(topo0.get("gen", 0)) if topo0 else 0,
        }

        def _watch():
            while not stop.wait(period):
                # elastic resharding (PR 19): a committed topology with a
                # new (num_shards, gen) re-points EVERY handle the caller
                # holds — fresh RemoteShards, fresh meta, one
                # swap_topology — so trainers/writers/servers re-route
                # without reconnecting. The registry's gen filter makes
                # this atomic: the same lookup that reveals the new
                # members hides the old ones.
                try:
                    topo = (
                        registry.topology()
                        if hasattr(registry, "topology") else None
                    )
                    if topo and (
                        int(topo.get("gen", 0)) != state["gen"]
                        or int(topo["num_shards"]) != len(state["shards"])
                    ):
                        n2 = int(topo["num_shards"])
                        table = registry.wait_for(n2, timeout=period * 2)
                        new_shards = [
                            RemoteShard(s, table[s]) for s in sorted(table)
                        ]
                        meta2 = GraphMeta.from_dict(
                            json.loads(
                                new_shards[0].call("get_meta", [])[0]
                            )
                        )
                        g.swap_topology(meta2, new_shards)
                        state["shards"] = new_shards
                        state["gen"] = int(topo.get("gen", 0))
                        continue
                    table = registry.lookup(len(state["shards"]))
                except (OSError, RuntimeError, TimeoutError):
                    continue  # registry briefly down: keep current set
                for sh in state["shards"]:
                    sh.sync_replicas(table.get(sh.shard, []))

        threading.Thread(
            target=_watch, daemon=True, name="topology-watch"
        ).start()
        g.stop_topology_watch = stop.set
    return g
