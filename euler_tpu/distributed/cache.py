"""Deterministic client-side read cache for the remote graph path.

Euler 2.0 hides hot-node re-reads behind a client query-proxy cache
(euler/client/query_proxy.cc); this is that cache for the TPU build's
wire protocol. Power-law graphs re-touch the same hot nodes every
batch, so without it every RPC re-ships bytes the client already holds.

Scope discipline — the A/B contract of this repo is that fused, per-op,
and cached paths are BIT-IDENTICAL under the same seeds — restricts the
cache to deterministic reads only: `lookup`, `node_type`, dense/sparse/
binary features, `get_full_neighbor` (fixed cap), `degree_sum`. Seeded
sampling verbs never touch it.

Shape:

- sharded-lock LRU: N stripes, each its own ``threading.Lock`` +
  ``OrderedDict`` + byte counter, so concurrent readers on different id
  ranges never serialize on one lock. Stripe of an id is ``id % N``.
- entries are PER-ID blocks keyed ``(cache key, id)`` where the cache
  key is ``(verb, names/args...)``: one row of a dense response, one
  capped neighbor row set, one degree. Blocks are stored as raw bytes
  (copied OUT of the wire frame, so a few cached rows never pin a
  multi-MB borrowed recv buffer) and reassembled with one
  ``b"".join`` + ``np.frombuffer`` per component — no per-id array
  stacking on the hot path.
- negative entries come free: a missing id's block IS the deterministic
  value the server returns for it (-1 row, zero features, empty
  neighbor set), so repeated misses of absent ids cost zero RPCs.
- size-bounded: ``EULER_TPU_READ_CACHE_MB`` (per shard handle) divided
  across stripes; inserting past the stripe budget evicts LRU entries.
  A single block bigger than a stripe's budget is simply not cached.
- staleness: the server's ``stats`` verb carries a ``graph_epoch``
  field. ``observe_epoch`` invalidates everything on mismatch; servers
  predating the field report nothing → epoch 0 → cache-forever, which
  is exactly right for their immutable stores.

Request-side dedup rides the same entry point: ``fetch`` uniques the
requested ids before probing, fetches only the residual (miss) ids over
the wire, and scatters hits+fetches back by inverse index — so even a
fully-cold batch never ships a duplicate id or re-receives its row.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np

# fixed per-entry bookkeeping estimate (key tuple, OrderedDict node,
# bytes objects) added to each block's payload bytes for the budget
_ENTRY_OVERHEAD = 96

# "no epoch stamp passed" sentinel for insert_rows (None is a real
# epoch value: never-observed)
_UNSET = object()

# cache keys whose per-entry identifier is a LOCAL ROW, not a node id —
# targeted publish invalidation (advance_epoch) must match them against
# the merge's mutated-row set instead of its touched-id set
_ROW_KEYED = frozenset({"dense_rows", "ids_rows"})


def cache_enabled() -> bool:
    return os.environ.get("EULER_TPU_READ_CACHE", "1") != "0"


def cache_budget_bytes() -> int:
    return int(
        float(os.environ.get("EULER_TPU_READ_CACHE_MB", "64")) * (1 << 20)
    )


def epoch_refresh_s() -> float:
    """Seconds between graph_epoch re-polls (0 = check once per shard
    handle and trust it — the right default for immutable deployments)."""
    return float(os.environ.get("EULER_TPU_READ_CACHE_EPOCH_S", "0"))


class _Stripe:
    __slots__ = ("lock", "map", "bytes")

    def __init__(self):
        self.lock = threading.Lock()
        self.map: OrderedDict = OrderedDict()
        self.bytes = 0


class ReadCache:
    """Sharded-lock LRU of per-id blocks for deterministic remote reads."""

    def __init__(self, budget_bytes: int, stripes: int = 8):
        self.budget = max(int(budget_bytes), 1)
        self._stripes = [_Stripe() for _ in range(max(int(stripes), 1))]
        self._per_stripe = max(self.budget // len(self._stripes), 1)
        # per-key component layout: [(np.dtype, per-id shape, nbytes)].
        # Bounded by the handful of (verb, names) combos a run touches,
        # so it never needs eviction; guarded by its own lock.
        self._meta: dict[tuple, list] = {}
        self._meta_lock = threading.Lock()
        # epoch transitions (first observation, invalidation) are rare
        # and must be atomic — one lock, never held during fetches
        self._epoch_lock = threading.Lock()
        self.epoch: int | None = None
        # telemetry counters: GIL-racy increments are fine (same stance
        # as RemoteShard.rpc_count — they are telemetry, not invariants)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.bytes_saved = 0  # wire bytes a hit avoided re-shipping
        self.dedup_ids = 0  # duplicate ids removed before the wire
        self.dedup_bytes_saved = 0  # bytes those duplicates would ship

    @classmethod
    def from_env(cls) -> "ReadCache | None":
        return cls(cache_budget_bytes()) if cache_enabled() else None

    # -- epoch / invalidation -------------------------------------------

    def observe_epoch(self, epoch: int) -> None:
        """Record the server's graph_epoch; a CHANGE flushes everything
        (mutated graphs must never serve stale bytes). Epoch 0 — old
        servers without the field — means cache-forever."""
        epoch = int(epoch)
        flush = False
        with self._epoch_lock:
            if self.epoch is None:
                self.epoch = epoch
            elif epoch != self.epoch:
                self.epoch = epoch
                self.invalidations += 1
                flush = True
        if flush:
            self.clear()

    def advance_epoch(self, epoch: int, ids=None, rows=None) -> None:
        """Publish-driven epoch advance with EXACT invalidation: drop
        only the blocks the merge reported stale (``ids`` for id-keyed
        verbs, ``rows`` for row-keyed verbs like ``get_dense_by_rows``)
        and keep everything else warm across the epoch boundary.

        Falls back to a full flush when the targeted sets are unknown
        (``ids`` and ``rows`` both None) or when the epoch did not
        advance by exactly one from the last observed value — a skipped
        epoch means some publish's stale set was never seen, so nothing
        cached can be trusted. The epoch is published BEFORE any drop:
        a concurrent fetch that started under the old epoch then fails
        its insert-time epoch check instead of re-seeding stale bytes.
        """
        epoch = int(epoch)
        with self._epoch_lock:
            prior = self.epoch
            self.epoch = epoch
        if prior is not None and epoch == prior:
            return  # idempotent re-publish (retried publish_epoch)
        targeted = (
            (ids is not None or rows is not None)
            and prior is not None
            and epoch == prior + 1
        )
        with self._epoch_lock:  # counter shares observe_epoch's guard
            self.invalidations += 1
        if not targeted:
            self.clear()
            return
        id_set = (
            {int(x) for x in np.asarray(ids).reshape(-1)}
            if ids is not None
            else set()
        )
        row_set = (
            {int(x) for x in np.asarray(rows).reshape(-1)}
            if rows is not None
            else set()
        )
        for st in self._stripes:
            with st.lock:
                doomed = [
                    k
                    for k in st.map
                    if k[1] in (
                        row_set if k[0][0] in _ROW_KEYED else id_set
                    )
                ]
                for k in doomed:
                    b = st.map.pop(k)
                    st.bytes -= sum(len(c) for c in b) + _ENTRY_OVERHEAD

    def clear(self) -> None:
        for st in self._stripes:
            with st.lock:
                st.map.clear()
                st.bytes = 0

    # -- introspection ---------------------------------------------------

    @property
    def nbytes(self) -> int:
        return sum(st.bytes for st in self._stripes)

    def stats(self) -> dict:
        lookups = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / lookups, 4) if lookups else 0.0,
            "evictions": self.evictions,
            "invalidations": self.invalidations,
            "bytes": self.nbytes,
            "budget_bytes": self.budget,
            "bytes_saved": self.bytes_saved,
            "dedup_ids": self.dedup_ids,
            "dedup_bytes_saved": self.dedup_bytes_saved,
            "epoch": self.epoch,
        }

    # -- core ------------------------------------------------------------

    def _stripe_of(self, uniq: np.ndarray) -> np.ndarray:
        return (uniq.astype(np.int64, copy=False) % len(self._stripes)).astype(
            np.int64
        )

    def _probe(self, key: tuple, uniq: np.ndarray, promote: bool = True):
        """blocks[i] = stored block for uniq[i] (None = miss)."""
        blocks: list = [None] * len(uniq)
        stripe_ids = self._stripe_of(uniq)
        for s in np.unique(stripe_ids):
            st = self._stripes[int(s)]
            sel = np.nonzero(stripe_ids == s)[0]
            with st.lock:
                for i in sel:
                    k = (key, int(uniq[i]))
                    b = st.map.get(k)
                    if b is not None:
                        if promote:
                            st.map.move_to_end(k)
                        blocks[int(i)] = b
        return blocks

    def _insert(
        self, key: tuple, ids: np.ndarray, blocks: list, ep=None
    ) -> None:
        """Store blocks; `ep` is the epoch observed when their fetch
        STARTED. A publish that lands mid-fetch publishes the new epoch
        before dropping blocks, so the per-stripe `epoch != ep` check
        below rejects the stale insert — without it, a slow fetch could
        re-seed pre-publish bytes after the invalidation swept past
        (the cross-epoch-mix race the hammer test pins)."""
        stripe_ids = self._stripe_of(ids)
        for s in np.unique(stripe_ids):
            st = self._stripes[int(s)]
            sel = np.nonzero(stripe_ids == s)[0]
            with st.lock:
                if self.epoch != ep:
                    return  # fetched under a superseded epoch: drop
                for i in sel:
                    b = blocks[int(i)]
                    size = sum(len(c) for c in b) + _ENTRY_OVERHEAD
                    if size > self._per_stripe:
                        continue  # would evict the whole stripe for one row
                    k = (key, int(ids[i]))
                    old = st.map.pop(k, None)
                    if old is not None:
                        st.bytes -= sum(len(c) for c in old) + _ENTRY_OVERHEAD
                    st.map[k] = b
                    st.bytes += size
                    while st.bytes > self._per_stripe and st.map:
                        _, ev = st.map.popitem(last=False)
                        st.bytes -= sum(len(c) for c in ev) + _ENTRY_OVERHEAD
                        self.evictions += 1

    def covers(self, key: tuple, ids) -> bool:
        """True when EVERY id already has a block (no promotion, no
        telemetry) — lets planners skip fetch steps for fully-cached
        frontiers. Races with eviction are benign: the later fetch just
        pays a residual RPC."""
        ids = np.asarray(ids)
        if ids.size == 0:
            return True
        uniq = np.unique(ids.reshape(-1))
        return all(
            b is not None for b in self._probe(key, uniq, promote=False)
        )

    def fetch(self, key: tuple, ids, fetch_fn):
        """Deduplicated, cache-merged read of fixed-layout array results.

        ``fetch_fn(miss_ids) -> [arr, ...]`` with every component's
        leading dim == len(miss_ids) and a per-id layout that is constant
        for this key (the verb wrappers guarantee that by folding every
        shape-affecting argument — names, caps, max_len — into the key).
        Returns the components assembled for the FULL ``ids`` in order —
        bit-identical to ``fetch_fn(ids)``.
        """
        ids = np.asarray(ids)
        if ids.size == 0:
            return [np.asarray(a) for a in fetch_fn(ids)]
        ep = self.epoch  # stamp BEFORE the fetch (see _insert)
        uniq, inv = np.unique(ids.reshape(-1), return_inverse=True)
        blocks = self._probe(key, uniq)
        miss = [i for i, b in enumerate(blocks) if b is None]
        n_hit = len(uniq) - len(miss)
        if miss:
            fetched = [
                np.ascontiguousarray(a) for a in fetch_fn(uniq[np.asarray(miss)])
            ]
            meta = self._register_meta(key, fetched)
            for j, i in enumerate(miss):
                blocks[i] = tuple(a[j].tobytes() for a in fetched)
            self._insert(
                key, uniq[np.asarray(miss)], [blocks[i] for i in miss], ep
            )
        meta = self._meta[key]
        per_id = sum(m[2] for m in meta)
        out = []
        for k, (dt, shape, _nb) in enumerate(meta):
            buf = b"".join(b[k] for b in blocks)
            arr = np.frombuffer(buf, dtype=dt).reshape((len(uniq),) + shape)
            out.append(arr[inv])  # fancy index: fresh writable copy
        self.hits += n_hit
        self.misses += len(miss)
        self.bytes_saved += n_hit * per_id
        ndup = int(ids.size - len(uniq))
        self.dedup_ids += ndup
        self.dedup_bytes_saved += ndup * per_id
        return out

    def fetch_objects(self, key: tuple, ids, fetch_fn):
        """Like ``fetch`` for variable-length per-id payloads (binary
        features): ``fetch_fn(miss_ids) -> [[bytes per id], ...]`` (one
        list per component). Python-loop assembly — fine off the hot
        path."""
        ids = np.asarray(ids)
        if ids.size == 0:
            return [list(c) for c in fetch_fn(ids)]
        ep = self.epoch  # stamp BEFORE the fetch (see _insert)
        uniq, inv = np.unique(ids.reshape(-1), return_inverse=True)
        blocks = self._probe(key, uniq)
        miss = [i for i, b in enumerate(blocks) if b is None]
        n_hit = len(uniq) - len(miss)
        if miss:
            fetched = fetch_fn(uniq[np.asarray(miss)])
            for j, i in enumerate(miss):
                blocks[i] = tuple(c[j] for c in fetched)
            self._insert(
                key, uniq[np.asarray(miss)], [blocks[i] for i in miss], ep
            )
        ncomp = len(blocks[0])
        out = [[blocks[i][k] for i in inv] for k in range(ncomp)]
        miss_set = set(miss)
        self.hits += n_hit
        self.misses += len(miss)
        self.bytes_saved += sum(
            sum(len(c) for c in b)
            for i, b in enumerate(blocks)
            if i not in miss_set
        )
        self.dedup_ids += int(ids.size - len(uniq))
        return out

    def insert_rows(self, key: tuple, ids, *components, ep=_UNSET) -> None:
        """Client-side write-back: store already-received rows (e.g. a
        fused exec_plan response) under `key`. The caller's contract is
        that each row equals what the keyed verb would return for that
        id — which holds for any deterministic read the server answered.

        `ep` must be the epoch observed when the RESPONSE'S FETCH
        STARTED (capture it with `snapshot_epochs` before the RPC).
        Defaulting it to the insert-time epoch is only safe when no
        fetch separates capture from insert — a publish landing mid-
        flight would otherwise re-seed pre-publish bytes AFTER the
        invalidation swept past, stamped as the new epoch (the
        serve-under-mutation regression tests/test_delta.py pins)."""
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            return
        if ep is _UNSET:
            ep = self.epoch
        uniq, first = np.unique(ids, return_index=True)
        comps = [np.ascontiguousarray(a) for a in components]
        self._register_meta(key, comps)
        blocks = [tuple(a[i].tobytes() for a in comps) for i in first]
        self._insert(key, uniq, blocks, ep)

    def _register_meta(self, key: tuple, fetched: list) -> list:
        with self._meta_lock:
            meta = self._meta.get(key)
            if meta is None:
                meta = [
                    (a.dtype, a.shape[1:], a[:1].nbytes if len(a) else 0)
                    for a in fetched
                ]
                self._meta[key] = meta
            return meta


# process-wide telemetry for the dataflow-layer id coalescing
# (dataflow/base.py gather_unique): duplicates removed BEFORE any fetch,
# and the result bytes they would have re-shipped. GIL-racy increments —
# telemetry, not an invariant (the repo's standing counter stance).
GATHER_DEDUP = {"ids": 0, "bytes_saved": 0}


def note_gather_dedup(n_dup: int, row_bytes: int) -> None:
    GATHER_DEDUP["ids"] += int(n_dup)
    GATHER_DEDUP["bytes_saved"] += int(n_dup) * int(row_bytes)


def shard_caches(graph) -> list[ReadCache]:
    """Every shard-level ReadCache hanging off a Graph facade."""
    out = []
    for sh in getattr(graph, "shards", []) or []:
        c = getattr(sh, "_cache", None)
        if isinstance(c, ReadCache):
            out.append(c)
    return out


def graph_cache_stats(graph) -> dict | None:
    """Summed cache telemetry across a facade's remote shards (None when
    no shard carries a cache — local graphs, kill switch)."""
    caches = shard_caches(graph)
    if not caches:
        return None
    keys = (
        "hits", "misses", "evictions", "invalidations", "bytes",
        "budget_bytes", "bytes_saved", "dedup_ids", "dedup_bytes_saved",
    )
    agg = {k: sum(c.stats()[k] for c in caches) for k in keys}
    lookups = agg["hits"] + agg["misses"]
    agg["hit_rate"] = round(agg["hits"] / lookups, 4) if lookups else 0.0
    return agg


def clear_graph_caches(graph) -> None:
    for c in shard_caches(graph):
        c.clear()


def snapshot_epochs(graph) -> dict[int, object]:
    """Per-shard cache epochs, to capture BEFORE a fetch whose response
    will be written back (`seed_dense_rows(..., epochs=...)`): a
    write-back must carry the epoch its fetch STARTED under, or a
    publish landing mid-flight re-seeds pre-publish bytes after the
    invalidation sweep, stamped as current."""
    out: dict[int, object] = {}
    for s, sh in enumerate(getattr(graph, "shards", []) or []):
        c = getattr(sh, "_cache", None)
        if isinstance(c, ReadCache):
            out[s] = c.epoch
    return out


def seed_dense_rows(graph, ids, names, values, epochs=None) -> None:
    """Write dense feature rows that arrived via a FUSED plan response
    into the owning shards' read caches (keyed exactly like the
    `get_dense_feature` verb). Fused responses bypass the per-verb cache
    on the way in; seeding them keeps warm-plan runs able to skip their
    root feature step, and later direct fetches of the same hot ids free.

    `epochs` is `snapshot_epochs(graph)` captured BEFORE the plan RPC;
    without it the insert is stamped at insert time, which is only safe
    when no publish can land between the fetch and this call."""
    shards = getattr(graph, "shards", None)
    if not shards:
        return
    ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
    values = np.asarray(values)
    if ids.size == 0 or values.shape[0] != ids.size:
        return
    num = len(shards)
    owner = (ids % np.uint64(num)).astype(np.int64)
    key = ("dense", tuple(names))
    for s, sh in enumerate(shards):
        c = getattr(sh, "_cache", None)
        if not isinstance(c, ReadCache):
            continue
        sel = np.nonzero(owner == s)[0]
        if len(sel):
            ep = _UNSET if epochs is None else epochs.get(s)
            c.insert_rows(key, ids[sel], values[sel], ep=ep)


def dense_coverage(graph, ids, names) -> bool:
    """True when every shard's read cache already holds the dense rows
    for its subset of ``ids`` — the precondition for a plan to skip its
    root feature step entirely."""
    shards = getattr(graph, "shards", None)
    if not shards:
        return False
    ids = np.asarray(ids, dtype=np.uint64)
    num = len(shards)
    owner = (ids % np.uint64(num)).astype(np.int64)
    for s, sh in enumerate(shards):
        cov = getattr(sh, "cached_dense_coverage", None)
        if cov is None:
            return False
        sub = ids[owner == s]
        if len(sub) and not cov(sub, names):
            return False
    return True
