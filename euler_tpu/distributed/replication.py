"""Replicated graph shard groups — primary/backup over shipped WAL bytes.

Euler 2.0 serves each shard from multiple replicas under ZooKeeper
membership (PAPER.md L2/L4b); this is that availability story for the
mutable shards PRs 8-10 built. One replica group per shard: the PRIMARY
holds a term-numbered, TTL-renewed lease in the registry and is the only
member that accepts mutations; FOLLOWERS tail its WAL over the
`wal_ship` verb, append the raw record bytes verbatim, and replay them
through the same `graph/wal.py` staging/merge code — so every replica's
log is byte-identical, logical offsets are interchangeable, and the
stores are bit-identical by construction (the repo's determinism
discipline doing the heavy lifting Chain-Replication/Raft papers spend
pages on).

Roles and safety:

  lease    — `registry.acquire_lease("shard_<i>", "host:port", ttl)`;
             a NEW holder bumps the term. The primary renews every
             ttl/3 and considers itself fenced once (monotonic time of
             the last successful renew) + ttl passes — strictly before
             the server-side expiry any follower promotes on.
  fencing  — every mutation gates on `check_primary()`: followers and
             fenced ex-primaries answer the typed `NotPrimaryError`
             naming the current primary, which `GraphWriter` uses to
             re-route its keyed outbox (idempotency keys make the
             retry exactly-once across the failover). WAL records are
             term-stamped (`wal.wrap_term`), so divergent history is
             diagnosable from the log alone.
  election — on lease expiry, the live follower with the highest
             durable WAL position promotes (tie → lowest replica id),
             acquiring the lease with min_term = last-seen term + 1 so
             even a wiped registry cannot rewind the fencing clock.
             Peer positions come from registry heartbeat meta, which
             every member republishes live.
  quorum   — EULER_TPU_REPL_ACK=quorum (default) holds each mutation
             ack until ⌈R/2⌉ followers have durably shipped past the
             record (their next `wal_ship` from_pos is the implicit
             ack); `async` acks after the primary's fsync alone
             (windows of un-replicated tail may be discarded on
             failover); `off` additionally skips position bookkeeping.
  history  — each ship request carries a crc of the follower's log
             tail; a mismatch (an ex-primary holding never-replicated
             records) or a trimmed prefix makes the primary answer
             need_snapshot, and the follower re-bootstraps from the
             primary's newest publish-consistent snapshot over the
             wire, then the WAL suffix.

The coordinator is two daemon threads per replica: a lease loop (renew /
observe / elect) and a tail loop (ship / apply / bootstrap). Everything
observable rides the three deterministic verbs `wal_ship` / `wal_pos` /
`repl_status` (tables + dispatch arms + runtime twins per house rules).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import numpy as np

from euler_tpu.distributed import wire
from euler_tpu.distributed.errors import (
    NotPrimaryError,
    OverloadError,
    RpcError,
    from_wire,
)

# load-bearing verb table (wire-protocol checker + runtime parity twin):
# every verb this module puts on the wire
WIRE_VERBS = frozenset({
    "repl_status",
    "wal_pos",
    "wal_ship",
})


def ack_mode() -> str:
    """quorum | async | off (EULER_TPU_REPL_ACK, default quorum)."""
    mode = os.environ.get("EULER_TPU_REPL_ACK", "quorum").strip().lower()
    return mode if mode in ("quorum", "async", "off") else "quorum"


def lease_ttl_default() -> float:
    return float(os.environ.get("EULER_TPU_LEASE_TTL_S", "5.0"))


def _parse_addr(holder: str) -> tuple[str, int] | None:
    host, _, port = str(holder).rpartition(":")
    try:
        return (host, int(port)) if host else None
    except ValueError:
        return None


class _PrimaryLink:
    """One follower→primary connection (single-threaded: the tail loop
    owns it). Speaks the standard frame protocol; err frames surface as
    the typed exceptions the rest of the stack expects."""

    def __init__(self, host: str, port: int):
        self.host = str(host)
        self.port = int(port)
        self._sock: socket.socket | None = None
        # None until the first log-mode reply: True once the primary
        # answered the extended (codec-aware) wal_ship reply shape,
        # False for a pre-codec peer — the STICKY degrade bit. Only a
        # proven-new primary may be pipelined: an old primary reads each
        # request's from_pos as the durable ack, and a speculative
        # request would over-ack bytes not yet fsync'd here.
        self.new_proto: bool | None = None

    def _send(self, op: str, values: list, timeout_s: float | None = None):
        """Fire one request without waiting for its reply — the
        pipelining half; pair each _send with exactly one _recv."""
        to = (
            timeout_s if timeout_s is not None
            else float(os.environ.get("EULER_TPU_SHIP_TIMEOUT_S", "10.0"))
        )
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=to
            )
            self._sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
        self._sock.settimeout(to)
        wire.send_frame(self._sock, wire.encode_vectored(op, values))

    def _recv(self):
        payload = wire.read_frame(self._sock)
        if payload is None:
            raise ConnectionError("connection closed by peer")
        status, result = wire.decode(payload, borrow=True)
        if status == "err":
            raise from_wire(result[0])
        return result

    def _call(self, op: str, values: list, timeout_s: float | None = None):
        self._send(op, values, timeout_s)
        return self._recv()

    def close(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class ReplicaCoordinator:
    """Per-replica state machine: lease renewal, WAL tailing, election,
    and quorum-ack accounting for one GraphService."""

    def __init__(
        self,
        service,
        registry,
        replica_id: int,
        group_size: int,
        lease_ttl: float | None = None,
    ):
        self.service = service
        self.registry = registry
        self.rid = int(replica_id)
        self.group_size = max(int(group_size), 1)
        self.ttl = float(
            lease_ttl if lease_ttl is not None else lease_ttl_default()
        )
        self.group = f"shard_{service.shard}"
        self.role = "follower"
        self.term = 0
        self.primary_addr: tuple[str, int] | None = None
        # monotonic fencing clock: mutations are accepted only while
        # now < _lease_ok_until. The deadline is stamped from a time
        # captured BEFORE the successful acquire/renew RPC, so it is
        # always ≤ the server-side expiry a follower promotes on.
        self._lease_ok_until = 0.0
        self.ack_mode = ack_mode()
        self.ack_timeout = float(
            os.environ.get("EULER_TPU_REPL_ACK_TIMEOUT_S", "30.0")
        )
        # heartbeat meta: mutated IN PLACE — both registry backends
        # re-serialize it every beat, so peers read live positions
        self.heartbeat_meta = {
            "replica": self.rid, "role": self.role, "pos": 0, "term": 0,
        }
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # primary side: follower rid → durable shipped position
        self._pos_cond = threading.Condition()
        self._positions: dict[int, int] = {}
        # positions quorum committers are currently parked on (guarded
        # by _pos_cond) — shipper long-polls consult these via
        # ack_wanted() so a stale-ack request never stalls a commit
        self._commit_waiting: list[int] = []
        # shippers long-poll on this for the next committed record
        self._ship_cond = threading.Condition()
        self._link: _PrimaryLink | None = None
        # telemetry (GIL-racy increments fine — repo counter stance).
        # ship_bytes counts LOGICAL (decoded) log bytes — the catch-up
        # MB/s numerator; ship_wire_bytes counts what actually crossed
        # the wire, so the pair exposes the compression ratio.
        self.promotions = 0
        self.demotions = 0
        self.bootstraps = 0
        self.ship_batches = 0
        self.ship_bytes = 0
        self.ship_wire_bytes = 0
        self.ship_pipelined = 0

    # -- lifecycle -------------------------------------------------------

    def start(self):
        for name, fn in (
            ("lease", self._lease_loop), ("tail", self._tail_loop)
        ):
            t = threading.Thread(
                target=fn, daemon=True,
                name=f"shard{self.service.shard}-r{self.rid}-{name}",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        with self._ship_cond:
            self._ship_cond.notify_all()
        with self._pos_cond:
            self._pos_cond.notify_all()
        for t in self._threads:
            t.join(timeout=1.0)
        self._drop_link()

    # -- service-facing hooks --------------------------------------------

    def check_primary(self) -> None:
        """Raise NotPrimaryError unless this replica holds a live lease."""
        if self.role == "primary":
            if time.monotonic() < self._lease_ok_until:
                return
            role = "fenced"  # deposed-or-partitioned ex-primary
            primary = None
        else:
            role = self.role
            primary = self.primary_addr
        raise NotPrimaryError(
            NotPrimaryError.format(
                self.service.shard, role, self.term, primary
            )
        )

    def after_commit(self, pos: int) -> None:
        """Called by the primary after each WAL group-commit: wake
        long-polling shippers, then (quorum mode) hold this ack until
        ⌈R/2⌉ followers have durably shipped past `pos`."""
        with self._ship_cond:
            self._ship_cond.notify_all()
        if self.ack_mode != "quorum" or self.group_size <= 1:
            return
        needed = min((self.group_size + 1) // 2, self.group_size - 1)
        deadline = time.monotonic() + self.ack_timeout
        with self._pos_cond:
            self._commit_waiting.append(pos)
            try:
                while (
                    sum(1 for p in self._positions.values() if p >= pos)
                    < needed
                ):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise OverloadError(
                            f"quorum ack timeout: {needed} follower"
                            f" ack(s) past pos {pos} not reached within"
                            f" {self.ack_timeout}s"
                            f" (followers at {dict(self._positions)})"
                        )
                    self._pos_cond.wait(min(left, 0.1))
            finally:
                self._commit_waiting.remove(pos)

    def ack_wanted(self, ack_pos: int) -> bool:
        """True when a quorum committer is parked on a position past
        `ack_pos` — the shipper long-poll answers empty instead of
        parking such a request, so a pipelined follower whose durable
        ack trails its speculative from_pos can refresh the ack now."""
        with self._pos_cond:
            return any(p > ack_pos for p in self._commit_waiting)

    def note_follower(self, rid: int, pos: int) -> None:
        """A ship request's from_pos IS the follower's durable ack."""
        if rid == self.rid:
            return
        with self._pos_cond:
            if pos > self._positions.get(rid, -1):
                self._positions[rid] = int(pos)
                self._pos_cond.notify_all()

    def wait_for_append(self, from_pos: int, timeout_s: float) -> None:
        """Server-side long poll: block (briefly) until the log grows
        past `from_pos` or the timeout lapses. Predicate loop — a
        notify for an unrelated event never ends the poll early with
        no data."""
        wal = self.service._wal
        if wal is None:
            return
        deadline = time.monotonic() + max(min(timeout_s, 1.0), 0.0)
        with self._ship_cond:
            while wal.tell() <= from_pos and not self._stop.is_set():
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self._ship_cond.wait(left)

    def status(self) -> dict:
        with self._pos_cond:
            followers = {
                str(k): int(v) for k, v in sorted(self._positions.items())
            }
        pa = self.primary_addr
        return {
            "role": self.role,
            "term": int(self.term),
            "replica": self.rid,
            "group_size": self.group_size,
            "primary": f"{pa[0]}:{pa[1]}" if pa else None,
            "ack_mode": self.ack_mode,
            "followers": followers,
            "promotions": self.promotions,
            "demotions": self.demotions,
            "bootstraps": self.bootstraps,
            "ship_batches": int(self.ship_batches),
            "ship_bytes": int(self.ship_bytes),
            "ship_wire_bytes": int(self.ship_wire_bytes),
            "ship_pipelined": int(self.ship_pipelined),
        }

    # -- lease state machine ---------------------------------------------

    def _holder_str(self) -> str:
        return f"{self.service.host}:{self.service.port}"

    def _my_pos(self) -> int:
        wal = self.service._wal
        return int(wal.tell()) if wal is not None else 0

    def _lease_loop(self):
        while not self._stop.is_set():
            try:
                self._lease_step()
            except Exception:  # the loop must outlive any one bad step
                pass
            m = self.heartbeat_meta
            m["role"] = self.role
            m["term"] = int(self.term)
            if self.role == "primary":
                m["pos"] = self._my_pos()
            self._stop.wait(min(self.ttl / 3.0, 0.5))

    def _lease_step(self):
        holder = self._holder_str()
        if self.role == "primary":
            t0 = time.monotonic()
            try:
                ok = self.registry.renew(
                    self.group, holder, self.term, self.ttl
                )
            except (OSError, RuntimeError, ConnectionError, TimeoutError):
                # registry unreachable: keep serving until the fencing
                # clock (set from the LAST successful renew) runs out —
                # never mistake a dead registry for a lost lease, and
                # never outlive the window a follower may promote in
                return
            if ok:
                self._lease_ok_until = t0 + self.ttl
                return
            # renew refused: superseded, or the lease record is gone
            lease = self._observe()
            if lease is not None and lease["holder"] != holder:
                self._demote(lease)
                return
            got = self._try_acquire(min_term=self.term, t0=t0)
            if got is None and time.monotonic() >= self._lease_ok_until:
                self._demote(lease)
            elif got is not None:
                self._adopt_primary(got)
            return
        # follower path
        lease = self._observe()
        if lease is not None and float(lease["expires_in"]) > 0:
            if lease["holder"] == holder:
                self._adopt_primary(lease)
            else:
                self.term = max(self.term, int(lease["term"]))
                self.primary_addr = _parse_addr(lease["holder"])
            return
        self._elect(lease)

    def _observe(self):
        try:
            return self.registry.observe(self.group)
        except (OSError, RuntimeError, ConnectionError, TimeoutError):
            return None

    def _try_acquire(self, min_term: int, t0: float | None = None):
        t0 = time.monotonic() if t0 is None else t0
        try:
            lease = self.registry.acquire_lease(
                self.group, self._holder_str(), self.ttl,
                meta={"replica": self.rid}, min_term=int(min_term),
            )
        except (OSError, RuntimeError, ConnectionError, TimeoutError):
            return None
        if lease is not None:
            lease = dict(lease)
            lease["_t0"] = t0
        return lease

    def _elect(self, lapsed_lease):
        """The lease is absent or expired: promote if no live peer is a
        strictly better candidate — higher durable position, tie broken
        by lower replica id. The lapsed holder itself is excluded (it is
        suspected dead; if it is alive it re-acquires under its own
        min_term floor). Peer positions are heartbeat-meta reads, so a
        better-but-dead peer delays promotion at most one heartbeat TTL."""
        if lapsed_lease is not None:
            self.term = max(self.term, int(lapsed_lease["term"]))
        dead_holder = (
            lapsed_lease["holder"] if lapsed_lease is not None else None
        )
        try:
            peers = self.registry.members(self.service.shard)
        except (OSError, RuntimeError, ConnectionError, TimeoutError):
            return
        me = (self._my_pos(), -self.rid)
        for host, port, meta in peers:
            addr = f"{host}:{int(port)}"
            if addr == self._holder_str() or addr == dead_holder:
                continue
            try:
                cand = (
                    int(meta.get("pos", 0)),
                    -int(meta.get("replica", 1 << 30)),
                )
            except (TypeError, ValueError, AttributeError):
                continue
            if cand > me:
                return  # a better candidate is live; let it promote
        got = self._try_acquire(min_term=self.term + 1)
        if got is not None:
            self._adopt_primary(got)

    def _adopt_primary(self, lease):
        promoted = self.role != "primary"
        self.term = max(self.term, int(lease["term"]))
        self.role = "primary"
        self.primary_addr = (self.service.host, self.service.port)
        t0 = float(lease.get("_t0", time.monotonic()))
        self._lease_ok_until = t0 + self.ttl
        if promoted:
            self.promotions += 1
            self._drop_link()
            with self._pos_cond:
                # followers re-ack against THIS log; stale positions
                # from the previous reign must not satisfy a quorum
                self._positions.clear()
                self._pos_cond.notify_all()

    def _demote(self, lease):
        if self.role == "primary":
            self.demotions += 1
        self.role = "follower"
        self._lease_ok_until = 0.0
        if lease is not None:
            self.term = max(self.term, int(lease["term"]))
            self.primary_addr = _parse_addr(lease["holder"])
        else:
            self.primary_addr = None

    # -- follower tail loop ----------------------------------------------

    def _get_link(self, addr: tuple[str, int]) -> _PrimaryLink:
        link = self._link
        if (
            link is None
            or (link.host, link.port) != (addr[0], int(addr[1]))
        ):
            self._drop_link()
            link = self._link = _PrimaryLink(addr[0], addr[1])
        return link

    def _drop_link(self):
        link, self._link = self._link, None
        if link is not None:
            link.close()

    def _tail_loop(self):
        max_bytes = int(
            os.environ.get("EULER_TPU_SHIP_MAX_BYTES", str(1 << 20))
        )
        poll_ms = float(os.environ.get("EULER_TPU_SHIP_POLL_MS", "100.0"))
        while not self._stop.is_set():
            if self.role != "follower":
                self._stop.wait(0.05)
                continue
            addr = self.primary_addr
            if addr is None or addr == (
                self.service.host, self.service.port
            ):
                self._stop.wait(0.05)
                continue
            try:
                self._tail_once(addr, max_bytes, poll_ms)
            except (OSError, ConnectionError, ValueError, RuntimeError):
                # transport fault / primary died / local log raced a
                # role change: drop the link, re-observe, retry
                self._drop_link()
                self._stop.wait(0.1)

    @staticmethod
    def _ship_args(pos, max_bytes, rid, crc, clen, poll_ms, offer, ack):
        """wal_ship request: the first seven args are the PR-13 shape an
        old primary dispatches on positionally; the codec offer and the
        EXPLICIT durable ack ride as trailing args old primaries ignore
        (they fall back to reading from_pos as the ack, which is why
        pipelining stays off until the new reply shape is proven)."""
        return [pos, max_bytes, rid, "log", crc, clen, poll_ms, offer, ack]

    @staticmethod
    def _ship_payload(link: _PrimaryLink, reply) -> bytes:
        """Decode one log-mode reply's record bytes, learning (sticky)
        whether the primary speaks the codec-aware reply shape. Damaged
        compressed payloads raise ValueError — the tail loop treats that
        as a transport fault, never applies the bytes."""
        from euler_tpu.distributed import codec

        data = reply[1]
        raw = bytes(np.ascontiguousarray(data)) if len(data) else b""
        if len(reply) >= 6:
            link.new_proto = True
            # an expired long poll answers EMPTY and unframed (the
            # server only compresses non-empty batches): nothing to
            # decode, and handing b"" to decompress would turn every
            # idle poll cycle into a dropped link
            return codec.decompress(str(reply[4]), raw) if raw else b""
        link.new_proto = False
        return raw

    # unsynced pipelined batches a catch-up stream accumulates before a
    # group fsync advances the reported durable ack
    _SYNC_EVERY = 32

    def _tail_once(self, addr, max_bytes: int, poll_ms: float):
        from euler_tpu.distributed import codec

        link = self._get_link(addr)
        pipeline = (
            os.environ.get("EULER_TPU_SHIP_PIPELINE", "1") != "0"
        )
        offer = codec.wire_codec()
        pos, crc, clen = self.service.wal_tail_probe()
        try:
            reply = link._call(
                "wal_ship",
                self._ship_args(
                    pos, max_bytes, self.rid, crc, clen, poll_ms, offer,
                    pos,
                ),
            )
        except RpcError:
            # typed server verdict (e.g. the peer has no WAL, or an old
            # peer without the verb): back off, the lease loop decides
            self._drop_link()
            self._stop.wait(0.2)
            return
        # ship/apply loop, two pipelined modes against a proven-new
        # primary (the reply's log_end says which):
        #  - BEHIND (throughput): the next request goes out BEFORE this
        #    batch's apply — the primary's read+compress+send overlaps
        #    the follower's apply — and fsync is deferred across up to
        #    _SYNC_EVERY batches (the ack only advances after a sync);
        #    the lockstep request-fsync-reply gap capped catch-up MB/s.
        #  - CAUGHT UP (latency): apply+fsync FIRST, then park a
        #    request carrying the fresh durable ack — a quorum commit
        #    unblocks one send after the follower's fsync, with no
        #    re-handshake (tail-crc probe) on the write path.
        durable = pos  # last fsync-covered position (the ack we report)
        unsynced = 0
        try:
            while True:
                term, end = int(reply[0]), int(reply[2])
                need = bool(reply[3])
                if term < self.term:
                    # a fenced ex-primary still answering its old
                    # connections: its records must not enter our log
                    self._drop_link()
                    self._stop.wait(0.2)
                    return
                self.term = max(self.term, term)
                if need:
                    self._bootstrap(link)
                    return
                wire_len = int(len(reply[1]))
                blob = self._ship_payload(link, reply)
                if not blob:
                    self.heartbeat_meta["pos"] = int(pos)
                    return
                log_end = int(reply[6]) if len(reply) >= 7 else end
                can_pipe = (
                    pipeline
                    and link.new_proto
                    and self.role == "follower"
                    and not self._stop.is_set()
                )
                if can_pipe and end < log_end:
                    # behind: overlap — request first, deferred fsync.
                    # ack_pos stays at `durable` so quorum accounting
                    # never sees bytes not yet fsync'd here. No
                    # tail-crc on the speculative leg: same socket +
                    # same primary log makes the suffix continuous by
                    # construction; the next non-pipelined cycle
                    # re-runs the full handshake.
                    link._send(
                        "wal_ship",
                        self._ship_args(
                            end, max_bytes, self.rid, 0, 0, poll_ms,
                            offer, durable,
                        ),
                    )
                    newpos = self.service.apply_shipped(
                        blob, pos, durable=False
                    )
                    unsynced += 1
                    if unsynced >= self._SYNC_EVERY:
                        self.service._wal.sync()
                        durable, unsynced = newpos, 0
                elif can_pipe:
                    # caught up: durable append first so the next
                    # request's ack is fresh the moment the primary
                    # reads it — and send that ack from inside the
                    # apply, BEFORE the staging replay (durability is
                    # what the quorum certifies; the replay rides
                    # behind the commit path). Send faults are noted,
                    # never raised: the replay must run regardless or
                    # the appended records would skip the delta store.
                    sent = []

                    def _ack_now(newend):
                        try:
                            link._send(
                                "wal_ship",
                                self._ship_args(
                                    newend, max_bytes, self.rid, 0, 0,
                                    poll_ms, offer, newend,
                                ),
                            )
                            sent.append(newend)
                        except OSError:
                            pass

                    newpos = self.service.apply_shipped(
                        blob, pos, acked=_ack_now
                    )
                    durable, unsynced = newpos, 0
                    if not sent:
                        self._drop_link()
                        return
                    end = newpos  # the in-flight request resumes here
                else:
                    newpos = self.service.apply_shipped(blob, pos)
                    durable, unsynced = newpos, 0
                self.ship_batches += 1
                self.ship_bytes += len(blob)
                self.ship_wire_bytes += wire_len
                self.heartbeat_meta["pos"] = int(newpos)
                if not can_pipe:
                    return
                if newpos != end or self.role != "follower":
                    # partial-record tail (or a role change mid-batch):
                    # the in-flight request's from_pos no longer
                    # matches our log — resync through a fresh
                    # connection
                    self._drop_link()
                    return
                self.ship_pipelined += 1
                pos = end
                try:
                    reply = link._recv()
                except RpcError:
                    # typed verdict mid-stream (e.g. the primary
                    # demoted): same stance as the head-of-loop case
                    self._drop_link()
                    self._stop.wait(0.2)
                    return
        finally:
            if unsynced:
                # close the deferred-fsync window on EVERY exit — the
                # next handshake reports wal.tell() as its ack, which
                # must not outrun durability
                self.service._wal.sync()

    def _bootstrap(self, link: _PrimaryLink):
        """Install the primary's newest publish-consistent snapshot over
        the wire, then resume tailing its WAL suffix. When the primary
        has no snapshot but a complete log (base 0), fall back to the
        construction-time dataset partition and replay from 0."""
        from euler_tpu.distributed import codec
        from euler_tpu.graph import wal as walmod

        to = float(os.environ.get("EULER_TPU_BOOTSTRAP_TIMEOUT_S", "60.0"))
        try:
            reply = link._call(
                "wal_ship",
                [0, 0, self.rid, "snapshot", None, None, None,
                 codec.wire_codec()],
                timeout_s=to,
            )
        except RpcError:
            t, base, end, _ep = link._call("wal_pos", [])
            if int(base) == 0:
                self.service.reset_to_source()
                self.heartbeat_meta["pos"] = 0
                self.bootstraps += 1
                return
            raise
        term, epoch, wal_pos = int(reply[0]), int(reply[1]), int(reply[2])
        head = json.loads(reply[4])
        if isinstance(head, dict):
            # v2 (codec-aware) bootstrap: the header names the codec and
            # each array's dtype/shape; blobs arrive framed+compressed.
            # Any damage surfaces as ValueError before install.
            use = str(head["codec"])
            applied = walmod._applied_from_blob(
                codec.decompress(
                    use, bytes(np.ascontiguousarray(reply[3]))
                )
            )
            arrays = {}
            for n, dt, shape, blob in zip(
                head["names"], head["dtypes"], head["shapes"], reply[5:]
            ):
                raw = codec.decompress(
                    use, bytes(np.ascontiguousarray(blob))
                )
                arrays[n] = np.frombuffer(raw, np.dtype(dt)).reshape(
                    shape
                ).copy()
        else:
            # pre-codec primary: [.., applied_blob, names_json, *arrays]
            applied = walmod._applied_from_blob(
                bytes(np.ascontiguousarray(reply[3]))
            )
            arrays = {
                n: np.array(a, copy=True) for n, a in zip(head, reply[5:])
            }
        # install_snapshot writes a local snapshot through the full
        # write_snapshot commit discipline (per-file fsync + dir fsync +
        # atomic rename) BEFORE returning, so by the time the position
        # below is published as this replica's durable ack, a restart of
        # this process recovers to it without re-bootstrapping — the ack
        # is never ahead of the disk.
        self.service.install_snapshot(epoch, arrays, applied, wal_pos)
        self.term = max(self.term, term)
        self.bootstraps += 1
        self.heartbeat_meta["pos"] = int(wal_pos)
