"""Retry discipline: deadlines, exponential backoff, retry budgets.

The reference pairs its 10x retry loop with bad-host quarantine and timed
revival (rpc_manager.h:66-124, rpc_client.h:32-66); this module supplies
the discipline AROUND that loop that the reference gets from gRPC:

  RetryPolicy — per-call deadline (EULER_TPU_RPC_TIMEOUT_S replaces the
                old hardcoded 30 s socket timeout), per-attempt socket
                timeout, exponential backoff with DETERMINISTIC seeded
                jitter (same seed -> same schedule, so failure tests
                replay bit-identically), attempt cap.
  RetryBudget — per-shard token bucket that stops retry storms: each
                transport retry spends a token, each success refills a
                fraction; when the bucket is dry the call fails fast
                instead of joining a thundering herd against a shard
                that is already down.

Everything here is pure policy — no sockets — so it is unit-testable
without a cluster and shared by the graph and serving clients.
"""

from __future__ import annotations

import itertools
import os
import threading
from dataclasses import dataclass

import numpy as np

# Replaces the hardcoded 30 s socket timeout: the default budget for one
# logical call INCLUDING retries and backoff. Also the connect timeout.
DEFAULT_TIMEOUT_S = 30.0


def default_timeout_s() -> float:
    """The configured per-call deadline (EULER_TPU_RPC_TIMEOUT_S)."""
    return float(os.environ.get("EULER_TPU_RPC_TIMEOUT_S", DEFAULT_TIMEOUT_S))


@dataclass
class RetryPolicy:
    """Backoff + deadline policy for one client (shard handle).

    retries=0 means "defer to the caller's attempt cap" (RemoteShard keeps
    its RETRIES class attribute so existing tests/tuning keep working).
    """

    retries: int = 0
    timeout_s: float | None = None  # None -> default_timeout_s() per call
    attempt_timeout_s: float = 10.0
    backoff_base_s: float = 0.02
    backoff_mult: float = 2.0
    backoff_max_s: float = 2.0
    jitter: float = 0.5  # fraction of each backoff that is randomized
    seed: int = 0

    def __post_init__(self):
        # per-call jitter streams: SeedSequence([seed, call#]) — drawing
        # never touches shared Generator state, so concurrent calls stay
        # deterministic given their call index
        self._call_ids = itertools.count()

    @classmethod
    def from_env(cls, seed: int = 0) -> "RetryPolicy":
        e = os.environ.get
        return cls(
            retries=int(e("EULER_TPU_RPC_RETRIES", 0)),
            attempt_timeout_s=float(e("EULER_TPU_RPC_ATTEMPT_TIMEOUT_S", 10.0)),
            backoff_base_s=float(e("EULER_TPU_RPC_BACKOFF_S", 0.02)),
            seed=seed,
        )

    def deadline_budget_s(self, deadline_s: float | None) -> float:
        if deadline_s is not None:
            return float(deadline_s)
        if self.timeout_s is not None:
            return float(self.timeout_s)
        return default_timeout_s()

    def call_rng(self) -> np.random.Generator:
        """A fresh deterministic jitter stream for one logical call."""
        return np.random.default_rng(
            np.random.SeedSequence([self.seed, next(self._call_ids)])
        )

    def backoff_s(self, attempt: int, rng: np.random.Generator) -> float:
        """Sleep before retry `attempt` (attempt 0 = first retry)."""
        base = min(
            self.backoff_max_s,
            self.backoff_base_s * self.backoff_mult**attempt,
        )
        if self.jitter <= 0:
            return base
        u = float(rng.random())
        return base * (1.0 - self.jitter + self.jitter * u)


class RetryBudget:
    """Token bucket bounding transport retries per shard.

    gRPC retry-throttling semantics: spend 1 token per retry, refill
    `refill` per successful call, never above `cap`. A dry bucket means
    the shard is systematically failing — more retries would only add
    load exactly when the shard can least absorb it, so fail fast and
    let quarantine + timed revival do their job.
    """

    def __init__(self, cap: float = 16.0, refill: float = 0.5):
        self.cap = float(cap)
        self.refill = float(refill)
        self._lock = threading.Lock()
        self._tokens = float(cap)
        self._denied = 0

    @property
    def tokens(self) -> float:
        return self._tokens

    @property
    def denied(self) -> int:
        """Spends refused by a dry bucket — the storms that did NOT
        happen (retry storms for the RPC retry loop, hedge storms for
        the serving router); dashboards watch this to see a budget
        actively protecting a degraded fleet."""
        return self._denied

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            self._denied += 1
            return False

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.refill)
