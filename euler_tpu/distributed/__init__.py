from euler_tpu.distributed.client import RemoteShard, RpcError, connect  # noqa: F401
from euler_tpu.distributed.cache import (  # noqa: F401
    ReadCache,
    clear_graph_caches,
    graph_cache_stats,
)
from euler_tpu.distributed.chaos import Fault, FaultPlan  # noqa: F401
from euler_tpu.distributed.errors import (  # noqa: F401
    DeadlineExceeded,
    OverloadError,
)
from euler_tpu.distributed.registry import Registry  # noqa: F401
from euler_tpu.distributed.retry import RetryBudget, RetryPolicy  # noqa: F401
from euler_tpu.distributed.service import GraphService, serve_shard  # noqa: F401
from euler_tpu.distributed.supervisor import ShardSupervisor  # noqa: F401
from euler_tpu.distributed.rendezvous import (  # noqa: F401
    RendezvousServer,
    TcpRegistry,
    make_registry,
)
