"""GraphWriter — the batched client of the streaming-mutation lane.

Euler 2.0's builder surface lets "millions of users generating events"
rebuild the graph while trainers read it; `GraphWriter` is that write
path against this repo's shards. It buffers mutation verbs client-side,
scatters them to their owner shards (nodes by ``id % P``, out-edges by
``src % P``, in-edges by ``dst % P`` — the builder's partition
invariant), and ships them over the standard RPC stack, so every batch
rides the PR-4 deadline envelope, typed-error discipline, and transport
retry loop.

Retry safety: each batch RPC carries a per-batch idempotency key drawn
once when the batch enters the outbox. A transport retry (or a
re-`flush()` after a partial failure) re-sends the SAME key, and the
server's applied-key window answers ``applied=False`` without staging —
a retried upsert can never double-apply. `publish()` carries its own
key the same way, so a publish whose response was lost replays the
recorded merge outcome instead of merging twice.

Reads stay epoch-consistent throughout: staged batches live in the
server-side delta overlay, invisible until `publish()` merges them and
bumps `graph_epoch`. After a publish the writer drives the client-side
handshake eagerly — `RemoteShard.on_publish` advances each shard's
ReadCache to the new epoch dropping EXACTLY the stale blocks the merge
reported, and the returned global row set is what device tables feed to
`refresh_rows` (dense or paged) to re-stage just the mutated rows.

Works against in-process graphs too (no servers): local shards get a
`DeltaStore` each and `publish()` merges + swaps `graph.shards[i]` in
one assignment — the same no-torn-snapshot discipline the server uses.

Typed failure semantics (OPERATIONS.md): `OverloadError` = delta buffer
full (publish first; never retried), `RpcError: unknown op ...` = the
peer predates the mutation verbs (fast-fail; the READ path of that
server is unaffected), transport faults = retried with the same
idempotency key.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

import numpy as np

from euler_tpu.distributed.errors import (
    NotPrimaryError,
    ReshardFencedError,
    RpcError,
)
from euler_tpu.graph.meta import GraphMeta


def _u64(x):
    return np.asarray(x, dtype=np.uint64).reshape(-1)


def _i32(x):
    return np.asarray(x, dtype=np.int32).reshape(-1)


def _f32(x):
    return np.asarray(x, dtype=np.float32).reshape(-1)


class GraphWriter:
    """Batched mutation client over a Graph facade (remote or local)."""

    # load-bearing verb table (wire-protocol checker + runtime parity
    # twin): every verb this client puts on the wire
    WIRE_VERBS = frozenset({
        "delete_edges",
        "get_meta",
        "publish_epoch",
        "repl_status",
        "upsert_edges",
        "upsert_nodes",
    })

    # NotPrimaryError redirects followed per batch before giving up —
    # bounds the wait for an in-flight election (lease TTLs are seconds)
    REDIRECT_CAP = 8

    def __init__(self, graph, batch_rows: int = 4096, writer_id: str | None = None):
        self.graph = graph
        self.batch_rows = max(int(batch_rows), 1)
        # unique per writer instance; uniqueness (not determinism) is
        # what idempotency keys need
        self._wid = writer_id or f"w{os.getpid()}-{os.urandom(4).hex()}"
        self._seq = itertools.count()
        self._lock = threading.Lock()
        # pending (pre-scatter) buffers
        self._pn: list = []  # (ids, types, weights, names, dense)
        self._pe: list = []  # (src, dst, tt, w)
        self._pd: list = []  # (src, dst, tt)
        self._pnd: list = []  # node-delete ids (local graphs only)
        self._pending_rows = 0
        # keyed outbox: batches that already own an idempotency key but
        # are not yet acked — a re-flush after a failure re-sends THESE
        # entries with their original keys
        self._outbox: list = []  # (key, shard_idx, verb, values, scatter_P)
        self._local_deltas: dict = {}
        self._closed = False
        # replica groups: per-shard primary hint (host, port) — learned
        # from NotPrimaryError redirects / repl_status discovery and
        # passed as call(prefer=) so mutations pin the primary while
        # reads keep round-robining the whole replica set
        self._primaries: dict[int, tuple[str, int]] = {}
        # telemetry (GIL-racy increments fine — repo counter stance)
        self.batches_sent = 0
        self.rows_sent = 0
        self.publishes = 0
        self.redirects = 0

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Flush the pending outbox, then seal the writer.

        Staged-but-unflushed batches are NEVER silently dropped: close
        sends them, and any failure surfaces typed (the outbox keeps the
        unsent entries under their original idempotency keys, so a
        caller that handles the error can flush() again before the
        writer goes away). Idempotent; staging after close raises."""
        if self._closed:
            return
        try:
            self.flush()
        finally:
            # sealed even when flush raised: the error told the caller
            # exactly what was at risk, and a retried flush() on the
            # original keys is still safe — but NEW batches must not
            # quietly pile into a writer that is being torn down
            self._closed = True

    def __enter__(self) -> "GraphWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()  # flush errors surface to the caller, typed
        else:
            # the body already failed — try to save the staged batches,
            # but never mask the original error with a flush failure
            try:
                self.close()
            except Exception:
                pass

    def _ensure_open(self) -> None:
        if self._closed:
            raise ValueError("GraphWriter is closed")

    @property
    def num_shards(self) -> int:
        """Live shard count — read through to the facade every time so a
        reshard (swap_topology) is picked up by the next scatter instead
        of being frozen at construction."""
        return len(self.graph.shards)

    # -- buffering --------------------------------------------------------

    def upsert_nodes(self, ids, types=None, weights=None, dense=None) -> int:
        """Buffer node upserts. `dense` is {feature_name: [n, dim]};
        provided features replace, missing ones keep their values (new
        nodes default them to zeros — builder semantics)."""
        self._ensure_open()
        ids = _u64(ids)
        n = len(ids)
        types = _i32(types if types is not None else np.zeros(n))
        weights = _f32(weights if weights is not None else np.ones(n))
        names: list = []
        block = None
        if dense:
            names = sorted(dense)
            block = np.concatenate(
                [
                    np.asarray(dense[nm], np.float32).reshape(n, -1)
                    for nm in names
                ],
                axis=1,
            )
        with self._lock:
            self._pn.append((ids, types, weights, names, block))
            self._pending_rows += n
        self._maybe_flush()
        return n

    def upsert_edges(self, src, dst, types=None, weights=None) -> int:
        self._ensure_open()
        src = _u64(src)
        dst = _u64(dst)
        n = len(src)
        types = _i32(types if types is not None else np.zeros(n))
        weights = _f32(weights if weights is not None else np.ones(n))
        with self._lock:
            self._pe.append((src, dst, types, weights))
            self._pending_rows += n
        self._maybe_flush()
        return n

    def delete_edges(self, src, dst, types=None) -> int:
        self._ensure_open()
        src = _u64(src)
        dst = _u64(dst)
        types = _i32(types if types is not None else np.zeros(len(src)))
        with self._lock:
            self._pd.append((src, dst, types))
            self._pending_rows += len(src)
        self._maybe_flush()
        return len(src)

    def delete_nodes(self, ids) -> int:
        """Local graphs only: node deletion is not a wire verb (the
        remote protocol streams node/edge upserts and edge deletes; node
        retirement is an offline rebuild concern)."""
        self._ensure_open()
        if any(hasattr(s, "call") for s in self.graph.shards):
            raise ValueError(
                "delete_nodes is not a wire verb — rebuild the remote "
                "shard offline, or stream edge deletes instead"
            )
        ids = _u64(ids)
        with self._lock:
            self._pnd.append(ids)
            self._pending_rows += len(ids)
        return len(ids)

    def _maybe_flush(self) -> None:
        with self._lock:
            full = self._pending_rows >= self.batch_rows
        if full:
            self.flush()

    # -- scatter / send ---------------------------------------------------

    def _key(self) -> str:
        return f"{self._wid}:{next(self._seq)}"

    def _stage_outbox(self) -> None:
        """Move pending buffers into keyed per-shard outbox entries.
        Keys are drawn HERE, once per entry — re-sending after a partial
        failure reuses them, which is what makes flush retry-safe."""
        with self._lock:
            pn, self._pn = self._pn, []
            pe, self._pe = self._pe, []
            pd, self._pd = self._pd, []
            pnd, self._pnd = self._pnd, []
            self._pending_rows = 0
        P = self.num_shards
        entries = []
        for ids, types, weights, names, block in pn:
            owner = (ids % np.uint64(P)).astype(np.int64)
            for s in np.unique(owner):
                sel = owner == s
                entries.append((
                    int(s),
                    "upsert_nodes",
                    [
                        ids[sel], types[sel], weights[sel], list(names),
                        block[sel] if block is not None else None,
                    ],
                ))
        for src, dst, tt, w in pe:
            o_owner = (src % np.uint64(P)).astype(np.int64)
            i_owner = (dst % np.uint64(P)).astype(np.int64)
            for s in range(P):
                osel = o_owner == s
                isel = i_owner == s
                if not (osel.any() or isel.any()):
                    continue
                entries.append((
                    s,
                    "upsert_edges",
                    [
                        src[osel], dst[osel], tt[osel], w[osel],
                        src[isel], dst[isel], tt[isel], w[isel],
                    ],
                ))
        for src, dst, tt in pd:
            o_owner = (src % np.uint64(P)).astype(np.int64)
            i_owner = (dst % np.uint64(P)).astype(np.int64)
            for s in range(P):
                osel = o_owner == s
                isel = i_owner == s
                if not (osel.any() or isel.any()):
                    continue
                entries.append((
                    s,
                    "delete_edges",
                    [
                        src[osel], dst[osel], tt[osel],
                        src[isel], dst[isel], tt[isel],
                    ],
                ))
        for ids in pnd:
            owner = (ids % np.uint64(P)).astype(np.int64)
            for s in np.unique(owner):
                entries.append((int(s), "delete_nodes", [ids[owner == s]]))
        with self._lock:
            for e in entries:
                # each entry remembers the shard count it was scattered
                # under: flush() re-splits stale entries when the
                # cluster resharded between staging and sending
                self._outbox.append((self._key(),) + e + (P,))

    def _local_delta(self, part: int):
        from euler_tpu.graph.delta import DeltaStore

        with self._lock:
            d = self._local_deltas.get(part)
            if d is None:
                d = self._local_deltas[part] = DeltaStore(
                    part, self.num_shards
                )
        return d

    def flush(self) -> int:
        """Send every outbox entry to its owner shard. Raises on the
        first failure with the unsent entries retained — a later flush
        (or publish) re-sends them under their ORIGINAL keys, so the
        whole call is retry-safe end to end.

        Reshard-aware: an entry scattered under P shards that is still
        in the outbox when the cluster reshards to P' is re-split by
        the CURRENT modulo (same idempotency key, so a half-sent batch
        stays exactly-once), and a ReshardFencedError mid-cutover is
        absorbed by waiting for the topology watch to re-route before
        re-scattering the batch."""
        self._stage_outbox()
        with self._lock:
            outbox = list(self._outbox)
        sent = 0
        for entry in outbox:
            key, shard_idx, verb, values, scatter_p = entry
            cur_p = self.num_shards
            if cur_p != scatter_p:
                # topology changed since staging: the old shard_idx is
                # meaningless — re-split the rows by the new modulo
                for dest, sub in self._resplit(verb, values, cur_p):
                    self._send_split(key, dest, verb, sub)
            else:
                self._send_split(key, shard_idx, verb, values)
            with self._lock:
                self._outbox.remove(entry)
            self.batches_sent += 1
            sent += 1
        return sent

    def _send_split(self, key: str, shard_idx: int, verb: str, values: list):
        """Deliver one (possibly re-split) batch to one shard, absorbing
        a fenced-cutover rejection by waiting for the new topology and
        re-scattering under it (original key — exactly-once holds: the
        reshard seeds dest applied-key windows from the sources)."""
        sh = self.graph.shards[shard_idx]
        if not hasattr(sh, "call"):
            d = self._local_delta(shard_idx)
            if verb == "upsert_nodes":
                d.stage_nodes(*values)
            elif verb == "upsert_edges":
                d.stage_edges(*values)
            elif verb == "delete_edges":
                d.stage_edge_deletes(*values)
            else:
                d.stage_node_deletes(*values)
            return
        if verb not in (
            "upsert_nodes", "upsert_edges", "delete_edges"
        ):  # guarded in delete_nodes()
            raise ValueError("delete_nodes is not a wire verb")
        # capture BEFORE the send: a topology swap racing the fence
        # rejection is then seen immediately instead of stalling the
        # wait loop for its full budget
        p0, te0 = self.num_shards, int(getattr(self.graph, "topology_epoch", 0))
        try:
            reply = self._send_mutation(sh, shard_idx, verb, [key] + values)
        except ReshardFencedError:
            # cutover in flight: the source refused the write so the
            # migrated tail stays bounded. Wait (bounded) for connect()'s
            # topology watch to swap the facade, then re-send by the new
            # modulo. If the reshard ABORTED instead, the wait times out
            # with the topology unchanged and the re-send goes back to
            # the (now unfenced) original shards.
            self._await_topology_change(p0, te0)
            cur_p = self.num_shards
            for dest, sub in self._resplit(verb, values, cur_p):
                sh2 = self.graph.shards[dest]
                r2 = self._send_mutation(sh2, dest, verb, [key] + sub)
                self.rows_sent += int(r2[0])
            return
        self.rows_sent += int(reply[0])

    def _await_topology_change(
        self, p0: int | None = None, te0: int | None = None
    ) -> bool:
        """Poll the facade for a topology swap (shard count or
        topology_epoch change) away from the captured (p0, te0) — pass
        values captured BEFORE the failed send so a swap that raced the
        rejection is seen at once — for up to
        EULER_TPU_RESHARD_WRITER_WAIT_S seconds (default 10). Returns
        True when a change was seen."""
        budget = float(os.environ.get("EULER_TPU_RESHARD_WRITER_WAIT_S", "10"))
        p0 = self.num_shards if p0 is None else int(p0)
        te0 = (
            int(getattr(self.graph, "topology_epoch", 0))
            if te0 is None else int(te0)
        )
        deadline = time.monotonic() + max(budget, 0.0)
        while time.monotonic() < deadline:
            if (
                self.num_shards != p0
                or int(getattr(self.graph, "topology_epoch", 0)) != te0
            ):
                return True
            time.sleep(0.05)
        return False

    @staticmethod
    def _resplit(verb: str, values: list, P: int) -> list:
        """Re-scatter one outbox entry's rows by `id % P` under a NEW
        shard count, preserving the writer wire layouts (out-half
        src-owned / in-half dst-owned for edge verbs)."""
        out: list = []
        if verb in ("upsert_nodes", "delete_nodes"):
            ids = values[0]
            owner = (ids % np.uint64(P)).astype(np.int64)
            for s in np.unique(owner):
                sel = owner == s
                if verb == "upsert_nodes":
                    _, types, weights, names, block = values
                    out.append((int(s), [
                        ids[sel], types[sel], weights[sel], list(names),
                        block[sel] if block is not None else None,
                    ]))
                else:
                    out.append((int(s), [ids[sel]]))
            return out
        if verb == "upsert_edges":
            osrc, odst, ott, ow, isrc, idst, itt, iw = values
            o_owner = (osrc % np.uint64(P)).astype(np.int64)
            i_owner = (idst % np.uint64(P)).astype(np.int64)
            for s in range(P):
                osel = o_owner == s
                isel = i_owner == s
                if not (osel.any() or isel.any()):
                    continue
                out.append((s, [
                    osrc[osel], odst[osel], ott[osel], ow[osel],
                    isrc[isel], idst[isel], itt[isel], iw[isel],
                ]))
            return out
        # delete_edges
        osrc, odst, ott, isrc, idst, itt = values
        o_owner = (osrc % np.uint64(P)).astype(np.int64)
        i_owner = (idst % np.uint64(P)).astype(np.int64)
        for s in range(P):
            osel = o_owner == s
            isel = i_owner == s
            if not (osel.any() or isel.any()):
                continue
            out.append((s, [
                osrc[osel], odst[osel], ott[osel],
                isrc[isel], idst[isel], itt[isel],
            ]))
        return out

    # -- replica-group routing --------------------------------------------

    def set_primary(self, shard_idx: int, addr: tuple[str, int]) -> None:
        """Pin shard `shard_idx`'s mutations to one replica address —
        normally learned automatically (NotPrimaryError redirects and
        repl_status discovery); exposed for operators and tests."""
        self._primaries[int(shard_idx)] = (str(addr[0]), int(addr[1]))

    def discover_primaries(self) -> dict[int, tuple[str, int]]:
        """Eagerly discover and pin every shard's primary (repl_status
        against any replica) — the first batch then lands on the lease
        holder instead of paying a NotPrimaryError redirect. Solo
        shards and shards mid-election are simply left unpinned."""
        for idx, sh in enumerate(self.graph.shards):
            if hasattr(sh, "call"):
                addr = self._discover_primary(sh)
                if addr is not None:
                    self.set_primary(idx, addr)
        return dict(self._primaries)

    def _discover_primary(self, sh) -> tuple[str, int] | None:
        """Ask any replica of this shard who the primary is. Returns
        None for solo shards, during an election, or on failure."""
        try:
            st = json.loads(sh.call("repl_status", [])[0])
        except (RpcError, OSError, ConnectionError):
            return None
        addr = st.get("primary")
        if not addr or ":" not in str(addr):
            return None
        host, _, port = str(addr).rpartition(":")
        try:
            return host, int(port)
        except ValueError:
            return None

    def _send_mutation(self, sh, shard_idx: int, verb: str, payload: list):
        """One mutation RPC with replica-group routing: pin the known
        primary when there is one, and on the typed NotPrimaryError
        re-route to the address the rejection names (re-discovering via
        repl_status while an election is in flight). The payload keeps
        its original idempotency key across every redirect, so the
        retry is exactly-once even when the first attempt's ack was
        lost to the failover."""
        last: Exception | None = None
        for attempt in range(self.REDIRECT_CAP):
            prefer = self._primaries.get(shard_idx)
            kw = {"prefer": prefer} if prefer is not None else {}
            try:
                # literal verbs: the wire-protocol checker diffs these
                # sends against the declared tables
                if verb == "upsert_nodes":
                    return sh.call("upsert_nodes", payload, **kw)
                if verb == "upsert_edges":
                    return sh.call("upsert_edges", payload, **kw)
                if verb == "delete_edges":
                    return sh.call("delete_edges", payload, **kw)
                if verb == "publish_epoch":
                    return sh.call("publish_epoch", payload, **kw)
                raise ValueError(f"not a mutation verb: {verb!r}")
            except NotPrimaryError as e:
                last = e
                self.redirects += 1
                addr = NotPrimaryError.parse_primary(str(e))
                if addr is not None and addr != prefer:
                    self._primaries[shard_idx] = addr
                    continue
                # primary=? (election in flight) or a hint the group
                # just rejected: drop it, give the election a beat,
                # then ask the group directly
                self._primaries.pop(shard_idx, None)
                time.sleep(min(0.1 * (attempt + 1), 0.5))
                addr = self._discover_primary(sh)
                if addr is not None:
                    self._primaries[shard_idx] = addr
        raise last

    # -- publish ----------------------------------------------------------

    def publish(self) -> dict:
        """Flush, then merge every shard's delta at an epoch boundary.

        Returns {"epochs": {shard: epoch}, "rows": global mutated rows
        (shard-major, int64; None when any shard reported an untrackable
        stale set), "ids": touched node ids (u64 or None), "num_nodes"}.
        `rows` feeds device-table `refresh_rows` (dense and paged);
        `ids`/`rows` drive the exact ReadCache invalidation — both
        already applied to remote shard handles before this returns."""
        self.flush()
        epochs: dict[int, int] = {}
        per_rows: list = []
        per_ids: list = []
        nn: list[int] = []
        exact = True
        for s, sh in enumerate(self.graph.shards):
            if hasattr(sh, "call"):
                p0 = self.num_shards
                te0 = int(getattr(self.graph, "topology_epoch", 0))
                try:
                    ep, rows, ids, n = self._send_mutation(
                        sh, s, "publish_epoch", [self._key()]
                    )[:4]
                except ReshardFencedError:
                    # cutover fenced this source mid-publish: wait for
                    # the topology swap, then publish the NEW shard set
                    # from scratch (a republish of already-merged shards
                    # is a no-op epoch-wise, so this is safe)
                    self._await_topology_change(p0, te0)
                    return self.publish()
                sh.on_publish(ep, rows=rows, ids=ids, num_nodes=int(n))
            else:
                delta = self._local_deltas.pop(s, None)
                if delta is None or delta.empty:
                    ep = int(getattr(sh, "graph_epoch", 0))
                    rows = np.empty(0, np.int64)
                    ids = np.empty(0, np.uint64)
                else:
                    new_store, rows, ids = sh.merge_delta(delta)
                    # ONE reference assignment — readers grab the shard
                    # once per call, so no torn snapshot (server parity)
                    self.graph.shards[s] = new_store
                    ep = int(new_store.graph_epoch)
                n = self.graph.shards[s].num_nodes
            epochs[s] = int(ep)
            nn.append(int(n))
            if rows is None or ids is None:
                exact = False
            else:
                per_rows.append(np.asarray(rows, np.int64))
                per_ids.append(np.asarray(ids, np.uint64))
        # shard-major globalization over the NEW per-shard row counts
        offsets = np.concatenate([[0], np.cumsum(nn)])
        if exact:
            rows_g = (
                np.concatenate(
                    [r + offsets[s] for s, r in enumerate(per_rows)]
                )
                if per_rows
                else np.empty(0, np.int64)
            )
            ids_g = (
                np.unique(np.concatenate(per_ids))
                if per_ids
                else np.empty(0, np.uint64)
            )
        else:
            rows_g = ids_g = None
        self._refresh_meta_weights()
        self.publishes += 1
        return {
            "epochs": epochs,
            "rows": rows_g,
            "ids": ids_g,
            "num_nodes": int(offsets[-1]),
        }

    def _refresh_meta_weights(self) -> None:
        """Re-sync the facade's shard-weighted root sampling with the
        merged weight sums (local merges updated the shared meta in
        place; remote merges updated the SERVER meta, re-read here)."""
        remote = next(
            (s for s in self.graph.shards if hasattr(s, "call")), None
        )
        if remote is not None:
            meta = GraphMeta.from_dict(
                json.loads(remote.call("get_meta", [])[0])
            )
            self.graph.meta.node_weight_sums = meta.node_weight_sums
            self.graph.meta.edge_weight_sums = meta.edge_weight_sums
        self.graph.refresh_shard_weights()

    def pending(self) -> dict:
        """Buffered-but-unsent row counts (client-side overlay view)."""
        with self._lock:
            return {
                "rows": self._pending_rows,
                "outbox_batches": len(self._outbox),
            }
