"""TrainingSession — durable, preemption-safe training over the Estimator.

The last major component without crash-safety was the trainer itself:
`kill -9` mid-save destroyed the only checkpoint, SIGTERM was a hard
kill, resume never restored the batch stream position, and a NaN burst
or a hung device step took the run down silently. This module mirrors
the PR 9 shard discipline for trainer state:

- **Atomic retained checkpoints** (`checkpoint.CheckpointStore`): every
  cadence step commits a `ckpt_<step>/` dir via tmp + fsync + rename +
  COMMIT marker, keep-N retained. A crash mid-save can never lose the
  previous complete checkpoint.
- **Async save off the step path**: the device never stalls on disk —
  the step loop only snapshots host copies (one bounded device_get at
  cadence); a background writer commits them. `EULER_TPU_SAVE_ASYNC=0`
  forces inline saves.
- **Bit-exact resume**: the checkpoint carries the step, the opt_state,
  the batch-source cursor (`ResumableSource.cursor`), and the per-shard
  graph-epoch book. Under the standing seed contract, train-2N-straight
  equals train-N + kill -9 + resume-N, params and per-step losses
  bit-identical — the RNG streams (`_base_key`/`_flow_key`) are folded
  per GLOBAL step, so only the step and the source cursor need
  restoring.
- **Anomaly guard**: a jitted all-finite check over (loss, updated
  params) every `guard_every` steps, against a NON-donating step
  program (the pre-step state must survive a rejected update — see
  `_step_fn`). Policy "skip" drops the poisoned update and keeps the
  position; "rollback" reverts to the last-good in-memory snapshot and
  retries (transient-fault recovery); "abort" raises immediately. A
  bounded strike cap turns a persistent burst into a typed
  `AnomalyError` instead of an infinite skip/rollback loop.
- **Hung-step watchdog**: with `step_deadline_s` set, each step
  (draw + dispatch + guard fetch) runs under a wall-clock deadline on a
  watchdog worker; expiry dumps all-thread stacks to a diagnostic file
  and raises typed `HungStepError` instead of hanging the run.
- **SIGTERM drain**: the handler finishes the in-flight step, drains
  the on-device loss history, flushes a final checkpoint, and returns
  with `preempted=True` — the trainer-side analog of the PR 4 server
  drain.

Supervised restart closes the loop: `distributed.supervisor.
TrainerSupervisor` respawns a crashed `tools/train.py` with `--resume`,
so a `kill -9` of the trainer is a non-event end to end.
"""

from __future__ import annotations

import dataclasses
import faulthandler
import json
import os
import queue
import signal
import sys
import threading
import time

import numpy as np

from euler_tpu.training.checkpoint import CheckpointStore


class TrainingError(RuntimeError):
    """Base for typed trainer failures (never a silent hang/poison)."""


class AnomalyError(TrainingError):
    """Non-finite loss/params persisted past the strike cap (or the
    policy forbids recovery)."""


class HungStepError(TrainingError):
    """A step exceeded its wall-clock deadline; diagnostics were
    dumped before the abort."""


# ---------------------------------------------------------------------------
# resumable batch sources
# ---------------------------------------------------------------------------


class ResumableSource:
    """A batch source where draw i is a pure function of (seed, i).

    Each call derives a fresh Generator from SeedSequence([seed, i]) —
    the repo's standing per-draw seeding idiom — so `seek(i)` replays
    the stream from any position: the cursor IS the checkpointable
    dataflow position. `draw_fn(rng) -> tuple` builds one batch."""

    is_resumable = True

    def __init__(self, draw_fn, seed: int = 0, start: int = 0):
        self._draw_fn = draw_fn
        self._seed = int(seed)
        self._i = int(start)

    def __call__(self) -> tuple:
        rng = np.random.default_rng(
            np.random.SeedSequence([self._seed, self._i])
        )
        self._i += 1
        return self._draw_fn(rng)

    def cursor(self) -> int:
        """Number of draws taken so far (the checkpointed position)."""
        return self._i

    def seek(self, i: int) -> None:
        self._i = int(i)


def resumable_node_batches(
    graph, flow, batch_size: int, node_type: int = -1, seed: int = 0
) -> ResumableSource:
    """`node_batches` with a checkpointable cursor: roots AND the flow's
    neighbor sampling both draw from the per-step derived Generator, so
    a resumed trainer regenerates batch i bit-identically instead of
    inheriting a lost mid-run Generator state."""

    def draw(rng):
        if getattr(flow, "rng", None) is not None:
            flow.rng = rng  # sampling flows: make the draw pure in (seed, i)
        roots = graph.sample_node(batch_size, node_type, rng=rng)
        return (flow.query(roots),)

    return ResumableSource(draw, seed=seed)


# ---------------------------------------------------------------------------
# watchdog + async writer plumbing
# ---------------------------------------------------------------------------


class _DeadlineRunner:
    """Run closures on a daemon worker with a wall-clock deadline.

    A device step blocked in the runtime cannot be interrupted from
    Python; what CAN happen is the driver abandoning the wait, dumping
    diagnostics, and failing typed. A timed-out worker is left wedged
    (daemon) and a fresh one is spawned for any later call."""

    def __init__(self):
        self._lock = threading.Lock()
        self._q: queue.Queue | None = None

    def _ensure(self) -> queue.Queue:
        with self._lock:
            if self._q is None:
                self._q = queue.Queue()
                t = threading.Thread(
                    target=self._loop, args=(self._q,), daemon=True,
                    name="training-step-deadline",
                )
                t.start()
            return self._q

    @staticmethod
    def _loop(q: queue.Queue):
        while True:
            fn, box, done = q.get()
            try:
                box["result"] = fn()
            except BaseException as e:  # surfaced on the caller thread
                box["exc"] = e
            done.set()

    def call(self, fn, timeout_s: float):
        q = self._ensure()
        done = threading.Event()
        box: dict = {}
        q.put((fn, box, done))
        if not done.wait(timeout_s):
            with self._lock:
                self._q = None  # the worker is wedged; abandon it
            raise TimeoutError(f"step exceeded {timeout_s:.3f}s deadline")
        if "exc" in box:
            raise box["exc"]
        return box["result"]


class _AsyncSaver:
    """Background checkpoint writer: the step path hands over host
    snapshots; commits happen off it. Bounded queue (2) so a slow disk
    backpressures instead of accumulating whole-model host copies."""

    def __init__(self, store: CheckpointStore):
        self._store = store
        self._q: queue.Queue = queue.Queue(maxsize=2)
        self._lock = threading.Lock()
        self._error: Exception | None = None
        self._thread: threading.Thread | None = None

    def _ensure(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="training-ckpt-writer"
                )
                self._thread.start()

    def _loop(self):
        while True:
            step, p, o, meta = self._q.get()
            try:
                self._store.save_leaves(step, p, o, meta)
            except Exception as e:  # surfaced at the next submit/drain
                with self._lock:
                    self._error = e
            finally:
                self._q.task_done()

    def _raise_pending(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise TrainingError(f"async checkpoint save failed: {err!r}") \
                from err

    def submit(self, step, p_leaves, o_leaves, meta):
        self._raise_pending()
        self._ensure()
        self._q.put((step, p_leaves, o_leaves, meta))

    def drain(self):
        """Block until every queued save committed; surface failures."""
        if self._thread is not None:
            self._q.join()
        self._raise_pending()


# ---------------------------------------------------------------------------
# the session
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SessionConfig:
    checkpoint_every: int = 50  # steps between retained checkpoints (0=end only)
    keep: int = 3  # retained complete checkpoints
    async_save: bool = True  # EULER_TPU_SAVE_ASYNC=0 overrides to False
    anomaly_policy: str = "skip"  # off | skip | rollback | abort
    guard_every: int = 1  # steps between all-finite checks (device sync each)
    max_strikes: int = 3  # anomalies per checkpoint interval before AnomalyError
    step_deadline_s: float = 0.0  # 0 = watchdog off
    handle_sigterm: bool = True  # drain + final checkpoint on SIGTERM
    drain_every: int = 1024  # on-device loss history drain chunk


class TrainingSession:
    """Durable training-session layer over one Estimator.

    `source` is the estimator's batch source when it supports the
    cursor protocol (`ResumableSource`); device flows need none (their
    batch stream derives from the global step). `graph` (optional)
    feeds the checkpointed graph-epoch book. Requires
    `cfg.steps_per_call == 1` on the estimator — multi-step scan
    dispatch puts checkpoint/anomaly boundaries inside one XLA call,
    which this layer deliberately refuses to blur."""

    def __init__(self, est, source=None, graph=None, cfg: SessionConfig | None = None):
        if int(getattr(est.cfg, "steps_per_call", 1)) > 1:
            raise ValueError(
                "TrainingSession drives single-step dispatches "
                "(steps_per_call=1): checkpoint, anomaly, and preemption "
                "boundaries must fall between optimizer steps"
            )
        self.est = est
        self.source = source
        self.graph = graph
        self.cfg = cfg or SessionConfig()
        if self.cfg.anomaly_policy not in ("off", "skip", "rollback", "abort"):
            raise ValueError(
                f"anomaly_policy: {self.cfg.anomaly_policy!r}"
            )
        if os.environ.get("EULER_TPU_SAVE_ASYNC", "1") == "0":
            self.cfg = dataclasses.replace(self.cfg, async_save=False)
        self.store = CheckpointStore(est.cfg.model_dir, keep=self.cfg.keep)
        self._saver = _AsyncSaver(self.store)
        self._runner = _DeadlineRunner()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._guard = None
        self._step = None
        self._last_good: dict | None = None
        self._strikes = 0
        self._last_saved_step: int | None = None
        self._resumed_from: int | None = None
        self.telemetry = {
            "steps": 0,
            "saves": 0,
            "async_saves": 0,
            "save_stall_ms_total": 0.0,
            "anomalies": 0,
            "rollbacks": 0,
            "skipped_steps": [],
            "hung_aborts": 0,
            "preemptions": 0,
        }

    # -- state snapshot / restore ----------------------------------------

    def _cursor(self):
        if self.source is not None and hasattr(self.source, "cursor"):
            return int(self.source.cursor())
        if self.est._device_flow is not None:
            return int(self.est.step)  # keys fold per global step
        return None

    def _epoch_book(self) -> dict:
        """Per-shard graph epoch at checkpoint time: the resume proof's
        record of WHICH data version each step trained against (local
        stores expose graph_epoch; remote shards re-observe via the
        stats handshake)."""
        book: dict = {}
        g = self.graph
        for i, sh in enumerate(getattr(g, "shards", []) or []):
            ep = getattr(sh, "graph_epoch", None)
            if ep is None and hasattr(sh, "refresh_epoch"):
                try:
                    ep = sh.refresh_epoch()
                except Exception:
                    ep = None
            if ep is not None:
                book[str(i)] = int(ep)
        return book

    def _snapshot_state(self) -> dict:
        """Host copies of the full trainer state (the async writer's
        input AND the anomaly guard's rollback point)."""
        import jax

        est = self.est
        p_leaves, p_tdef = jax.tree_util.tree_flatten(est.params)
        o_leaves, o_tdef = jax.tree_util.tree_flatten(est.opt_state)
        # copy=True is load-bearing: on CPU device_get returns a VIEW of
        # the device buffer, and the donating train step deletes/reuses
        # that buffer on the very next dispatch — an aliased "snapshot"
        # would silently corrupt both the rollback point and the bytes
        # the async writer is committing
        host_p = [np.array(jax.device_get(x), copy=True) for x in p_leaves]
        host_o = [np.array(jax.device_get(x), copy=True) for x in o_leaves]
        return {
            "step": int(est.step),
            "cursor": self._cursor(),
            "p": host_p,
            "o": host_o,
            "p_sharding": [getattr(x, "sharding", None) for x in p_leaves],
            "o_sharding": [getattr(x, "sharding", None) for x in o_leaves],
            "p_tdef": p_tdef,
            "o_tdef": o_tdef,
        }

    def _install_state(self, snap: dict) -> None:
        import jax
        import jax.numpy as jnp

        def put(host, shardings, tdef):
            leaves = [
                jax.device_put(h, s) if s is not None else jnp.asarray(h)
                for h, s in zip(host, shardings)
            ]
            return jax.tree_util.tree_unflatten(tdef, leaves)

        est = self.est
        est.params = put(snap["p"], snap["p_sharding"], snap["p_tdef"])
        est.opt_state = put(snap["o"], snap["o_sharding"], snap["o_tdef"])
        est.step = int(snap["step"])
        if self.source is not None and snap.get("cursor") is not None and \
                hasattr(self.source, "seek"):
            self.source.seek(int(snap["cursor"]))

    def restore(self) -> dict | None:
        """Resume from the newest COMPLETE retained checkpoint: params,
        opt_state, step, source cursor. Returns the resume report (with
        the saved and live graph-epoch books), or None when there is
        nothing to resume from. A torn dir left by a crash mid-save is
        skipped by construction — `latest_step` only sees committed
        checkpoints."""
        step = self.store.latest_step()
        if step is None:
            return None
        est = self.est
        est._ensure_init()
        ckpt = self.store.load(step)
        import jax

        p_leaves, p_tdef = jax.tree_util.tree_flatten(est.params)
        o_leaves, o_tdef = jax.tree_util.tree_flatten(est.opt_state)
        if len(ckpt["params"]) != len(p_leaves) or \
                len(ckpt["opt_state"]) != len(o_leaves):
            raise TrainingError(
                f"checkpoint ckpt_{step:012d} has "
                f"{len(ckpt['params'])}+{len(ckpt['opt_state'])} leaves but "
                f"the live model has {len(p_leaves)}+{len(o_leaves)} — "
                "model/optimizer config drifted from the saved run"
            )
        snap = {
            "step": step,
            "cursor": ckpt["meta"].get("cursor"),
            "p": ckpt["params"],
            "o": ckpt["opt_state"],
            "p_sharding": [getattr(x, "sharding", None) for x in p_leaves],
            "o_sharding": [getattr(x, "sharding", None) for x in o_leaves],
            "p_tdef": p_tdef,
            "o_tdef": o_tdef,
        }
        self._install_state(snap)
        with self._lock:
            self._last_good = snap
            self._last_saved_step = step
            self._resumed_from = step
        saved_book = ckpt["meta"].get("graph_epochs") or {}
        live_book = self._epoch_book()
        return {
            "resumed": True,
            "step": step,
            "cursor": snap["cursor"],
            "graph_epochs": saved_book,
            "live_graph_epochs": live_book,
            "epoch_match": (
                all(live_book.get(k) == v for k, v in saved_book.items())
                if saved_book
                else None
            ),
        }

    # -- checkpointing ----------------------------------------------------

    def _checkpoint(self, final: bool = False) -> None:
        t0 = time.perf_counter()
        snap = self._snapshot_state()
        with self._lock:
            self._last_good = snap
            self._strikes = 0
        meta = {
            "cursor": snap["cursor"],
            "seed": int(self.est.cfg.seed),
            "graph_epochs": self._epoch_book(),
        }
        if self.cfg.async_save and not final:
            self._saver.submit(snap["step"], snap["p"], snap["o"], meta)
            with self._lock:
                self.telemetry["async_saves"] += 1
        else:
            # final flush orders behind every queued async commit
            self._saver.drain()
            self.store.save_leaves(snap["step"], snap["p"], snap["o"], meta)
        with self._lock:
            self.telemetry["saves"] += 1
            self.telemetry["save_stall_ms_total"] += (
                time.perf_counter() - t0
            ) * 1e3
            self._last_saved_step = snap["step"]

    def flush(self) -> None:
        """Commit every in-flight async save (operator surface)."""
        self._saver.drain()

    # -- the step program -------------------------------------------------

    def _step_fn(self):
        """The session's jitted single-step program — same math as the
        Estimator's shared step, but WITHOUT buffer donation.

        Donation is semantically at odds with this layer: the anomaly
        guard must be able to REJECT an update and keep the pre-step
        params/opt_state intact, and a donating step destroys them by
        design (worse: donating restore-produced device_put buffers is
        exactly the pattern that flakes on this backend — the rollback
        proof caught heap corruption there). The cost is keeping old and
        new state alive across one step; `Estimator.train()` keeps the
        donating fast path for guard-less runs."""
        with self._lock:
            if self._step is None:
                import jax

                from euler_tpu.estimator.estimator import (
                    _apply_update,
                    _step_args,
                )

                est = self.est

                def step(params, opt_state, rngs, *batch):
                    return _apply_update(
                        est.model, est.tx, est.feature_cache,
                        params, opt_state, rngs,
                        _step_args(est._device_flow, batch),
                    )

                self._step = jax.jit(step)
            return self._step

    # -- anomaly guard ----------------------------------------------------

    def _guard_fn(self):
        with self._lock:
            if self._guard is None:
                import jax
                import jax.numpy as jnp

                @jax.jit
                def guard(loss, params):
                    # int leaves cast to f32 are always finite; float
                    # leaves carry a grad anomaly into the update, so
                    # all-finite(updated params) transitively covers
                    # all-finite(grads)
                    return jax.tree_util.tree_reduce(
                        lambda ok, leaf: ok & jnp.all(
                            jnp.isfinite(leaf.astype(jnp.float32))
                        ),
                        params,
                        jnp.all(
                            jnp.isfinite(jnp.asarray(loss, jnp.float32))
                        ),
                    )

                self._guard = guard
            return self._guard

    def _on_anomaly(self, step_no: int, history: list, losses: list):
        """One non-finite step. The non-donating step program means the
        pre-step params/opt_state are still intact, so policy "skip" is
        simply: drop the poisoned update, keep the position (the batch
        draw was consumed — cursor parity holds). Policy "rollback"
        reverts to the last-good snapshot and RETRIES from there
        (transient-fault recovery; a persistent anomaly re-trips and
        the strike cap converts it to a typed abort)."""
        with self._lock:
            self.telemetry["anomalies"] += 1
            self._strikes += 1
            strikes = self._strikes
        policy = self.cfg.anomaly_policy
        if policy == "abort" or strikes > self.cfg.max_strikes:
            raise AnomalyError(
                f"non-finite loss/params at step {step_no} "
                f"(policy={policy}, strike {strikes}/{self.cfg.max_strikes})"
            )
        if policy == "skip":
            self.est.step = step_no  # advance past the poisoned batch
            with self._lock:
                self.telemetry["skipped_steps"].append(step_no)
            return
        # policy == "rollback"
        replayable = (
            self.est._device_flow is not None
            or (self.source is not None and hasattr(self.source, "seek"))
        )
        if self._last_good is None or not replayable:
            raise AnomalyError(
                f"non-finite loss/params at step {step_no} "
                f"(policy=rollback, but last_good="
                f"{None if self._last_good is None else self._last_good['step']}"
                f" and replayable={replayable})"
            )
        snap = self._last_good
        self._install_state(snap)
        good = snap["step"]
        history[:] = [(s, x) for s, x in history if s <= good]
        losses[:] = [(s, v) for s, v in losses if s <= good]
        with self._lock:
            self.telemetry["rollbacks"] += 1

    # -- SIGTERM drain ----------------------------------------------------

    def _install_sigterm(self):
        if not self.cfg.handle_sigterm:
            return None
        if threading.current_thread() is not threading.main_thread():
            return None
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            self._stop.set()

        try:
            signal.signal(signal.SIGTERM, handler)
        except ValueError:
            return None
        return prev

    # -- the loop ---------------------------------------------------------

    def _diag_dump(self, step_no: int, deadline_s: float) -> str:
        path = os.path.join(
            os.path.abspath(self.est.cfg.model_dir),
            f"hung_step_{step_no}.txt",
        )
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                f.write(json.dumps({
                    "step": step_no,
                    "deadline_s": deadline_s,
                    "telemetry": {
                        k: v for k, v in self.telemetry.items()
                        if not isinstance(v, list)
                    },
                }) + "\n")
                faulthandler.dump_traceback(file=f, all_threads=True)
        except OSError:
            return "<diagnostic dump failed>"
        return path

    def run(self, steps: int | None = None, log: bool = False) -> dict:
        """Train `steps` more optimizer steps (default: cfg.total_steps)
        with durability, guard, watchdog, and drain semantics. Returns
        {"losses", "loss_steps", "start_step", "end_step", "preempted",
        "resumed_from", "telemetry"}."""
        est = self.est
        est._ensure_init()
        total = steps if steps is not None else est.cfg.total_steps
        target = est.step + int(total)
        step_fn = self._step_fn()
        guard_on = self.cfg.anomaly_policy != "off"
        guard = self._guard_fn() if guard_on else None
        prev_handler = self._install_sigterm()
        self._stop.clear()
        history: list = []  # (step, device loss) not yet drained
        losses: list = []  # (step, float)
        preempted = False
        t0 = time.time()

        def drain():
            if history:
                import jax.numpy as jnp

                stacked = np.asarray(jnp.stack([x for _, x in history]))
                losses.extend(
                    (s, float(v))
                    for (s, _), v in zip(history, stacked.tolist())
                )
                history.clear()

        try:
            while est.step < target:
                if self._stop.is_set():
                    preempted = True
                    with self._lock:
                        self.telemetry["preemptions"] += 1
                    break
                step_no = est.step + 1

                def one_step():
                    batch = est._next_batch(1)
                    p, o, loss, metric = step_fn(
                        est.params, est.opt_state, est._rngs(est.step), *batch
                    )
                    ok = True
                    if guard is not None and (
                        step_no % max(self.cfg.guard_every, 1) == 0
                    ):
                        ok = bool(guard(loss, p))
                    return p, o, loss, ok

                if self.cfg.step_deadline_s > 0:
                    try:
                        p, o, loss, ok = self._runner.call(
                            one_step, self.cfg.step_deadline_s
                        )
                    except TimeoutError:
                        with self._lock:
                            self.telemetry["hung_aborts"] += 1
                        diag = self._diag_dump(
                            step_no, self.cfg.step_deadline_s
                        )
                        raise HungStepError(
                            f"step {step_no} exceeded its "
                            f"{self.cfg.step_deadline_s:.3f}s deadline; "
                            f"all-thread diagnostics at {diag}"
                        ) from None
                else:
                    p, o, loss, ok = one_step()
                if not ok:
                    self._on_anomaly(step_no, history, losses)
                    continue
                est.params, est.opt_state = p, o
                est.step = step_no
                with self._lock:
                    self.telemetry["steps"] += 1
                history.append((step_no, loss))
                if len(history) >= max(self.cfg.drain_every, 1):
                    drain()
                if log and step_no % max(est.cfg.log_steps, 1) == 0:
                    drain()
                    dt = max(time.time() - t0, 1e-9)
                    print(
                        f"step {step_no}: loss={losses[-1][1]:.4f} "
                        f"({(step_no - (target - total)) / dt:.1f} it/s)"
                    )
                if (
                    self.cfg.checkpoint_every
                    and step_no % self.cfg.checkpoint_every == 0
                ):
                    self._checkpoint()
        finally:
            if prev_handler is not None:
                signal.signal(signal.SIGTERM, prev_handler)
            drain()
            # final flush: on clean exit AND on preemption; after an
            # exception est.params still hold the last ACCEPTED state
            # (poisoned updates are never installed), so a best-effort
            # save preserves real progress without masking the error
            exc_live = sys.exc_info()[0] is not None
            need_save = self._last_saved_step != est.step and est.params \
                is not None
            if need_save:
                if exc_live:
                    try:
                        self._checkpoint(final=True)
                    except Exception as e:
                        print(
                            f"# training: best-effort final checkpoint "
                            f"failed: {e!r}",
                            file=sys.stderr,
                        )
                else:
                    self._checkpoint(final=True)
            elif not exc_live:
                self._saver.drain()
        return {
            "losses": [v for _, v in losses],
            "loss_steps": [s for s, _ in losses],
            "start_step": target - total,
            "end_step": int(est.step),
            "preempted": preempted,
            "resumed_from": self._resumed_from,
            "telemetry": {
                k: (list(v) if isinstance(v, list) else v)
                for k, v in self.telemetry.items()
            },
        }
