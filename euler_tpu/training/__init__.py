from euler_tpu.training.checkpoint import (  # noqa: F401
    CheckpointStore,
    is_complete,
    latest_complete,
    watch_signature,
)
from euler_tpu.training.session import (  # noqa: F401
    AnomalyError,
    HungStepError,
    ResumableSource,
    SessionConfig,
    TrainingError,
    TrainingSession,
    resumable_node_batches,
)
