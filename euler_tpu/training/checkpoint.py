"""Atomic retained checkpoints — the trainer's durability floor.

PR 9 gave every graph shard a crash-safe on-disk story (WAL + atomic
snapshots); this module is the same discipline for trainer state. The
old `Estimator.save()` overwrote ONE fixed Orbax path with ``force=True``
— a `kill -9` landing mid-save destroyed the only checkpoint in
existence. Here a checkpoint is a step-numbered directory that either
exists completely or not at all:

    model_dir/
      ckpt_000000000040/
        tensors.bin / tensors.idx / tensors.json   (graph/format.py —
            the params + opt_state leaves, flattened in tree order)
        meta.json    {step, leaf counts, session extras: source cursor,
                      graph-epoch book, seed}
        COMMIT       the commit marker, written + fsync'd LAST

Write protocol (`CheckpointStore.save`): everything lands in
``ckpt_<step>.tmp-<pid>`` first, every file is fsync'd, the COMMIT
marker is written last, then ONE ``os.replace`` publishes the directory
and the parent dir is fsync'd. A crash at ANY point leaves either the
previous complete checkpoints untouched plus a reapable ``.tmp-`` dir,
or the new checkpoint fully committed — there is no state in which a
reader can observe a torn checkpoint as current (the torn-dir sweep in
tests/test_training_session.py walks every crash point).

Read protocol: only directories whose COMMIT marker exists and parses
count. `latest_step` / `restore` pick the NEWEST complete one, so a
crash mid-save can never lose the previous good state, and the serving
hot-reload watcher (`watch_signature`) can never trigger on — or load —
a half-written checkpoint.

Retention: `keep` newest complete checkpoints survive each save
(default 3); older ones and stale tmp dirs are reaped after commit.
"""

from __future__ import annotations

import json
import os
import shutil
import time

import numpy as np

from euler_tpu.graph import format as tformat

PREFIX = "ckpt_"
MARKER = "COMMIT"
LEGACY_NAME = "ckpt"  # the pre-retained single Orbax path


def _fsync_path(path: str) -> None:
    """fsync one already-written file (Linux allows fsync on O_RDONLY)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def step_of(name: str) -> int | None:
    """`ckpt_000000000040` -> 40; None for anything else (tmp dirs,
    the legacy path, unrelated files)."""
    if not name.startswith(PREFIX) or ".tmp-" in name:
        return None
    tail = name[len(PREFIX):]
    if not tail.isdigit():
        return None
    return int(tail)


def is_complete(path: str) -> bool:
    """A checkpoint dir counts only with a parseable COMMIT marker —
    the write protocol's last act, so marker present ⇒ every byte
    before it was fsync'd."""
    marker = os.path.join(path, MARKER)
    try:
        with open(marker, encoding="utf-8") as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


class CheckpointStore:
    """Keep-N atomic retained checkpoints under one model_dir."""

    def __init__(self, root: str, keep: int = 3):
        self.root = os.path.abspath(root)
        self.keep = max(int(keep), 1)

    def _path(self, step: int) -> str:
        return os.path.join(self.root, f"{PREFIX}{int(step):012d}")

    # -- read side -------------------------------------------------------

    def steps(self) -> list[int]:
        """Committed checkpoint steps, ascending."""
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in os.listdir(self.root):
            s = step_of(name)
            if s is not None and is_complete(os.path.join(self.root, name)):
                out.append(s)
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def load(self, step: int | None = None) -> dict:
        """Load one complete checkpoint: {"step", "meta", "params",
        "opt_state"} with params/opt_state as leaf lists in tree-flatten
        order. step=None loads the newest complete one."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no complete checkpoint under {self.root!r}"
                )
        path = self._path(step)
        if not is_complete(path):
            raise FileNotFoundError(f"{path}: checkpoint is not complete")
        with open(os.path.join(path, "meta.json"), encoding="utf-8") as f:
            meta = json.load(f)
        arrays = tformat.read_arrays(path, mmap=False)
        n_p = int(meta["num_params_leaves"])
        n_o = int(meta["num_opt_leaves"])
        # the tensor-dir format promotes 0-d leaves to (1,) (an
        # ascontiguousarray artifact); the recorded shapes restore them
        p_shapes = meta.get("param_shapes") or [None] * n_p
        o_shapes = meta.get("opt_shapes") or [None] * n_o
        params = [
            arrays[f"p_{i:05d}"].reshape(p_shapes[i])
            if p_shapes[i] is not None
            else arrays[f"p_{i:05d}"]
            for i in range(n_p)
        ]
        opt = [
            arrays[f"o_{i:05d}"].reshape(o_shapes[i])
            if o_shapes[i] is not None
            else arrays[f"o_{i:05d}"]
            for i in range(n_o)
        ]
        return {
            "step": int(meta["step"]),
            "meta": meta,
            "params": params,
            "opt_state": opt,
        }

    # -- write side ------------------------------------------------------

    def save_leaves(
        self,
        step: int,
        params_leaves: list[np.ndarray],
        opt_leaves: list[np.ndarray],
        extra_meta: dict | None = None,
    ) -> str:
        """Commit one checkpoint atomically; returns the committed path.

        Leaves must already be HOST arrays (the async writer hands them
        over pre-snapshotted so this whole function can run off the step
        path). Single-writer discipline: concurrent savers to one
        model_dir are not supported (the supervisor guarantees one
        trainer per dir)."""
        final = self._path(step)
        if os.path.isdir(final) and is_complete(final):
            return final  # re-saving a committed step is a no-op
        os.makedirs(self.root, exist_ok=True)
        tmp = f"{final}.tmp-{os.getpid()}"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        arrays = {f"p_{i:05d}": np.asarray(v)
                  for i, v in enumerate(params_leaves)}
        arrays.update(
            {f"o_{i:05d}": np.asarray(v) for i, v in enumerate(opt_leaves)}
        )
        tformat.write_arrays(tmp, arrays)
        meta = {
            "version": 1,
            "step": int(step),
            "num_params_leaves": len(params_leaves),
            "num_opt_leaves": len(opt_leaves),
            "param_shapes": [
                list(np.asarray(v).shape) for v in params_leaves
            ],
            "opt_shapes": [list(np.asarray(v).shape) for v in opt_leaves],
            "ts": time.time(),
        }
        if extra_meta:
            meta.update(extra_meta)
        with open(os.path.join(tmp, "meta.json"), "w", encoding="utf-8") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        for name in ("tensors.bin", "tensors.idx", "tensors.json"):
            _fsync_path(os.path.join(tmp, name))
        # the marker goes LAST: its presence certifies every fsync above
        with open(os.path.join(tmp, MARKER), "w", encoding="utf-8") as f:
            json.dump({"step": int(step), "ts": meta["ts"]}, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        if os.path.isdir(final):  # an incomplete husk from a dead writer
            shutil.rmtree(final)
        os.replace(tmp, final)
        _fsync_dir(self.root)
        self.gc()
        return final

    def gc(self) -> list[str]:
        """Reap stale tmp dirs and all but the newest `keep` complete
        checkpoints; returns removed paths. Torn dirs (no COMMIT) are
        aborted writes and always reaped."""
        removed: list[str] = []
        if not os.path.isdir(self.root):
            return removed
        complete = self.steps()
        drop_steps = set(complete[:-self.keep]) if len(complete) > self.keep \
            else set()
        for name in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, name)
            if name.startswith(PREFIX) and ".tmp-" in name:
                shutil.rmtree(path, ignore_errors=True)
                removed.append(path)
                continue
            s = step_of(name)
            if s is None:
                continue
            if s in drop_steps or not is_complete(path):
                shutil.rmtree(path, ignore_errors=True)
                removed.append(path)
        return removed


# ---------------------------------------------------------------------------
# model_dir-level helpers (serving / tools)
# ---------------------------------------------------------------------------


def latest_complete(model_dir: str) -> str | None:
    """Path of the newest COMPLETE retained checkpoint under model_dir,
    or None (legacy single-path dirs return None — callers fall back)."""
    store = CheckpointStore(model_dir)
    step = store.latest_step()
    return None if step is None else store._path(step)


def watch_signature(model_dir: str) -> tuple:
    """Change-detection token for the serving hot-reload watcher.

    Moves ONLY when a new COMPLETE checkpoint commits: (newest complete
    step, its COMMIT mtime). A half-written `ckpt_*.tmp-*` dir — or a
    torn dir left by a killed trainer — never changes the signature, so
    a watcher poll landing mid-write cannot trigger a swap onto a torn
    checkpoint. Legacy single-path dirs (pre-retained `ckpt/`) fall back
    to the old newest-entry-mtime scan so existing deploy flows keep
    reloading."""
    root = os.path.abspath(model_dir)
    store = CheckpointStore(root)
    step = store.latest_step()
    if step is not None:
        marker = os.path.join(store._path(step), MARKER)
        try:
            return ("retained", step, os.path.getmtime(marker))
        except OSError:
            return ("retained", step, 0.0)
    legacy = os.path.join(root, LEGACY_NAME)
    try:
        mtime = max(
            os.path.getmtime(os.path.join(legacy, e))
            for e in os.listdir(legacy)
        )
    except (OSError, ValueError):
        return ("none", 0, 0.0)
    return ("legacy", 0, mtime)
