"""Subgraph (edge/node-axis) parallelism — the GNN analog of sequence/
context parallelism.

In an LLM trainer, sequence parallelism shards the token axis; in a GNN
the blow-up axis is the fanout product (SURVEY.md §5: `sample_fanout`
output is [batch, k0, k0·k1, …]) or, for whole-graph training, the full
edge set. Two schemes, mirroring the two standard long-context layouts:

1. **Edge-sharded, nodes replicated** (`sp_segment_sum/mean`): each
   device scatter-adds its edge slice into a full-size destination table
   and a `psum` over the axis combines the partials — the all-to-all
   block-sum. Communication O(n_dst·F) per device, independent of E.
   Right when the node table fits every device but the edge set (or the
   per-edge message tensor) does not.

2. **Ring-streamed, nodes AND edges sharded** (`ring_segment_sum` +
   `bucket_edges` / `bucket_full_graph`): node rows are sharded over the
   axis, edges are bucketed by (dst block, src block), and source-node
   feature blocks rotate around the ring via `ppermute` — each step,
   device p aggregates the bucket whose sources just arrived, exactly
   ring attention's block rotation. Per-device memory O(N/P·F + E/P);
   per-step communication O(N/P·F) riding ICI. Right when neither the
   node table nor the edge set fits one device — the true long-context
   regime. Reference counterpart: the whole-graph/full-neighbor training
   the reference can only do single-host (tf_euler full-graph models);
   here it scales over the mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from euler_tpu.ops import scatter_add
from euler_tpu.parallel.mesh import MODEL_AXIS, shard_map


def sp_segment_sum(
    msgs, dst, n_dst: int, mesh: Mesh, axis: str = MODEL_AXIS, mask=None
):
    """Segment-sum msgs[e] into n_dst rows with edges sharded over `axis`.

    msgs f32[E, F], dst i32[E], mask bool[E]; the axis size must divide E.
    Each device reduces its local edge slice, then partials psum across the
    axis — communication is O(n_dst · F) per device, independent of E.
    """
    if mask is None:
        mask = jnp.ones(dst.shape[0], dtype=bool)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(),
    )
    def f(m, d, mk):
        part = scatter_add(m, d, n_dst, mask=mk)
        return jax.lax.psum(part, axis)

    return f(msgs, dst, mask)


def sp_segment_mean(
    msgs, dst, n_dst: int, mesh: Mesh, axis: str = MODEL_AXIS, mask=None
):
    """Masked segment mean over a sharded edge axis.

    One fused collective: a ones column rides along with msgs so the sum
    and the count come out of a single shard_map + psum.
    """
    ones = jnp.ones((dst.shape[0], 1), msgs.dtype)
    both = sp_segment_sum(
        jnp.concatenate([msgs, ones], axis=1), dst, n_dst, mesh, axis, mask
    )
    total, count = both[:, :-1], both[:, -1:]
    return total / jnp.maximum(count, 1.0)


# ---------------------------------------------------------------------------
# Ring-streamed scheme: nodes and edges both sharded over the axis.
# ---------------------------------------------------------------------------


def bucket_edges(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_w: np.ndarray,
    n_nodes: int,
    parts: int,
):
    """Host-side (numpy) bucketing of a whole-graph edge list for the ring.

    Node rows are block-partitioned: block p owns rows
    [p·N/P, (p+1)·N/P) with N padded up to a multiple of P. Edges are
    grouped by (dst block, src block) and padded to the max bucket size,
    yielding static [P, P, E_max] arrays whose leading axis shards over
    the mesh axis (device p receives its dst-row of buckets).

    Returns dict(src, dst, w, mask, n_pad) — src/dst are block-LOCAL row
    indices (int32), w f32, mask bool; n_pad the padded node count.
    """
    if parts <= 0:
        raise ValueError("parts must be positive")
    n_pad = -(-n_nodes // parts) * parts
    blk = n_pad // parts
    src = np.asarray(edge_src, np.int64)
    dst = np.asarray(edge_dst, np.int64)
    w = np.asarray(edge_w, np.float32)
    # one sort-based grouping pass (not a P² scan): edges ordered by
    # (dst block, src block), then each group scatters into its bucket row
    key = (dst // blk) * parts + (src // blk)
    order = np.argsort(key, kind="stable")
    key_s = key[order]
    counts = np.bincount(key_s, minlength=parts * parts)
    e_max = max(1, int(counts.max()))
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(key_s)) - np.repeat(starts, counts)
    p_idx, q_idx = key_s // parts, key_s % parts
    out = {
        "src": np.zeros((parts, parts, e_max), np.int32),
        "dst": np.zeros((parts, parts, e_max), np.int32),
        "w": np.zeros((parts, parts, e_max), np.float32),
        "mask": np.zeros((parts, parts, e_max), bool),
        "n_pad": n_pad,
    }
    out["src"][p_idx, q_idx, pos] = (src[order] - q_idx * blk).astype(np.int32)
    out["dst"][p_idx, q_idx, pos] = (dst[order] - p_idx * blk).astype(np.int32)
    out["w"][p_idx, q_idx, pos] = w[order]
    out["mask"][p_idx, q_idx, pos] = True
    return out


def bucket_full_graph(graph, parts: int, norm: str = "gcn"):
    """Bucket a (single- or multi-shard) Graph's full edge set for the ring.

    Nodes are re-indexed by sorted id → dense row. norm='gcn' adds self
    loops and weights each edge 1/sqrt(d̂_src·d̂_dst) (the exact
    Â=D̂^-1/2(A+I)D̂^-1/2 the full-graph GCN path uses); norm='none'
    keeps raw edge weights, no self loops. Returns (buckets, ids) where
    ids[row] is the node id of dense row `row`.
    """
    ids = np.sort(
        np.concatenate([np.asarray(sh.node_ids) for sh in graph.shards])
    ).astype(np.uint64)
    n = len(ids)
    srcs, dsts, ws = [], [], []
    for sh in graph.shards:
        srcs.append(np.asarray(sh.edge_src))
        dsts.append(np.asarray(sh.edge_dst))
        ws.append(np.asarray(sh.edge_weights))

    def rows_of(vals):  # id → table row, verified (dangling → -1)
        pos = np.clip(np.searchsorted(ids, vals), 0, n - 1)
        return np.where(ids[pos] == vals, pos, -1).astype(np.int64)

    src = rows_of(np.concatenate(srcs))
    dst = rows_of(np.concatenate(dsts))
    ok = (src >= 0) & (dst >= 0)  # drop edges with dangling endpoints
    src, dst = src[ok], dst[ok]
    w = np.concatenate(ws).astype(np.float32)[ok]
    if norm == "gcn":
        # the exact Â the FullGraphFlow+GCNConv path computes
        # (dataflow/whole.py degree block + layers/conv.py:62-69): true
        # graph degree_sum + 1 implicit self loop, symmetric rescale —
        # with the self loop materialized as an edge of weight 1 here
        # (its normalized weight (d̂·d̂)^-0.5 = 1/d̂ matches the
        # x_dst/d̂ term GCNConv adds separately)
        loops = np.arange(n, dtype=np.int64)
        src = np.concatenate([src, loops])
        dst = np.concatenate([dst, loops])
        deg_hat = np.asarray(graph.degree_sum(ids), np.float32) + 1.0
        w = 1.0 / np.sqrt(deg_hat[src] * deg_hat[dst])
    return bucket_edges(src, dst, w, n, parts), ids


def put_ring(mesh: Mesh, buckets: dict, x: np.ndarray, axis: str = MODEL_AXIS):
    """device_put bucket arrays (dst-block axis sharded) and the padded
    node-feature table (row-sharded) for ring_segment_sum."""
    shard = NamedSharding(mesh, P(axis))
    n_pad = buckets["n_pad"]
    xp = np.zeros((n_pad, x.shape[1]), x.dtype)
    xp[: x.shape[0]] = x
    dev = {
        k: jax.device_put(v, shard)
        for k, v in buckets.items()
        if k != "n_pad"
    }
    return dev, jax.device_put(xp, shard)


def ring_segment_sum(
    x, buckets: dict, mesh: Mesh, axis: str = MODEL_AXIS
):
    """out[d] = Σ_e w[e]·x[src[e]] with nodes AND edges sharded over `axis`.

    x f32[N_pad, F] row-sharded; buckets from `bucket_edges` (leading dst-
    block axis sharded). P-step ring: at step s device p aggregates its
    (p, (p+s) mod P) bucket against the resident source block, then the
    blocks rotate one hop via ppermute — communication O(N/P·F) per step,
    the ring-attention schedule. Differentiable (ppermute/scan transpose
    cleanly); out is row-sharded like x.
    """
    parts = mesh.shape[axis]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(axis),
    )
    def f(xb, src_b, dst_b, w_b, m_b):
        # xb [N/P, F]; bucket leaves [1, P, E]
        p = jax.lax.axis_index(axis)
        nloc = xb.shape[0]
        perm = [(i, (i - 1) % parts) for i in range(parts)]

        def body(carry, s):
            blk, out = carry
            q = (p + s) % parts
            src = jax.lax.dynamic_index_in_dim(
                src_b[0], q, keepdims=False
            )
            dst = jax.lax.dynamic_index_in_dim(
                dst_b[0], q, keepdims=False
            )
            wgt = jax.lax.dynamic_index_in_dim(w_b[0], q, keepdims=False)
            msk = jax.lax.dynamic_index_in_dim(m_b[0], q, keepdims=False)
            msgs = blk[src] * jnp.where(msk, wgt, 0.0)[:, None]
            out = out + scatter_add(msgs, dst, nloc)
            blk = jax.lax.ppermute(blk, axis, perm)
            return (blk, out), None

        out0 = jnp.zeros_like(xb)
        (_, out), _ = jax.lax.scan(
            body, (xb, out0), jnp.arange(parts)
        )
        return out

    return f(
        x, buckets["src"], buckets["dst"], buckets["w"], buckets["mask"]
    )
