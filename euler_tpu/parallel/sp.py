"""Subgraph (edge-axis) parallelism — the GNN analog of sequence/context
parallelism.

In an LLM trainer, sequence parallelism shards the token axis; in a GNN
the blow-up axis is the fanout product (SURVEY.md §5: `sample_fanout`
output is [batch, k0, k0·k1, …]). For very large fanouts or whole-graph
batches, one device need not hold a hop's full edge set: these helpers
shard the EDGE axis of a block across a mesh axis with `shard_map` — each
device scatter-adds its edge slice into a full-size destination table and
a `psum` over the axis combines the partials, riding ICI exactly like a
ring-attention block-sum.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from euler_tpu.ops import scatter_add
from euler_tpu.parallel.mesh import MODEL_AXIS


def sp_segment_sum(
    msgs, dst, n_dst: int, mesh: Mesh, axis: str = MODEL_AXIS, mask=None
):
    """Segment-sum msgs[e] into n_dst rows with edges sharded over `axis`.

    msgs f32[E, F], dst i32[E], mask bool[E]; the axis size must divide E.
    Each device reduces its local edge slice, then partials psum across the
    axis — communication is O(n_dst · F) per device, independent of E.
    """
    if mask is None:
        mask = jnp.ones(dst.shape[0], dtype=bool)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(),
    )
    def f(m, d, mk):
        part = scatter_add(m, d, n_dst, mask=mk)
        return jax.lax.psum(part, axis)

    return f(msgs, dst, mask)


def sp_segment_mean(
    msgs, dst, n_dst: int, mesh: Mesh, axis: str = MODEL_AXIS, mask=None
):
    """Masked segment mean over a sharded edge axis.

    One fused collective: a ones column rides along with msgs so the sum
    and the count come out of a single shard_map + psum.
    """
    ones = jnp.ones((dst.shape[0], 1), msgs.dtype)
    both = sp_segment_sum(
        jnp.concatenate([msgs, ones], axis=1), dst, n_dst, mesh, axis, mask
    )
    total, count = both[:, :-1], both[:, -1:]
    return total / jnp.maximum(count, 1.0)
