"""Device mesh + sharding helpers.

The TPU-native replacement for the reference's distribution strategies
(SURVEY.md §2.3): synchronous data parallelism over a ('data',) mesh axis
replaces TF between-graph replication with parameter servers
(scripts/dist_tf_euler.sh:28-43); embedding-table model parallelism over the
('model',) axis replaces PS-partitioned embedding variables
(layers.py:119-171). Gradients all-reduce over ICI inside the jitted step —
XLA inserts the collectives from the shardings; there is no hand-written
NCCL/MPI equivalent.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

# jax moved shard_map to the top level in 0.5; this image's 0.4.x still
# has it under jax.experimental only — resolve once here so every sp/
# embedding call site works on both
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax<0.5 images (like this one)
    from jax.experimental.shard_map import shard_map  # noqa: F401


def make_mesh(
    n_devices: int | None = None, model: int = 1, devices=None
) -> Mesh:
    """(data, model) mesh over the first n_devices devices."""
    devs = list(devices if devices is not None else jax.devices())
    n = n_devices if n_devices is not None else len(devs)
    if n % model:
        raise ValueError(f"n_devices={n} not divisible by model={model}")
    grid = mesh_utils.create_device_mesh((n // model, model), devs[:n])
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch-major) axis across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh, batch_axis: int = 0):
    """device_put a MiniBatch/pytree: batch-dim sharding where divisible.

    Arrays whose `batch_axis` dim divides the data-axis size are split
    across it; everything else (scalars, ragged leftovers) is replicated.
    batch_axis=1 serves steps_per_call>1 training, where arrays are stacked
    [K_steps, batch, ...] and the scan axis K must stay unsharded.
    """
    ndata = mesh.shape[DATA_AXIS]
    ds = NamedSharding(
        mesh, P(*([None] * batch_axis), DATA_AXIS)
    )
    rep = replicated(mesh)

    def put(x):
        x = np.asarray(x) if not isinstance(x, jax.Array) else x
        if (
            getattr(x, "ndim", 0) >= batch_axis + 1
            and x.shape[batch_axis] % ndata == 0
        ):
            return jax.device_put(x, ds)
        return jax.device_put(x, rep)

    return jax.tree.map(put, batch)


def param_shardings(mesh: Mesh, params):
    """NamedShardings for a flax param tree (call BEFORE unboxing).

    Leaves declared with `nn.with_partitioning` (flax `Partitioned` boxes)
    get their spec (e.g. embedding tables over 'model'); plain leaves are
    replicated. The returned tree matches the *unboxed* params structure.
    """
    import flax.linen as nn

    specs = nn.get_partition_spec(params)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s if isinstance(s, P) else P()),
        specs,
        is_leaf=lambda s: isinstance(s, P) or s is None,
    )


def unbox_and_shard(mesh: Mesh, params):
    """Boxed flax params → (sharded plain params, shardings tree)."""
    import flax.linen as nn

    shardings = param_shardings(mesh, params)
    plain = nn.meta.unbox(params)
    return (
        jax.tree.map(lambda x, s: jax.device_put(x, s), plain, shardings),
        shardings,
    )


def shard_params(mesh: Mesh, params):
    return unbox_and_shard(mesh, params)[0]
