from euler_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    make_mesh,
    param_shardings,
    replicated,
    shard_batch,
    shard_params,
    unbox_and_shard,
)
