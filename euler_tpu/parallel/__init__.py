from euler_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    make_mesh,
    param_shardings,
    replicated,
    shard_batch,
    shard_params,
    unbox_and_shard,
)
from euler_tpu.parallel import multihost  # noqa: F401
from euler_tpu.parallel.sp import sp_segment_mean, sp_segment_sum  # noqa: F401
from euler_tpu.parallel.embedding import (  # noqa: F401
    ShardedEmbeddingTable,
    sharded_lookup,
    table_sharding,
)
