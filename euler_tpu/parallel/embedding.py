"""Model-parallel embedding tables with explicit cross-shard lookup.

The reference keeps billion-id embedding tables alive by partitioning them
across parameter servers and gathering rows over the network per step
(tf_euler/python/utils/layers.py:119-171 `SparseEmbedding` over
`PartitionedVariable`; encoders.py:106-121). The TPU-native equivalent:
row-shard the table over the mesh's 'model' axis so each chip's HBM holds
V/P rows, and run the lookup INSIDE the jitted step as a masked local
gather + psum over ICI (the SPMD one-hot-gather pattern). Every chip reads
only its own HBM; the psum moves [B, D] activations, not table rows, and
its transpose routes gradient scatters back to the owning shard — the
all-to-all analog of the reference's PS gather/scatter round trips.

Scale check: 1B ids x 64 dims x f32 = 256 GB — far beyond one chip's HBM
but 4 GB/chip on a v5e-64 ('model'=64), leaving room for the optimizer
slots, which shard identically (optax state mirrors the param tree, so the
same NamedSharding applies).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from euler_tpu.parallel.mesh import MODEL_AXIS


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Row-sharding over the model axis for a [V, D] table."""
    return NamedSharding(mesh, P(MODEL_AXIS, None))


def sharded_lookup(mesh: Mesh, table: jax.Array, ids: jax.Array) -> jax.Array:
    """Gather rows of a ('model',)-row-sharded table by replicated ids.

    jit-safe; ids any int shape [...]; returns [..., D] replicated over the
    mesh (out_specs=P()). Out-of-range ids belong to no shard, so their
    output rows are all-zero (and receive zero gradient).
    """
    from euler_tpu.parallel.mesh import shard_map

    nparts = mesh.shape[MODEL_AXIS]
    rows_per = table.shape[0] // nparts
    assert rows_per * nparts == table.shape[0], (
        f"table rows {table.shape[0]} must divide model axis {nparts}"
    )

    def local(tab, ids):  # tab: [V/P, D] this shard's rows; ids replicated
        p = jax.lax.axis_index(MODEL_AXIS)
        owner = ids // rows_per
        mine = owner == p
        rows = jnp.where(mine, ids - owner * rows_per, 0)
        vals = tab[rows] * mine[..., None].astype(tab.dtype)
        return jax.lax.psum(vals, MODEL_AXIS)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS, None), P()),
        out_specs=P(),
    )(table, ids)


class ShardedEmbeddingTable:
    """A [V, D] embedding table + adam slots, row-sharded over 'model'.

    A deliberately functional, estimator-independent unit for shallow
    embedding models (DeepWalk/LINE-class): `lookup` inside jit via
    sharded_lookup; gradients flow through the masked gather + psum, so
    `jax.grad` w.r.t. the table lands scatter-adds on the owning shard.
    """

    def __init__(
        self, mesh: Mesh, num_rows: int, dim: int, seed: int = 0, scale=0.1
    ):
        nparts = mesh.shape[MODEL_AXIS]
        self.num_rows = ((num_rows + nparts - 1) // nparts) * nparts
        self.dim = dim
        self.mesh = mesh
        sh = table_sharding(mesh)
        # per-shard init: build each shard's rows on its own device instead
        # of materializing the full table on one host
        self.table = jax.jit(
            lambda key: scale
            * jax.random.normal(key, (self.num_rows, dim), jnp.float32),
            out_shardings=sh,
        )(jax.random.PRNGKey(seed))

    def lookup(self, ids):
        return sharded_lookup(self.mesh, self.table, ids)
