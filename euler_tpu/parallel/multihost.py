"""Multi-host data parallelism — SPMD across processes.

The reference deploys one TF worker per host with parameter servers
(scripts/dist_tf_euler.sh:28-43, TF_CONFIG worker/ps roles); the TPU-native
equivalent is single-program multiple-data: every host runs the SAME jitted
step over a global device mesh, feeds the process-local slice of the global
batch, and XLA all-reduces gradients over ICI (intra-pod) / DCN (cross-pod)
from the shardings alone — no parameter servers, no hand-written collectives.

Flow: `initialize()` once per process → `data_mesh()` over the global
devices → build the LOCAL slice of each batch with any grid dataflow →
`put_global()` to assemble global sharded arrays. Grid blocks' edge indices
are rebuilt on device from global iota (`hydrate_blocks`), so hosts never
have to agree on index offsets.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from euler_tpu.dataflow.base import MiniBatch

DATA_AXIS = "data"


def initialize(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join the multi-host cluster; returns True when multi-process.

    Arguments fall back to EULER_COORDINATOR / EULER_NUM_PROCESSES /
    EULER_PROCESS_ID (the dist_tf_euler.sh-style launcher contract). A
    single-process caller (no coordinator configured) is a no-op, so the
    same training script runs unchanged on one host.
    """
    coordinator = coordinator or os.environ.get("EULER_COORDINATOR")
    if num_processes is None and "EULER_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["EULER_NUM_PROCESSES"])
    if process_id is None and "EULER_PROCESS_ID" in os.environ:
        process_id = int(os.environ["EULER_PROCESS_ID"])
    if coordinator is None or not num_processes or num_processes <= 1:
        return False
    try:
        jax.distributed.initialize(
            coordinator, num_processes=num_processes, process_id=process_id
        )
    except RuntimeError as e:  # tolerate repeat calls in one process
        if "already" not in str(e).lower():
            raise
    return True


def data_mesh(devices=None) -> Mesh:
    """1-D ('data',) mesh over every device of every process."""
    devs = np.array(list(devices if devices is not None else jax.devices()))
    return Mesh(devs, (DATA_AXIS,))


def _globalize_blocks(mb: MiniBatch, pc: int) -> MiniBatch:
    """Rescale static block sizes local→global and drop host-built edge ids.

    Grid blocks (dst row i owns src slots [i*g, (i+1)*g)) keep their
    structure under process-major concatenation, and `hydrate_blocks`
    rebuilds edge_src/edge_dst from GLOBAL iota inside the jitted step —
    host-local index arrays would point into the wrong global rows.
    """
    blocks = []
    for b in mb.blocks:
        if not b.grid:
            raise ValueError(
                "multi-host batches need grid-structured blocks (sampled "
                "fanout / full-neighbor flows); irregular blocks would "
                "carry host-local indices into the global program"
            )
        blocks.append(
            b.replace(
                edge_src=None,
                edge_dst=None,
                n_src=b.n_src * pc,
                n_dst=b.n_dst * pc,
            )
        )
    return mb.replace(blocks=tuple(blocks))


def put_global(mesh: Mesh, tree):
    """Assemble per-process local batch slices into global sharded arrays.

    Every array leaf is the process-LOCAL slice; leaves stack process-major
    along their leading axis into a global array sharded over the data
    axis. MiniBatch blocks are globalized (see _globalize_blocks). Leading
    dims must divide evenly over the local devices — silent replication of
    per-host-different data would corrupt the batch, so it is an error.
    """
    pc = jax.process_count()
    per_proc = mesh.shape[DATA_AXIS] // pc
    shd = NamedSharding(mesh, P(DATA_AXIS))

    def put(x):
        x = np.asarray(x)
        if x.ndim == 0 or x.shape[0] % per_proc != 0:
            raise ValueError(
                f"leaf shape {x.shape} does not shard over {per_proc} local"
                f" devices; pad the per-host batch"
            )
        return jax.make_array_from_process_local_data(shd, x)

    tree = jax.tree.map(
        lambda x: _globalize_blocks(x, pc) if isinstance(x, MiniBatch) else x,
        tree,
        is_leaf=lambda x: isinstance(x, MiniBatch),
    )
    return jax.tree.map(put, tree)


def replicate_global(mesh: Mesh, tree):
    """Replicate (identical-on-every-host) values across the global mesh —
    params/optimizer state in pure data parallelism."""
    rep = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(rep, np.asarray(x)),
        tree,
    )
