"""A second, independent graph backend: plain-Python dict store.

The reference ships NebulaGraph as an alternative backend behind the same
op surface (tf_euler/python/euler_ops/base.py:30-127, kernels/
nebula_sample_neighbor_op.cc) — proving its ops are store-agnostic. This
module plays that role for the TPU build: a from-scratch store over Python
dicts (no shard arrays, no CSR, no C++ engine) that implements just the
query surface the dataflow/estimator stack needs, registered under the
`dictdb://` URI scheme. Every model that trains on the native store trains
unchanged on this one — the `Graph` facade seam is real, not hypothetical.

    from euler_tpu.contrib.dict_backend import register
    register()
    g = open_graph("dictdb:///path/to/graph.json")
    SageDataFlow(g, ...)  # standard stack, third-party store
"""

from __future__ import annotations

import json

import numpy as np

from euler_tpu.graph.store import DEFAULT_ID


class DictGraph:
    """Minimal Graph-surface implementation over {id: node-dict} maps.

    Holds the graph exactly as the converter-input JSON describes it:
    adjacency as per-node lists of (dst, weight, type) tuples, features as
    per-node dicts — a deliberately different representation from the
    columnar GraphStore, so tests against it exercise the *contract*, not
    shared code paths.
    """

    def __init__(self, graph_json: dict):
        self.nodes: dict[int, dict] = {}
        self.adj: dict[int, list[tuple[int, float, int]]] = {}
        for n in graph_json["nodes"]:
            nid = int(n["id"])
            feats = {
                f["name"]: f["value"]
                for f in n.get("features", [])
                if f.get("type") == "dense"
            }
            self.nodes[nid] = {
                "type": int(n.get("type", 0)),
                "weight": float(n.get("weight", 1.0)),
                "features": feats,
            }
            self.adj[nid] = []
        for e in graph_json["edges"]:
            src = int(e["src"])
            if src in self.adj:
                self.adj[src].append(
                    (int(e["dst"]), float(e.get("weight", 1.0)),
                     int(e.get("type", 0)))
                )
        self._ids = np.asarray(sorted(self.nodes), dtype=np.uint64)
        self._weights = np.asarray(
            [self.nodes[int(i)]["weight"] for i in self._ids], np.float64
        )
        self._types = np.asarray(
            [self.nodes[int(i)]["type"] for i in self._ids], np.int64
        )
        # feature schema: name → dim, from first occurrence (the columnar
        # store gets this from GraphMeta; a dict store derives it) — so
        # feature fetches are total functions of the schema, not of
        # whichever ids happen to be in the queried batch
        self._feat_dims: dict[str, int] = {}
        for n in self.nodes.values():
            for name, v in n["features"].items():
                self._feat_dims.setdefault(name, len(v))

    # -- the query surface the model stack uses --------------------------

    @property
    def num_shards(self) -> int:
        return 1

    def sample_node(self, count: int, node_type: int = -1, rng=None):
        rng = rng if rng is not None else np.random.default_rng()
        sel = (
            np.ones(len(self._ids), bool)
            if node_type < 0
            else self._types == node_type
        )
        ids, w = self._ids[sel], self._weights[sel]
        if not len(ids):
            return np.full(count, DEFAULT_ID, dtype=np.uint64)
        return rng.choice(ids, size=count, p=w / w.sum())

    def sample_neighbor(
        self, ids, edge_types=None, count=10, rng=None, in_edges=False
    ):
        rng = rng if rng is not None else np.random.default_rng()
        ids = np.asarray(ids, dtype=np.uint64)
        n = len(ids)
        nbr = np.full((n, count), DEFAULT_ID, dtype=np.uint64)
        w = np.zeros((n, count), np.float32)
        tt = np.full((n, count), -1, np.int32)
        mask = np.zeros((n, count), bool)
        eid = np.full((n, count), -1, np.int64)
        want = None if edge_types is None else set(int(t) for t in edge_types)
        for i, nid in enumerate(ids.tolist()):
            cand = [
                c
                for c in self.adj.get(nid, [])
                if want is None or c[2] in want
            ]
            if not cand:
                continue
            ws = np.asarray([c[1] for c in cand], np.float64)
            picks = rng.choice(len(cand), size=count, p=ws / ws.sum())
            for k, pk in enumerate(picks.tolist()):
                dst, ew, et = cand[pk]
                nbr[i, k], w[i, k], tt[i, k], mask[i, k] = dst, ew, et, True
        return nbr, w, tt, mask, eid

    def get_dense_feature(self, ids, names):
        ids = np.asarray(ids, dtype=np.uint64)
        dims = [self._feat_dims.get(nm, 0) for nm in names]
        out = np.zeros((len(ids), sum(dims)), np.float32)
        for i, nid in enumerate(ids.tolist()):
            feats = self.nodes.get(int(nid), {}).get("features", {})
            off = 0
            for nm, d in zip(names, dims):
                v = feats.get(nm)
                if v is not None and len(v) == d:
                    out[i, off : off + d] = v
                off += d  # missing names stay zero, like the columnar store
        return out

    def node_type(self, ids):
        ids = np.asarray(ids, dtype=np.uint64)
        return np.asarray(
            [self.nodes.get(int(i), {"type": -1})["type"] for i in ids],
            np.int32,
        )


def _open_dictdb(uri, **kw):
    path = (uri.netloc + uri.path) if uri.netloc else uri.path
    with open(path) as f:
        return DictGraph(json.load(f))


def register() -> None:
    from euler_tpu.graph.backends import register_backend

    register_backend("dictdb", _open_dictdb)
