"""SpMM-based aggregation alternative (tf_euler/python/contrib/spmm.py
parity): aggregate neighbor features with a sparse adjacency × dense
feature product via jax.experimental.sparse BCOO — useful when the batch
graph is given as COO instead of padded grids."""

from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import sparse as jsparse


def spmm_aggregate(
    edge_src, edge_dst, edge_w, x, n_dst: int, mask=None
) -> jnp.ndarray:
    """out[d] = Σ_{edges (s→d)} w · x[s] as one BCOO matmul."""
    w = jnp.asarray(edge_w, x.dtype)
    if mask is not None:
        w = jnp.where(mask, w, 0)
    indices = jnp.stack(
        [jnp.asarray(edge_dst, jnp.int32), jnp.asarray(edge_src, jnp.int32)],
        axis=1,
    )
    adj = jsparse.BCOO((w, indices), shape=(n_dst, x.shape[0]))
    return adj @ x
