from euler_tpu.contrib.spmm import spmm_aggregate  # noqa: F401
