"""Convolution layers over padded Blocks.

PyG-style conv contract from the reference (tf_euler/python/convolution/
conv.py:27-53): a conv consumes (x_dst, x_src, block) and produces new dst
embeddings. All aggregation is masked segment ops (euler_tpu.ops), which XLA
fuses with the layer matmuls on the MXU; shapes are static.

Layers mirror tf_euler/python/convolution/: GCNConv (gcn_conv.py:32-54),
SAGEConv, GATConv, GINConv, GraphConv, APPNPConv, SGCNConv, TAGConv,
AGNNConv, DNAConv, ARMAConv, GatedGraphConv, RelationConv (rgcn).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.dataflow.base import Block
from euler_tpu.ops import gather, scatter_add, scatter_softmax


def degrees(block: Block, with_self: bool = True) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(deg_dst, deg_src_per_edge) computed from the block mask."""
    ones = block.mask.astype(jnp.float32)
    deg_dst = scatter_add(ones, block.edge_dst, block.n_dst)
    if with_self:
        deg_dst = deg_dst + 1.0
    return deg_dst


class Conv(nn.Module):
    """Base conv: subclasses implement __call__(x_dst, x_src, block).

    dtype is the flax compute dtype for the layer matmuls: params stay
    f32 while dtype=jnp.bfloat16 runs the MXU in bf16 (mixed precision).
    """

    out_dim: int = 0
    dtype: object = None

    def msg(self, x_src, block: Block):
        return gather(x_src, block.edge_src)

    def agg_add(self, msgs, block: Block):
        return scatter_add(msgs, block.edge_dst, block.n_dst, mask=block.mask)


class GCNConv(Conv):
    """Symmetric-normalized GCN with implicit self-loops (gcn_conv.py:32-54).

    When the block carries true graph degrees (src_deg/dst_deg, attached by
    full-neighbor/whole-graph flows with gcn_norm=True) this is the exact
    Â = D̂^-1/2 (A+I) D̂^-1/2 propagation of the GCN paper; otherwise it
    falls back to the reference's in-batch degree approximation.
    """

    use_bias: bool = True

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        if block.dst_deg is not None and block.src_deg is not None:
            dd = block.dst_deg + 1.0  # +1: implicit self loop
            ds = block.src_deg + 1.0
            norm_e = jnp.power(
                gather(ds, block.edge_src) * gather(dd, block.edge_dst), -0.5
            )
            msgs = self.msg(x_src, block) * norm_e[:, None]
            h = self.agg_add(msgs, block) + x_dst / dd[:, None]
        else:
            deg_dst = degrees(block)  # [n_dst]
            # in sampled/padded flows each src slot feeds exactly one dst;
            # its in-batch degree is 1 (+1 self), matching the reference's
            # in-batch degree computation rather than global degrees
            norm_dst = jnp.power(deg_dst, -0.5)
            norm_src = jnp.power(2.0, -0.5)
            msgs = self.msg(x_src, block) * norm_src
            h = (self.agg_add(msgs, block) + x_dst) * norm_dst[:, None]
        return nn.Dense(dtype=self.dtype, features=self.out_dim, use_bias=self.use_bias)(h)


class SAGEConv(Conv):
    """GraphSAGE mean aggregator: W·[x_dst ‖ mean(x_src)] (sage_conv.py).

    Grid-structured blocks can use the fused Pallas gather+reduce kernel
    (mean = gather_weighted_sum with w = mask/deg), skipping the [E, F]
    message tensor entirely.
    """

    use_bias: bool = True

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        from euler_tpu.ops import pallas_mode

        mode = pallas_mode()
        if block.grid and mode != "off":
            d = block.grid
            m = block.mask.reshape(-1, d).astype(jnp.float32)
            w = m / jnp.maximum(m.sum(axis=1, keepdims=True), 1.0)
            slots = block.edge_src.reshape(-1, d)
            from euler_tpu.ops import gather_weighted_sum

            # honor an explicit 'pallas' request (no silent XLA fallback)
            impl = {"auto": "auto", "pallas": "pallas"}.get(mode, "interpret")
            mean = gather_weighted_sum(x_src, slots, w, impl)
            mean = mean.astype(x_dst.dtype)
        else:
            msgs = self.msg(x_src, block)
            total = self.agg_add(msgs, block)
            count = scatter_add(
                jnp.ones(block.edge_src.shape[0], jnp.float32),
                block.edge_dst,
                block.n_dst,
                mask=block.mask,
            )
            mean = total / jnp.maximum(count, 1.0)[:, None]
        h = jnp.concatenate([x_dst, mean], axis=-1)
        return nn.Dense(dtype=self.dtype, features=self.out_dim, use_bias=self.use_bias)(h)


class GATConv(Conv):
    """Graph attention with masked segment softmax (gat_conv.py).

    improved=True adds the transformed dst embedding to the attention
    output (gat_conv.py apply_node `improved`). heads>1 runs multi-head
    attention; concat=True concatenates head outputs (out_dim must divide
    by heads), else heads are averaged — the reference builds the same
    thing from head_num parallel single-head convs (examples/gat/gat.py
    get_conv, head_num=4 concat improved=True for the published score).
    """

    negative_slope: float = 0.2
    improved: bool = False
    heads: int = 1
    concat: bool = True

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        if self.concat:
            if self.out_dim % self.heads:
                raise ValueError(
                    f"out_dim {self.out_dim} must divide heads {self.heads}"
                )
            per = self.out_dim // self.heads
        else:
            per = self.out_dim
        total = per * self.heads
        w = nn.Dense(dtype=self.dtype, features=total, use_bias=False)
        h_dst = w(x_dst)
        h_src = w(x_src)
        hd = h_dst.reshape(-1, self.heads, per)
        hs = h_src.reshape(-1, self.heads, per)
        # params live in f32 (flax convention); compute casts to dtype
        att_s = self.param(
            "att_src", nn.initializers.lecun_normal(), (self.heads, per)
        )
        att_d = self.param(
            "att_dst", nn.initializers.lecun_normal(), (self.heads, per)
        )
        a_src = jnp.einsum("nhp,hp->nh", hs, att_s.astype(hs.dtype))
        a_dst = jnp.einsum("nhp,hp->nh", hd, att_d.astype(hd.dtype))
        e = gather(a_src, block.edge_src) + gather(a_dst, block.edge_dst)
        e = nn.leaky_relu(e, self.negative_slope)  # [E, heads]
        from euler_tpu.ops import pallas_mode

        mode = pallas_mode()
        if block.grid and mode != "off" and self.heads == 1:
            # fused segment-softmax family: attention logits are per-edge
            # SCALARS (a_src·h per node, gathered), so the softmax is a
            # cheap [n_dst, grid] op and the only [E, F]-sized work — the
            # value gather + weighted reduce — runs in the fused DMA
            # kernel. No [E, F] message tensor is ever materialized.
            d = block.grid
            e2 = e.reshape(-1, d)
            m2 = block.mask.reshape(-1, d)
            e2 = jnp.where(m2, e2, -1e9)
            alpha = jax.nn.softmax(e2, axis=1) * m2.astype(e2.dtype)
            from euler_tpu.ops import gather_weighted_sum

            impl = {"auto": "auto", "pallas": "pallas"}.get(mode, "interpret")
            out = gather_weighted_sum(
                h_src.astype(jnp.float32),
                block.edge_src.reshape(-1, d),
                alpha.astype(jnp.float32),
                impl,
            ).astype(h_dst.dtype)
        else:
            alpha = scatter_softmax(
                e, block.edge_dst, block.n_dst, mask=block.mask
            )  # [E, heads]
            msgs = gather(hs, block.edge_src) * alpha[:, :, None]
            out = self.agg_add(
                msgs.reshape(-1, total), block
            ).reshape(-1, self.heads, per)
            out = (
                out.reshape(-1, total) if self.concat else out.mean(axis=1)
            )
        if not self.improved:
            return out
        skip = h_dst if self.concat else hd.mean(axis=1)
        return out + skip


class GINConv(Conv):
    """GIN: MLP((1+eps)·x_dst + Σ x_src) (gin_conv.py)."""

    eps_init: float = 0.0
    hidden_dim: int = 0

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        eps = self.param("eps", nn.initializers.constant(self.eps_init), ())
        agg = self.agg_add(self.msg(x_src, block), block)
        h = (1.0 + eps) * x_dst + agg
        hidden = self.hidden_dim or self.out_dim
        h = nn.Dense(dtype=self.dtype, features=hidden)(h)
        h = nn.relu(h)
        return nn.Dense(dtype=self.dtype, features=self.out_dim)(h)


class GraphConv(Conv):
    """W1·x_dst + W2·Σ x_src (graph_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        agg = self.agg_add(self.msg(x_src, block), block)
        return nn.Dense(dtype=self.dtype, features=self.out_dim)(x_dst) + nn.Dense(dtype=self.dtype, features=self.out_dim, use_bias=False
        )(agg)


class APPNPConv(Conv):
    """One APPNP propagation step: (1-α)·Â h + α·h0 (appnp_conv.py).

    The dense transform runs once outside (in the net); this layer only
    propagates, like the reference's conv.
    """

    alpha: float = 0.1

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block, x0_dst=None):
        deg_dst = degrees(block)
        norm_dst = jnp.power(deg_dst, -0.5)
        msgs = self.msg(x_src, block) * jnp.power(2.0, -0.5)
        agg = (self.agg_add(msgs, block) + x_dst) * norm_dst[:, None]
        x0 = x_dst if x0_dst is None else x0_dst
        return (1.0 - self.alpha) * agg + self.alpha * x0


class SGCNConv(Conv):
    """Simplified GCN: propagation only, no nonlinearity (sgcn_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        deg_dst = degrees(block)
        norm = jnp.power(deg_dst, -0.5)[:, None]
        msgs = self.msg(x_src, block) * jnp.power(2.0, -0.5)
        return (self.agg_add(msgs, block) + x_dst) * norm


class TAGConv(Conv):
    """Topology-adaptive GCN: W·[h0 ‖ Âh0] per hop step (tagcn_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        deg_dst = degrees(block)
        norm = jnp.power(deg_dst, -0.5)[:, None]
        prop = (self.agg_add(self.msg(x_src, block), block) + x_dst) * norm
        return nn.Dense(dtype=self.dtype, features=self.out_dim)(jnp.concatenate([x_dst, prop], axis=-1))


class AGNNConv(Conv):
    """Attention over cosine similarity with learned temperature (agnn_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        beta = self.param("beta", nn.initializers.ones, ())
        xn_dst = x_dst / (jnp.linalg.norm(x_dst, axis=-1, keepdims=True) + 1e-9)
        xn_src = x_src / (jnp.linalg.norm(x_src, axis=-1, keepdims=True) + 1e-9)
        cos = jnp.sum(
            gather(xn_src, block.edge_src) * gather(xn_dst, block.edge_dst),
            axis=-1,
        )
        alpha = scatter_softmax(
            beta * cos, block.edge_dst, block.n_dst, mask=block.mask
        )
        msgs = gather(x_src, block.edge_src) * alpha[:, None]
        return self.agg_add(msgs, block)


class ARMAConv(Conv):
    """ARMA_K filter, one GCS step per stack: σ(Â·x·W + x0·V), stacks
    averaged (arma_conv.py)."""

    stacks: int = 2

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        deg_dst = degrees(block)
        norm = jnp.power(deg_dst, -0.5)[:, None]
        prop = (self.agg_add(self.msg(x_src, block), block) + x_dst) * norm
        outs = []
        for _ in range(self.stacks):
            outs.append(
                nn.relu(
                    nn.Dense(dtype=self.dtype, features=self.out_dim, use_bias=False)(prop)
                    + nn.Dense(dtype=self.dtype, features=self.out_dim)(x_dst)
                )
            )
        return sum(outs) / self.stacks


class DNAConv(Conv):
    """Dot-product attention aggregation (dna_conv.py semantics adapted to
    hop blocks: query = dst, keys/values = src neighbors)."""

    heads: int = 1

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        d = self.out_dim
        q = nn.Dense(dtype=self.dtype, features=d, use_bias=False)(x_dst)
        kk = nn.Dense(dtype=self.dtype, features=d, use_bias=False)(x_src)
        v = nn.Dense(dtype=self.dtype, features=d, use_bias=False)(x_src)
        e = jnp.sum(
            gather(kk, block.edge_src) * gather(q, block.edge_dst), axis=-1
        ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
        alpha = scatter_softmax(e, block.edge_dst, block.n_dst, mask=block.mask)
        msgs = gather(v, block.edge_src) * alpha[:, None]
        return self.agg_add(msgs, block) + q


class GatedGraphConv(Conv):
    """GRU state update from summed messages (gated_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        d = self.out_dim
        pad = d - x_dst.shape[-1]
        h = x_dst if pad == 0 else jnp.pad(x_dst, ((0, 0), (0, max(pad, 0))))
        h = h[:, :d]
        m = self.agg_add(
            nn.Dense(dtype=self.dtype, features=d, use_bias=False)(self.msg(x_src, block)), block
        )
        gru = nn.GRUCell(dtype=self.dtype, features=d)
        _, out = gru(h, m)
        return out


class RelationConv(Conv):
    """RGCN: W_0·x_dst + Σ_r mean_r(W_r·x_src) with optional basis
    decomposition (relation_conv.py). Call with per-relation blocks."""

    num_relations: int = 1
    num_bases: int = 0  # 0 → full per-relation weights

    @nn.compact
    def __call__(self, x_dst, x_src, rel_blocks):
        d_in = x_src.shape[-1]
        out = nn.Dense(dtype=self.dtype, features=self.out_dim)(x_dst)
        if self.num_bases:
            basis = self.param(
                "basis",
                nn.initializers.lecun_normal(),
                (self.num_bases, d_in, self.out_dim),
            )
            coef = self.param(
                "coef",
                nn.initializers.normal(0.1),
                (self.num_relations, self.num_bases),
            )
            weights = jnp.einsum("rb,bio->rio", coef, basis)
        else:
            weights = self.param(
                "rel_w",
                nn.initializers.lecun_normal(),
                (self.num_relations, d_in, self.out_dim),
            )
        for r, block in enumerate(rel_blocks):
            msgs = self.msg(x_src, block) @ weights[r]
            total = self.agg_add(msgs, block)
            cnt = scatter_add(
                jnp.ones(block.edge_src.shape[0], jnp.float32),
                block.edge_dst,
                block.n_dst,
                mask=block.mask,
            )
            out = out + total / jnp.maximum(cnt, 1.0)[:, None]
        return out


class LGCNConv(Conv):
    """Learnable graph conv (LGCN, encoders.py:872-922 parity): per-channel
    top-k over each node's sampled neighbors, self feature prepended, then
    two 1-D convolutions over the length-(k+1) sequence; the dst embedding
    is the sequence's first position. Requires a grid block (fixed fanout),
    which is how the reference feeds it (sample_neighbor(nb_num))."""

    k: int = 3
    hidden_dim: int = 128

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        if not block.grid:
            raise ValueError("LGCNConv needs a grid (fixed-fanout) block")
        if block.grid < self.k:
            raise ValueError(
                f"LGCNConv k={self.k} needs fanout >= k, got {block.grid}"
            )
        d = block.grid
        feat = x_src[block.edge_src.reshape(-1, d)]  # [n_dst, d, F]
        # padded slots behave like default-feature (zero) neighbors, as the
        # reference's default-id feature fetch does
        feat = feat * block.mask.reshape(-1, d)[..., None].astype(feat.dtype)
        topk = jax.lax.top_k(jnp.swapaxes(feat, 1, 2), self.k)[0]
        topk = jnp.swapaxes(topk, 1, 2)  # [n_dst, k, F]
        seq = jnp.concatenate([x_dst[:, None, :], topk], axis=1)
        kernel = self.k // 2 + 1
        h = nn.Conv(dtype=self.dtype, features=self.hidden_dim, kernel_size=(kernel,), padding="VALID")(seq)
        h = nn.Conv(dtype=self.dtype, features=self.out_dim, kernel_size=(kernel,), padding="VALID")(h)
        return h[:, 0, :]


class GeniePathConv(Conv):
    """GeniePath lazy variant: GAT-style breadth attention + LSTM depth
    gate (GenieEncoder, encoders.py:238-291).

    The reference runs the depth LSTM over the stack of per-layer root
    representations; in a layer-stacked conv the equivalent recurrence is
    the LSTM state DERIVED FROM x_dst — the previous layer's output — so
    each layer gates the attention-aggregated breadth signal against the
    depth-so-far instead of a zero state (a zero carry would reduce this
    to a saturating one-step LSTM with no depth memory; measured 0.46 vs
    0.80 F1 on the cora-like quality probe)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        d = self.out_dim
        w = nn.Dense(dtype=self.dtype, features=d, use_bias=False)
        h_src, h_dst = w(x_src), w(x_dst)
        a = nn.Dense(dtype=self.dtype, features=1, use_bias=False)
        e = nn.tanh(
            a(gather(h_src, block.edge_src) + gather(h_dst, block.edge_dst))
        )[:, 0]
        alpha = scatter_softmax(e, block.edge_dst, block.n_dst, mask=block.mask)
        breadth = self.agg_add(
            gather(h_src, block.edge_src) * alpha[:, None], block
        )
        lstm = nn.LSTMCell(dtype=self.dtype, features=d)
        carry = (
            nn.Dense(dtype=self.dtype, features=d, name="carry_c")(x_dst),
            nn.Dense(dtype=self.dtype, features=d, name="carry_h")(x_dst),
        )
        _, out = lstm(carry, breadth)
        return out
