"""Convolution layers over padded Blocks.

PyG-style conv contract from the reference (tf_euler/python/convolution/
conv.py:27-53): a conv consumes (x_dst, x_src, block) and produces new dst
embeddings. All aggregation is masked segment ops (euler_tpu.ops), which XLA
fuses with the layer matmuls on the MXU; shapes are static.

Layers mirror tf_euler/python/convolution/: GCNConv (gcn_conv.py:32-54),
SAGEConv, GATConv, GINConv, GraphConv, APPNPConv, SGCNConv, TAGConv,
AGNNConv, DNAConv, ARMAConv, GatedGraphConv, RelationConv (rgcn).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.dataflow.base import Block
from euler_tpu.ops import gather, scatter_add, scatter_softmax


def degrees(block: Block, with_self: bool = True) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(deg_dst, deg_src_per_edge) computed from the block mask."""
    ones = block.mask.astype(jnp.float32)
    deg_dst = scatter_add(ones, block.edge_dst, block.n_dst)
    if with_self:
        deg_dst = deg_dst + 1.0
    return deg_dst


class Conv(nn.Module):
    """Base conv: subclasses implement __call__(x_dst, x_src, block)."""

    out_dim: int = 0

    def msg(self, x_src, block: Block):
        return gather(x_src, block.edge_src)

    def agg_add(self, msgs, block: Block):
        return scatter_add(msgs, block.edge_dst, block.n_dst, mask=block.mask)


class GCNConv(Conv):
    """Symmetric-normalized GCN with implicit self-loops (gcn_conv.py:32-54)."""

    use_bias: bool = True

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        deg_dst = degrees(block)  # [n_dst]
        # in sampled/padded flows each src slot feeds exactly one dst; its
        # in-batch degree is 1 (+1 self), matching the reference's in-batch
        # degree computation rather than global degrees
        norm_dst = jnp.power(deg_dst, -0.5)
        norm_src = jnp.power(2.0, -0.5)
        msgs = self.msg(x_src, block) * norm_src
        aggregated = self.agg_add(msgs, block)
        h = (aggregated + x_dst) * norm_dst[:, None]
        return nn.Dense(self.out_dim, use_bias=self.use_bias)(h)


class SAGEConv(Conv):
    """GraphSAGE mean aggregator: W·[x_dst ‖ mean(x_src)] (sage_conv.py)."""

    use_bias: bool = True

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        msgs = self.msg(x_src, block)
        total = self.agg_add(msgs, block)
        count = scatter_add(
            jnp.ones(block.edge_src.shape[0], jnp.float32),
            block.edge_dst,
            block.n_dst,
            mask=block.mask,
        )
        mean = total / jnp.maximum(count, 1.0)[:, None]
        h = jnp.concatenate([x_dst, mean], axis=-1)
        return nn.Dense(self.out_dim, use_bias=self.use_bias)(h)


class GATConv(Conv):
    """Single-head graph attention (gat_conv.py); masked segment softmax."""

    negative_slope: float = 0.2

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        w = nn.Dense(self.out_dim, use_bias=False)
        h_dst = w(x_dst)
        h_src = w(x_src)
        a_src = nn.Dense(1, use_bias=False)(h_src)[:, 0]
        a_dst = nn.Dense(1, use_bias=False)(h_dst)[:, 0]
        e = gather(a_src, block.edge_src) + gather(a_dst, block.edge_dst)
        e = nn.leaky_relu(e, self.negative_slope)
        alpha = scatter_softmax(e, block.edge_dst, block.n_dst, mask=block.mask)
        msgs = gather(h_src, block.edge_src) * alpha[:, None]
        out = self.agg_add(msgs, block)
        # self-attention term so isolated nodes keep their embedding
        return out + h_dst


class GINConv(Conv):
    """GIN: MLP((1+eps)·x_dst + Σ x_src) (gin_conv.py)."""

    eps_init: float = 0.0
    hidden_dim: int = 0

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        eps = self.param("eps", nn.initializers.constant(self.eps_init), ())
        agg = self.agg_add(self.msg(x_src, block), block)
        h = (1.0 + eps) * x_dst + agg
        hidden = self.hidden_dim or self.out_dim
        h = nn.Dense(hidden)(h)
        h = nn.relu(h)
        return nn.Dense(self.out_dim)(h)


class GraphConv(Conv):
    """W1·x_dst + W2·Σ x_src (graph_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        agg = self.agg_add(self.msg(x_src, block), block)
        return nn.Dense(self.out_dim)(x_dst) + nn.Dense(
            self.out_dim, use_bias=False
        )(agg)


class APPNPConv(Conv):
    """One APPNP propagation step: (1-α)·Â h + α·h0 (appnp_conv.py).

    The dense transform runs once outside (in the net); this layer only
    propagates, like the reference's conv.
    """

    alpha: float = 0.1

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block, x0_dst=None):
        deg_dst = degrees(block)
        norm_dst = jnp.power(deg_dst, -0.5)
        msgs = self.msg(x_src, block) * jnp.power(2.0, -0.5)
        agg = (self.agg_add(msgs, block) + x_dst) * norm_dst[:, None]
        x0 = x_dst if x0_dst is None else x0_dst
        return (1.0 - self.alpha) * agg + self.alpha * x0


class SGCNConv(Conv):
    """Simplified GCN: propagation only, no nonlinearity (sgcn_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        deg_dst = degrees(block)
        norm = jnp.power(deg_dst, -0.5)[:, None]
        msgs = self.msg(x_src, block) * jnp.power(2.0, -0.5)
        return (self.agg_add(msgs, block) + x_dst) * norm


class TAGConv(Conv):
    """Topology-adaptive GCN: W·[h0 ‖ Âh0] per hop step (tagcn_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        deg_dst = degrees(block)
        norm = jnp.power(deg_dst, -0.5)[:, None]
        prop = (self.agg_add(self.msg(x_src, block), block) + x_dst) * norm
        return nn.Dense(self.out_dim)(jnp.concatenate([x_dst, prop], axis=-1))


class AGNNConv(Conv):
    """Attention over cosine similarity with learned temperature (agnn_conv.py)."""

    @nn.compact
    def __call__(self, x_dst, x_src, block: Block):
        beta = self.param("beta", nn.initializers.ones, ())
        xn_dst = x_dst / (jnp.linalg.norm(x_dst, axis=-1, keepdims=True) + 1e-9)
        xn_src = x_src / (jnp.linalg.norm(x_src, axis=-1, keepdims=True) + 1e-9)
        cos = jnp.sum(
            gather(xn_src, block.edge_src) * gather(xn_dst, block.edge_dst),
            axis=-1,
        )
        alpha = scatter_softmax(
            beta * cos, block.edge_dst, block.n_dst, mask=block.mask
        )
        msgs = gather(x_src, block.edge_src) * alpha[:, None]
        return self.agg_add(msgs, block)
