from euler_tpu.layers.conv import (  # noqa: F401
    AGNNConv,
    ARMAConv,
    DNAConv,
    GatedGraphConv,
    GeniePathConv,
    RelationConv,
    APPNPConv,
    Conv,
    GATConv,
    GCNConv,
    GINConv,
    GraphConv,
    LGCNConv,
    SAGEConv,
    SGCNConv,
    TAGConv,
    degrees,
)

CONVS = {
    "gcn": GCNConv,
    "sage": SAGEConv,
    "gat": GATConv,
    "gin": GINConv,
    "graph": GraphConv,
    "appnp": APPNPConv,
    "sgcn": SGCNConv,
    "tagcn": TAGConv,
    "agnn": AGNNConv,
    "arma": ARMAConv,
    "dna": DNAConv,
    "gated": GatedGraphConv,
    "geniepath": GeniePathConv,
    "lgcn": LGCNConv,
}


def get_conv(name: str):
    if name not in CONVS:
        raise KeyError(f"unknown conv {name!r}; have {sorted(CONVS)}")
    return CONVS[name]
