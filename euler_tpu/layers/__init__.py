from euler_tpu.layers.conv import (  # noqa: F401
    AGNNConv,
    APPNPConv,
    Conv,
    GATConv,
    GCNConv,
    GINConv,
    GraphConv,
    SAGEConv,
    SGCNConv,
    TAGConv,
    degrees,
)

CONVS = {
    "gcn": GCNConv,
    "sage": SAGEConv,
    "gat": GATConv,
    "gin": GINConv,
    "graph": GraphConv,
    "appnp": APPNPConv,
    "sgcn": SGCNConv,
    "tagcn": TAGConv,
    "agnn": AGNNConv,
}


def get_conv(name: str):
    if name not in CONVS:
        raise KeyError(f"unknown conv {name!r}; have {sorted(CONVS)}")
    return CONVS[name]
