"""Graph metadata: feature/type tables shared by all shards.

Plays the role of the reference's `GraphMeta` (euler/core/graph/graph_meta.h:28-39,
91-113): maps feature names to (kind, fid, dim) and records type counts plus
per-shard weight sums used for shard-weighted root sampling
(euler/client/query_proxy.cc:91-144).
"""

from __future__ import annotations

import dataclasses
import json
import os

DENSE = "dense"
SPARSE = "sparse"
BINARY = "binary"
KINDS = (DENSE, SPARSE, BINARY)


@dataclasses.dataclass
class FeatureSpec:
    name: str
    kind: str  # dense | sparse | binary
    fid: int  # id within its kind
    dim: int  # dense: feature width; sparse/binary: max observed length

    def to_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class GraphMeta:
    name: str = "graph"
    num_partitions: int = 1
    num_node_types: int = 0
    num_edge_types: int = 0
    node_features: dict[str, FeatureSpec] = dataclasses.field(default_factory=dict)
    edge_features: dict[str, FeatureSpec] = dataclasses.field(default_factory=dict)
    # per-partition, per-type weight sums: [P][num_types]
    node_weight_sums: list[list[float]] = dataclasses.field(default_factory=list)
    edge_weight_sums: list[list[float]] = dataclasses.field(default_factory=list)
    graph_labels: list[str] = dataclasses.field(default_factory=list)
    node_type_names: list[str] = dataclasses.field(default_factory=list)
    edge_type_names: list[str] = dataclasses.field(default_factory=list)

    def feature_spec(self, name: str, node: bool = True) -> FeatureSpec:
        table = self.node_features if node else self.edge_features
        if name not in table:
            kind = "node" if node else "edge"
            raise KeyError(f"unknown {kind} feature {name!r}; have {sorted(table)}")
        return table[name]

    def node_type_id(self, t) -> int:
        return _type_id(t, self.node_type_names)

    def edge_type_id(self, t) -> int:
        return _type_id(t, self.edge_type_names)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["node_features"] = {k: v.to_dict() for k, v in self.node_features.items()}
        d["edge_features"] = {k: v.to_dict() for k, v in self.edge_features.items()}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "GraphMeta":
        d = dict(d)
        d["node_features"] = {
            k: FeatureSpec(**v) for k, v in d.get("node_features", {}).items()
        }
        d["edge_features"] = {
            k: FeatureSpec(**v) for k, v in d.get("edge_features", {}).items()
        }
        return cls(**d)

    def save(self, directory: str) -> None:
        # tmp + fsync + atomic rename (the graph/wal.py state-file
        # idiom, enforced by graftlint durable-write): a crash mid-save
        # must leave the previous meta readable, never a torn JSON
        final = os.path.join(directory, "euler.meta.json")
        tmp = f"{final}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)

    @classmethod
    def load(cls, directory: str) -> "GraphMeta":
        with open(os.path.join(directory, "euler.meta.json")) as f:
            return cls.from_dict(json.load(f))


def _type_id(t, names: list[str]) -> int:
    """Resolve a type given as int or registered name (type_ops.py:32-55 parity)."""
    if isinstance(t, str):
        if t in names:
            return names.index(t)
        raise KeyError(f"unknown type name {t!r}; have {names}")
    return int(t)
