"""Disaster recovery for durable graph clusters: epoch-consistent
backup, point-in-time restore, and the at-rest integrity scrubber.

PRs 9/10/13 made every process survive `kill -9`, but the durability
ladder stopped at the local disk: losing a shard's WAL dir, silent
bit-rot in a snapshot at rest, a fat-finger publish, or total cluster
loss were unrecoverable. Euler 2.0 ships its graph as durable
partitioned artifacts with an offline build/restore path (PAPER.md);
this module is that layer for the streaming-mutation lane, in three
pillars:

- **Backup** (`backup_cluster`): per shard, every committed snapshot
  (verified against its crc manifest first — rot is never archived)
  plus the WAL slice cut at its valid record prefix (the epoch-
  consistent capture point under live writers), the trainer's newest
  COMMIT-complete checkpoint, and a topology manifest with a per-file
  crc32 of everything. The archive dir commits tmp → fsync → rename,
  the same discipline as the snapshots it contains.
- **Point-in-time restore** (`restore_cluster`): materializes fresh
  `--wal-dir`s from the archive. The target-epoch cut is found by
  replaying the archived records through `epoch_timeline`, which
  mirrors `wal.recover`'s control flow exactly (same DeltaStore
  staging, same applied-window skips), so a cluster booted from the
  restored dirs via the normal `recover()` path lands bit-identical on
  the requested published epoch. `--epoch E-1` is the fat-finger
  publish rollback; at-head restore (no epoch) keeps the pending
  staged-but-unpublished delta too.
- **Scrubber** (`IntegrityScrubber` / `scrub_service`): a low-priority
  per-shard pass that re-verifies snapshot crc manifests and re-parses
  the WAL at rest on an `EULER_TPU_SCRUB_S` cadence. At-rest rot never
  corrupts serving (records were applied to memory when written), but
  it WOULD lose the suffix on the next restart — so corrupt artifacts
  are quarantined (renamed `*.corrupt`, never deleted), snapshots are
  repaired locally (re-snapshot the last published state) or adopted
  from a live replica-group peer (`wal_ship` want=snapshot →
  `install_snapshot`), and rotten WAL byte ranges are re-fetched from
  a peer's byte-interchangeable log and spliced back in place. With no
  peer and no local repair, the shard is marked degraded (typed
  telemetry through `stats`/`repl_status` → `fleet_stats`) — it keeps
  serving its in-memory state and never silently serves corrupt bytes.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import sys
import threading
import time
import zlib

import numpy as np

from euler_tpu.graph import wal as walmod

# Verbs this module puts on the wire: the remote scrub trigger
# (`scrub_remote`) and the peer-repair channel (`wal_ship`, reusing the
# PR-13 replication verb for both byte-range fetch and snapshot
# adoption). graftlint's wire-protocol checker diffs the union of
# client tables against GraphService.HANDLED_VERBS; the runtime twin
# lives in tests/test_wire_parity.py.
WIRE_VERBS = frozenset({
    "scrub",
    "wal_ship",
})

ARCHIVE_MANIFEST = "manifest.json"
ARCHIVE_VERSION = 1


def archive_codec() -> str:
    """EULER_TPU_BACKUP_CODEC: stream codec archived files are stored
    under ("id" default — archives stay byte-identical to PR 15's;
    "zlib"/"zstd" shrink them under the distributed/codec.py seam).
    The manifest records the codec, so restore of either kind is
    automatic."""
    from euler_tpu.distributed import codec as codecmod

    name = os.environ.get("EULER_TPU_BACKUP_CODEC", "id").strip() or "id"
    return name if name in codecmod.available_codecs() else codecmod.IDENTITY


def _compress_tree(base_dir: str, name: str) -> None:
    """Rewrite every file under base_dir as a framed compressed blob
    (same relative paths — manifest crcs then cover the STORED bytes,
    so verify_archive needs no codec awareness)."""
    from euler_tpu.distributed import codec as codecmod

    for root, _dirs, files in os.walk(base_dir):
        for fn in files:
            p = os.path.join(root, fn)
            with open(p, "rb") as f:
                raw = f.read()
            blob = codecmod.compress(name, raw)
            with open(p, "wb") as f:
                f.write(blob)


def _explode_archive(archive_dir: str, manifest: dict, out: str) -> None:
    """Decompress a codec'd archive's payload files into `out` (same
    layout) so the restore path reads plain bytes. Each file's codec
    frame (raw length + crc) is checked during decompression — damage
    raises ValueError instead of restoring garbage."""
    from euler_tpu.distributed import codec as codecmod

    use = manifest.get("codec", codecmod.IDENTITY)

    def explode(src_base: str, files: dict, dst_base: str) -> None:
        for rel in sorted(files):
            src = os.path.join(src_base, rel)
            dst = os.path.join(dst_base, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            with open(src, "rb") as f:
                blob = f.read()
            with open(dst, "wb") as f:
                f.write(codecmod.decompress(use, blob))

    for sid in manifest["shards"]:
        entry = manifest["shards"][sid]
        explode(
            os.path.join(archive_dir, f"shard_{int(sid)}"),
            entry["files"],
            os.path.join(out, f"shard_{int(sid)}"),
        )
    tr = manifest.get("trainer")
    if tr:
        explode(
            os.path.join(archive_dir, "trainer", tr["checkpoint"]),
            tr["files"],
            os.path.join(out, "trainer", tr["checkpoint"]),
        )


def scrub_cadence_s() -> float:
    """EULER_TPU_SCRUB_S: background integrity-scrub cadence in seconds
    (0 = off, the default — operators and the supervisor opt in)."""
    return float(os.environ.get("EULER_TPU_SCRUB_S", "0"))


# ---------------------------------------------------------------------------
# epoch timeline — the PITR cut finder
# ---------------------------------------------------------------------------


def epoch_timeline(
    records,
    start_epoch: int,
    applied,
    part: int,
    num_partitions: int,
    applied_keys_max: int = 4096,
) -> list[tuple[int, int]]:
    """[(end_logical, epoch_after_record)] for each record, mirroring
    `wal.recover`'s replay control flow EXACTLY: publish records bump
    the epoch only when the pending delta is non-empty, applied-window
    keys skip re-staging and re-publishing, and the window FIFO-caps
    identically. Staging goes through a real DeltaStore so the `empty`
    semantics can never diverge from the live path. The cut position
    for a target epoch E is the end of the publish record whose
    epoch_after first equals E — everything after it (later mutations,
    the fat-fingered publish) is excluded by construction."""
    from euler_tpu.graph.delta import DeltaStore

    applied = collections.OrderedDict(applied)
    epoch = int(start_epoch)
    delta = None
    out: list[tuple[int, int]] = []
    for op, a, end in records:
        if op == "publish_epoch":
            key = a[0] if a else None
            if key is not None and f"pub:{key}" in applied:
                out.append((int(end), epoch))
                continue
            d, delta = delta, None
            if not (d is None or d.empty):
                epoch += 1
            if key is not None:
                applied[f"pub:{key}"] = (epoch,)
        else:
            key = str(a[0])
            if key in applied:
                out.append((int(end), epoch))
                continue
            if delta is None:
                delta = DeltaStore(part, num_partitions, max_rows=2**62)
            walmod.stage_record(delta, op, a)
            applied[key] = True
        while len(applied) > applied_keys_max:
            applied.popitem(last=False)
        out.append((int(end), epoch))
    return out


# ---------------------------------------------------------------------------
# archive: backup
# ---------------------------------------------------------------------------


def _fsync_tree(root: str) -> None:
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            fd = os.open(os.path.join(dirpath, fn), os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        fd = os.open(dirpath, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def _crc_walk(base_dir: str) -> dict[str, int]:
    out = {}
    for dirpath, _dirnames, filenames in os.walk(base_dir):
        for fn in filenames:
            p = os.path.join(dirpath, fn)
            out[os.path.relpath(p, base_dir)] = walmod._crc_file(p)
    return out


def collect_shard_dirs(wal_root: str) -> dict[int, str]:
    """Map shard id → the WAL dir to capture, handling both supervisor
    layouts: `shard_<i>/` holding wal.log directly (solo shards) and
    `shard_<i>/replica_<r>/` groups (PR 13) — replica logs are byte-
    interchangeable, so any member is a correct capture source; the one
    with the longest valid log is the freshest."""
    out: dict[int, str] = {}
    for name in sorted(os.listdir(wal_root)):
        if not name.startswith("shard_"):
            continue
        sdir = os.path.join(wal_root, name)
        if not os.path.isdir(sdir):
            continue
        try:
            sid = int(name.split("_", 1)[1])
        except ValueError:
            continue
        if os.path.exists(os.path.join(sdir, walmod.WAL_FILE)):
            out[sid] = sdir
            continue
        reps = [
            os.path.join(sdir, r)
            for r in sorted(os.listdir(sdir))
            if r.startswith("replica_")
            and os.path.exists(os.path.join(sdir, r, walmod.WAL_FILE))
        ]
        if reps:
            out[sid] = max(reps, key=_wal_horizon)
    return out


def _wal_horizon(wdir: str) -> int:
    try:
        _records, _base, valid_end = walmod.scan(
            os.path.join(wdir, walmod.WAL_FILE)
        )
        return valid_end
    except (OSError, ValueError):
        return -1


def _start_candidates(shard_dir: str, snap_names, wal_base: int) -> list:
    """Replay anchors available in `shard_dir`, ascending by epoch:
    (snap_name | None, epoch, applied, wal_pos). The None anchor is the
    construction-time source graph — only valid when the log still
    starts at 0 (nothing was trimmed into a snapshot)."""
    out = []
    if wal_base == 0:
        out.append((None, 0, collections.OrderedDict(), 0))
    for name in sorted(snap_names):
        d = os.path.join(shard_dir, name)
        try:
            with open(os.path.join(d, "snapshot.json")) as f:
                meta = json.load(f)
            pos = int(meta["wal_pos"])
            if pos < wal_base:
                continue  # its replay suffix is gone: not an anchor
            with open(os.path.join(d, "applied.bin"), "rb") as f:
                applied = walmod._applied_from_blob(f.read())
            out.append((name, int(meta["epoch"]), applied, pos))
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            continue
    return out


def backup_cluster(
    shard_dirs: dict[int, str],
    out_dir: str,
    model_dir: str | None = None,
    data_dir: str | None = None,
) -> dict:
    """Capture an epoch-consistent archive of a (possibly live) cluster.

    Per shard: every committed snapshot that passes its crc manifest
    (provably rotten dirs are never archived) plus the WAL copied and
    cut at its valid record prefix — the capture point; records a live
    writer appends after the copy simply aren't in this archive. The
    trainer's newest COMMIT-complete checkpoint rides along when
    `model_dir` is given. A topology manifest with per-file crc32s is
    written last, then the archive commits tmp → fsync → rename, so a
    half-written archive is never mistaken for a backup."""
    if os.path.exists(out_dir):
        raise FileExistsError(f"archive target {out_dir} already exists")
    num_shards = len(shard_dirs)
    if num_shards == 0:
        raise ValueError("backup_cluster: no shard WAL dirs to capture")
    tmp = out_dir.rstrip("/\\") + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    use = archive_codec()
    manifest: dict = {
        "version": ARCHIVE_VERSION,
        "created_ts": time.time(),
        "num_shards": num_shards,
        "data_dir": data_dir,
        # how payload files are STORED ("id" = plain bytes, the PR 15
        # format; restore reads this, operators never pass it)
        "codec": use,
        "shards": {},
        "trainer": None,
    }
    for sid, wdir in sorted(shard_dirs.items()):
        dst = os.path.join(tmp, f"shard_{int(sid)}")
        os.makedirs(dst)
        # WAL first, snapshots second: a background snapshot commits
        # BEFORE it trims the log, so any trim visible in our WAL copy
        # implies its covering snapshot is already on disk for the
        # listing below — the reverse order can capture a just-trimmed
        # log with no archived anchor
        wal_src = os.path.join(wdir, walmod.WAL_FILE)
        wal_dst = os.path.join(dst, walmod.WAL_FILE)
        if os.path.exists(wal_src):
            shutil.copyfile(wal_src, wal_dst)
            # a live writer may be mid-append: cut OUR COPY back to its
            # valid record prefix — the epoch-consistent capture point
            walmod.truncate_torn_tail(wal_dst)
        else:
            with open(wal_dst, "wb") as f:
                f.write(walmod._HEADER.pack(walmod.MAGIC, 0))
        records, base, valid_end = walmod.scan(wal_dst)
        snaps = []
        names = sorted(os.listdir(wdir)) if os.path.isdir(wdir) else []
        for name in names:
            if not walmod.is_committed_snapshot_name(name):
                continue
            src = os.path.join(wdir, name)
            if walmod.verify_snapshot(src):  # [] (clean) and None both pass
                continue
            shutil.copytree(src, os.path.join(dst, name))
            snaps.append(name)
        cand = [
            c for c in _start_candidates(dst, snaps, base)
            # a snapshot committed AFTER our WAL copy can cover a
            # position past the copy's end; it can't anchor THIS archive
            if c[3] <= valid_end
        ]
        if not cand:
            raise RuntimeError(
                f"shard {sid}: WAL base {base} > 0 but no usable snapshot"
                " was archived — this archive could never restore; fix the"
                " shard (scrub/repair) and re-run the backup"
            )
        _name0, e0, applied0, p0 = cand[-1]
        tl = epoch_timeline(
            [r for r in records if r[2] > p0], e0, applied0, sid, num_shards
        )
        if use != "id":
            # compress AFTER every content read above; the manifest crcs
            # below then cover the stored (compressed) bytes, keeping
            # verify_archive codec-blind
            _compress_tree(dst, use)
        manifest["shards"][str(int(sid))] = {
            "wal_base": int(base),
            "wal_end": int(valid_end),
            "epoch": int(tl[-1][1] if tl else e0),
            "earliest_epoch": int(cand[0][1]),
            "snapshots": snaps,
            "files": _crc_walk(dst),
        }
    if model_dir is not None:
        from euler_tpu.training.checkpoint import latest_complete

        ck = latest_complete(model_dir)
        if ck is not None:
            dst = os.path.join(tmp, "trainer", os.path.basename(ck))
            shutil.copytree(ck, dst)
            if use != "id":
                _compress_tree(dst, use)
            manifest["trainer"] = {
                "checkpoint": os.path.basename(ck),
                "files": _crc_walk(dst),
            }
    with open(os.path.join(tmp, ARCHIVE_MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    _fsync_tree(tmp)
    os.replace(tmp, out_dir)
    parent = os.path.dirname(os.path.abspath(out_dir)) or "."
    dfd = os.open(parent, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return manifest


# ---------------------------------------------------------------------------
# archive: verify + restore
# ---------------------------------------------------------------------------


def verify_archive(archive_dir: str) -> dict:
    """Re-hash every archived file against the manifest. Returns
    {"ok", "bad_files", "files_checked", "manifest"} — restore refuses
    a failing archive, and `tools/backup.py verify` surfaces this."""
    with open(os.path.join(archive_dir, ARCHIVE_MANIFEST)) as f:
        manifest = json.load(f)
    bad: list[str] = []
    checked = 0

    def check(base_dir: str, files: dict, prefix: str) -> None:
        nonlocal checked
        for rel in sorted(files):
            checked += 1
            p = os.path.join(base_dir, rel)
            try:
                got = walmod._crc_file(p)
            except OSError:
                bad.append(f"{prefix}/{rel} (missing)")
                continue
            if got != int(files[rel]):
                bad.append(f"{prefix}/{rel}")

    for sid in sorted(manifest["shards"], key=int):
        check(
            os.path.join(archive_dir, f"shard_{int(sid)}"),
            manifest["shards"][sid]["files"],
            f"shard_{int(sid)}",
        )
    tr = manifest.get("trainer")
    if tr:
        check(
            os.path.join(archive_dir, "trainer", tr["checkpoint"]),
            tr["files"],
            "trainer",
        )
    return {
        "ok": not bad,
        "bad_files": bad,
        "files_checked": checked,
        "manifest": manifest,
    }


def read_archive_wal(path: str, expect_crc: int | None = None):
    """Archived WAL slice → (records, base, valid_end). Unlike the live
    `scan` (which tolerates a torn tail by design), an archived slice
    was cut at a record boundary when captured, so ANY damage — a
    whole-file crc mismatch against the manifest, a broken header, or a
    record failing its crc before the recorded end — raises ValueError
    instead of silently restoring a shorter history."""
    with open(path, "rb") as f:
        blob = f.read()
    if (
        expect_crc is not None
        and zlib.crc32(blob) & 0xFFFFFFFF != int(expect_crc)
    ):
        raise ValueError(f"{path}: archived WAL slice fails its manifest crc")
    if len(blob) < walmod._HEADER.size:
        raise ValueError(f"{path}: archived WAL slice shorter than a header")
    magic, base = walmod._HEADER.unpack_from(blob, 0)
    if magic != walmod.MAGIC:
        raise ValueError(f"{path}: not a WAL slice (bad magic)")
    records4, valid_end = walmod.parse_records(
        blob[walmod._HEADER.size:], int(base)
    )
    if walmod._HEADER.size + (valid_end - int(base)) != len(blob):
        raise ValueError(
            f"{path}: corrupt record in archived WAL slice at logical"
            f" {valid_end}"
        )
    return (
        [(op, v, end) for op, v, end, _t in records4],
        int(base),
        int(valid_end),
    )


def restore_cluster(
    archive_dir: str,
    out_root: str,
    epoch: int | None = None,
    replication: int = 1,
    model_dir: str | None = None,
) -> dict:
    """Materialize fresh per-shard WAL dirs from an archive so a normal
    boot (`recover()` per shard) lands EXACTLY on the target epoch.

    `epoch=None` restores at head: newest snapshot + the full archived
    suffix, pending un-published delta and applied window included.
    `epoch=E` is point-in-time: per shard, the newest archived anchor
    with epoch ≤ E plus the record suffix cut at the publish that lands
    epoch E (`epoch_timeline`) — later records, including the
    fat-fingered publish being rolled back, never reach the restored
    dir. `replication=R` materializes R identical replica dirs per
    shard (`shard_<s>/replica_<r>`) — logs are byte-interchangeable, so
    a replica group boots straight from them. The archive is fully
    crc-verified first; damage raises instead of restoring garbage."""
    v = verify_archive(archive_dir)
    if not v["ok"]:
        raise ValueError(
            f"{archive_dir}: archive failed verification — damaged files:"
            f" {v['bad_files'][:8]}"
        )
    manifest = v["manifest"]
    use = manifest.get("codec", "id")
    exploded = None
    if use != "id":
        # codec'd archive: decompress payload files to a scratch mirror
        # first (each file's codec frame crc re-checked in the process)
        # and restore from THAT — the logic below then never needs to
        # know the archive was compressed
        import tempfile

        exploded = tempfile.mkdtemp(prefix="euler_restore_")
        _explode_archive(archive_dir, manifest, exploded)
    try:
        return _restore_verified(
            archive_dir if exploded is None else exploded,
            manifest, out_root, epoch, replication, model_dir,
            stored_crcs=exploded is None,
        )
    finally:
        if exploded is not None:
            shutil.rmtree(exploded, ignore_errors=True)


def _restore_verified(
    archive_dir: str,
    manifest: dict,
    out_root: str,
    epoch: int | None,
    replication: int,
    model_dir: str | None,
    stored_crcs: bool,
) -> dict:
    """restore_cluster's body against an already-verified plain-bytes
    archive view. `stored_crcs` is False for the decompressed mirror of
    a codec'd archive (manifest crcs cover the stored blobs, and the
    codec frames already re-checked the raw bytes)."""
    num_shards = int(manifest["num_shards"])
    replication = max(1, int(replication))
    report: dict = {
        "archive": archive_dir,
        "out_root": out_root,
        "epoch": None if epoch is None else int(epoch),
        "replication": replication,
        "shards": {},
        "trainer": None,
    }
    for sid_str in sorted(manifest["shards"], key=int):
        sid = int(sid_str)
        entry = manifest["shards"][sid_str]
        src = os.path.join(archive_dir, f"shard_{sid}")
        wal_src = os.path.join(src, walmod.WAL_FILE)
        records, base, valid_end = read_archive_wal(
            wal_src,
            expect_crc=(
                entry["files"][walmod.WAL_FILE] if stored_crcs else None
            ),
        )
        cand = [
            c for c in _start_candidates(src, entry.get("snapshots", []), base)
            # ride along only: an archived snapshot covering a position
            # past the archived WAL has no replay suffix here
            if c[3] <= valid_end
        ]
        feasible = [c for c in cand if epoch is None or c[1] <= int(epoch)]
        if not feasible:
            raise ValueError(
                f"shard {sid}: --epoch {epoch} predates the archive horizon"
                f" (earliest restorable epoch"
                f" {cand[0][1] if cand else 'none'})"
            )
        name0, e0, applied0, p0 = feasible[-1]
        suffix = [r for r in records if r[2] > p0]
        tl = epoch_timeline(suffix, e0, applied0, sid, num_shards)
        final_epoch = tl[-1][1] if tl else e0
        if epoch is None:
            cut, reached = valid_end, final_epoch
        elif int(epoch) == e0:
            cut, reached = p0, e0
        else:
            hit = next(
                ((end, ep) for end, ep in tl if ep == int(epoch)), None
            )
            if hit is None:
                raise ValueError(
                    f"shard {sid}: epoch {epoch} is not in the archive"
                    f" horizon [{cand[0][1]}, {final_epoch}]"
                )
            cut, reached = hit[0], int(epoch)
        dests = []
        for r in range(replication):
            dest = (
                os.path.join(out_root, f"shard_{sid}", f"replica_{r}")
                if replication > 1
                else os.path.join(out_root, f"shard_{sid}")
            )
            _materialize_shard(src, name0, p0, cut, wal_src, base, dest)
            dests.append(dest)
        report["shards"][sid] = {
            "epoch": int(reached),
            "snapshot": name0,
            "wal_bytes": int(cut - p0),
            "dests": dests,
        }
    tr = manifest.get("trainer")
    if tr and model_dir is not None:
        src = os.path.join(archive_dir, "trainer", tr["checkpoint"])
        dst = os.path.join(model_dir, tr["checkpoint"])
        if os.path.exists(dst):
            raise FileExistsError(f"restore target {dst} already exists")
        os.makedirs(model_dir, exist_ok=True)
        shutil.copytree(src, dst)
        _fsync_tree(dst)
        report["trainer"] = {"checkpoint": tr["checkpoint"], "dest": dst}
    return report


def _materialize_shard(
    src: str,
    snap_name: str | None,
    start: int,
    cut: int,
    wal_src: str,
    arch_base: int,
    dest: str,
) -> None:
    """One restored WAL dir: the chosen snapshot anchor (if any) plus a
    fresh wal.log whose header base is the anchor position, holding the
    archived record bytes [start, cut). `recover()` then replays it the
    normal way — restore invents no second recovery path."""
    if os.path.exists(os.path.join(dest, walmod.WAL_FILE)):
        raise FileExistsError(f"restore target {dest} already has a WAL")
    os.makedirs(dest, exist_ok=True)
    if snap_name is not None:
        shutil.copytree(os.path.join(src, snap_name),
                        os.path.join(dest, snap_name))
    with open(wal_src, "rb") as f:
        f.seek(walmod._HEADER.size + (start - arch_base))
        blob = f.read(cut - start)
    tmp = os.path.join(dest, walmod.WAL_FILE + ".tmp")
    with open(tmp, "wb") as f:
        f.write(walmod._HEADER.pack(walmod.MAGIC, int(start)))
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dest, walmod.WAL_FILE))
    _fsync_tree(dest)


# ---------------------------------------------------------------------------
# integrity scrubber
# ---------------------------------------------------------------------------


def scrub_remote(host: str, port: int) -> dict:
    """Trigger one synchronous scrub pass on a remote shard (the CLI's
    `scrub` subcommand) and return its report."""
    from euler_tpu.distributed.replication import _PrimaryLink

    link = _PrimaryLink(host, int(port))
    try:
        reply = link._call("scrub", [])
        return json.loads(reply[0])
    finally:
        link.close()


def _peer_addrs(svc) -> list[tuple[str, int]]:
    """Live repair peers for this shard: the known primary first (a
    follower's freshest source), then every registry member of the same
    shard group. Solo shards without a registry have none — scrub then
    degrades instead of repairing."""
    me = (svc.host, svc.port)
    out: list[tuple[str, int]] = []
    repl = getattr(svc, "_repl", None)
    if repl is not None and repl.primary_addr:
        pa = (repl.primary_addr[0], int(repl.primary_addr[1]))
        if pa != me:
            out.append(pa)
    reg = getattr(svc, "registry", None)
    if reg is not None:
        try:
            for host, port, _meta in reg.members(svc.shard):
                addr = (host, int(port))
                if addr != me and addr not in out:
                    out.append(addr)
        except Exception:
            pass
    return out


def _install_from_peer(svc, addr: tuple[str, int]) -> bool:
    """Adopt a peer's newest publish-consistent snapshot over the wire
    (the PR-13 bootstrap payload → `install_snapshot`, which writes a
    fresh durable local snapshot before returning)."""
    from euler_tpu.distributed.replication import _PrimaryLink

    link = _PrimaryLink(addr[0], int(addr[1]))
    try:
        reply = link._call("wal_ship", [0, 0, None, "snapshot"])
        epoch, pos = int(reply[1]), int(reply[2])
        applied = walmod._applied_from_blob(
            bytes(np.ascontiguousarray(reply[3]))
        )
        names = json.loads(reply[4])
        arrays = {
            n: np.array(a, copy=True) for n, a in zip(names, reply[5:])
        }
        svc.install_snapshot(epoch, arrays, applied, pos)
        return True
    finally:
        link.close()


def _fetch_wal_range(wal, addr, frm: int, to: int, max_bytes: int = 1 << 20):
    """Fetch the byte range [frm, to) of a peer's log over `wal_ship`.
    Replica logs are byte-interchangeable (`append_raw` verbatim), so
    the peer's bytes are OUR bytes; the first request carries the crc
    handshake of our intact local prefix so a divergent history answers
    need_snapshot instead of handing us someone else's suffix. Returns
    None when this peer can't serve the range (trimmed, divergent, or
    short); the fetched bytes must parse as whole records ending
    exactly at `to`."""
    from euler_tpu.distributed.replication import _PrimaryLink

    link = _PrimaryLink(addr[0], int(addr[1]))
    try:
        out = b""
        pos = frm
        tail_len = min(4096, frm - wal.base)
        tail_crc = wal.crc_range(frm - tail_len, frm) if tail_len > 0 else 0
        while pos < to:
            t_crc, t_len = (tail_crc, tail_len) if pos == frm else (0, 0)
            reply = link._call(
                "wal_ship", [pos, max_bytes, None, "log", t_crc, t_len, 0.0]
            )
            if bool(reply[3]):
                return None  # peer needs us to snapshot: range unserveable
            blob = bytes(np.ascontiguousarray(reply[1]))
            if not blob:
                return None  # peer's log ends before our range does
            out += blob
            pos = int(reply[2])
        out = out[: to - frm]
        _records, vend = walmod.parse_records(out, frm)
        if vend != to:
            return None  # cut must land on OUR record boundary at `to`
        return out
    finally:
        link.close()


def _has_restart_anchor(wal_dir: str, min_pos: int) -> bool:
    """Can a cold restart of this shard recover? True when the log
    still starts at 0 (source replay) or some committed snapshot at/
    after the base verifies clean."""
    if min_pos == 0:
        return True
    for name in sorted(os.listdir(wal_dir), reverse=True):
        if not walmod.is_committed_snapshot_name(name):
            continue
        d = os.path.join(wal_dir, name)
        try:
            with open(os.path.join(d, "snapshot.json")) as f:
                if int(json.load(f)["wal_pos"]) < min_pos:
                    continue
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            continue
        if not walmod.verify_snapshot(d):  # [] or None: not provably bad
            return True
    return False


def scrub_service(svc, repair: bool = True) -> dict:
    """One integrity pass over a live service's at-rest artifacts.

    Detection: every committed snapshot dir re-hashed against its crc
    manifest; the WAL file re-parsed end to end. Quarantine: provably
    corrupt artifacts renamed `*.corrupt` (snapshots moved out of the
    fallback chain; the WAL copied aside since the live file must keep
    serving). Repair: snapshots from the local published state
    (`snapshot_now`) or a peer's (`install_snapshot`); WAL byte ranges
    re-fetched from a peer's byte-interchangeable log and spliced in
    place — at-rest rot never touched the in-memory state, so repair
    restores BYTES, not state. Unrepairable rot that would strand a
    restart marks the shard degraded (telemetry, never an error leak
    on the serve path)."""
    report: dict = {
        "shard": int(svc.shard),
        "snapshots_checked": 0,
        "wal_bytes_checked": 0,
        "bytes_scanned": 0,
        "corruptions": [],
        "repairs": [],
        "degraded": None,
    }
    wal = getattr(svc, "_wal", None)
    wal_dir = getattr(svc, "wal_dir", None)
    if wal is None or wal_dir is None:
        svc.scrub_passes += 1
        return report
    # -- WAL at rest (checked FIRST: snapshot repair below re-snapshots
    # and trims the log, which would silently discard — not detect —
    # any rot sitting in the soon-to-be-trimmed region) ------------------
    v = wal.verify()
    # one read of the trim-swapped base: both byte counts must describe
    # the same [base, end) window even if a concurrent publish trims
    base = wal.base
    report["wal_bytes_checked"] = int(v["end"] - base)
    report["bytes_scanned"] += int(v["end"] - base)
    wal_repaired = v["ok"]
    if not v["ok"]:
        svc.scrub_corruptions += 1
        report["corruptions"].append({
            "artifact": walmod.WAL_FILE,
            "valid_end": int(v["valid_end"]),
            "end": int(v["end"]),
            "header_ok": bool(v["header_ok"]),
        })
        if repair:
            for addr in _peer_addrs(svc):
                try:
                    data = _fetch_wal_range(
                        wal, addr, int(v["valid_end"]), int(v["end"])
                    )
                except Exception:
                    continue
                if data is None:
                    continue
                # quarantine by COPY: the live file must keep serving
                # while we hold evidence of the rot
                qdst = wal.path + walmod.CORRUPT_SUFFIX
                n = 1
                while os.path.exists(qdst):
                    qdst = f"{wal.path}{walmod.CORRUPT_SUFFIX}.{n}"
                    n += 1
                shutil.copyfile(wal.path, qdst)
                try:
                    wal.splice(int(v["valid_end"]), int(v["end"]), data)
                except ValueError:
                    # the log moved under us — a concurrent trim, or the
                    # replication continuity handshake spotted the same
                    # rot and re-bootstrapped. The final re-verify below
                    # decides whether the shard is healthy.
                    break
                svc.scrub_repairs += 1
                wal_repaired = True
                report["repairs"].append({
                    "artifact": walmod.WAL_FILE,
                    "via": f"peer {addr[0]}:{addr[1]}",
                    "bytes": len(data),
                    "quarantined_to": os.path.basename(qdst),
                })
                break
        if repair and not wal_repaired:
            # a live follower may have healed underneath us: its ship
            # handshake covers the rotted tail, so the primary answered
            # need_snapshot and the coordinator re-bootstrapped (reset
            # log + fresh snapshot) while we were fetching
            v2 = wal.verify()
            if v2["ok"]:
                svc.scrub_repairs += 1
                wal_repaired = True
                report["repairs"].append({
                    "artifact": walmod.WAL_FILE,
                    "via": "replication bootstrap",
                    "bytes": 0,
                })
    # -- snapshots at rest ----------------------------------------------
    snaps = sorted(
        n for n in os.listdir(wal_dir)
        if walmod.is_committed_snapshot_name(n)
    )
    snap_rot = False
    for name in snaps:
        d = os.path.join(wal_dir, name)
        bad = walmod.verify_snapshot(d)
        if bad is None:
            continue  # pre-manifest snapshot: unverifiable, never touched
        report["snapshots_checked"] += 1
        size = sum(
            os.path.getsize(os.path.join(d, f))
            for f in os.listdir(d)
            if os.path.isfile(os.path.join(d, f))
        )
        report["bytes_scanned"] += size
        if not bad:
            continue
        q = walmod.quarantine_artifact(d)
        snap_rot = True
        svc.scrub_corruptions += 1
        report["corruptions"].append({
            "artifact": name,
            "files": bad,
            "quarantined_to": os.path.basename(q) if q else None,
        })
    if snap_rot and repair:
        if svc.snapshot_now():
            svc.scrub_repairs += 1
            report["repairs"].append(
                {"artifact": "snapshot", "via": "local_resnapshot"}
            )
        else:
            for addr in _peer_addrs(svc):
                try:
                    if _install_from_peer(svc, addr):
                        svc.scrub_repairs += 1
                        report["repairs"].append({
                            "artifact": "snapshot",
                            "via": f"peer {addr[0]}:{addr[1]}",
                        })
                        break
                except Exception:
                    continue
    # -- restartability verdict -----------------------------------------
    degraded = None
    if not wal_repaired:
        degraded = (
            f"wal-at-rest-corruption at logical {int(v['valid_end'])}"
            " (no peer could repair); a restart would lose the suffix"
        )
    else:
        # deliberate re-sample, not a torn read: repairs above may have
        # re-snapshotted + trimmed, and the verdict must describe the
        # base the NEXT restart will actually see — but anchor check and
        # message must agree on one value
        wal_base = wal.base  # graftlint: disable=hot-swap-reread -- post-repair re-sample is the point
        if not _has_restart_anchor(wal_dir, wal_base):
            degraded = (
                f"no usable snapshot covers WAL base {int(wal_base)}"
                " (no peer could repair); a restart cannot recover"
            )
    report["degraded"] = degraded
    svc.degraded = degraded
    svc.scrub_passes += 1
    svc.last_scrub = report
    return report


class IntegrityScrubber:
    """Low-priority background scrub daemon for one shard: runs
    `scrub_service` every `interval_s` (EULER_TPU_SCRUB_S) until
    stopped. Failures are contained — a scrub pass must never take the
    serve path down with it."""

    def __init__(self, service, interval_s: float):
        self.service = service
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "IntegrityScrubber":
        self._thread = threading.Thread(
            target=self._run,
            name=f"shard{self.service.shard}-scrub",
            daemon=True,
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                scrub_service(self.service)
            except Exception as e:  # contained: telemetry, not a crash
                print(
                    f"# shard {self.service.shard}: scrub pass failed"
                    f" ({e!r}); artifacts untouched",
                    file=sys.stderr,
                )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
