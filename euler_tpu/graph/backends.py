"""Pluggable graph-backend registry.

The reference lets every graph op route to an alternative store (the
NebulaGraph backend is toggled per-op via a `nebula_ops` dict,
tf_euler/python/euler_ops/base.py:30-127). Here the seam is the `Graph`
facade itself: anything exposing its query surface can serve the dataflow
and model stack. Backends register a URI scheme; `open_graph` dispatches:

    open_graph("/data/mygraph")                  # local shards (+C++ engine)
    open_graph("remote:///shared/reg?shards=2")  # RPC cluster via registry
    register_backend("mydb", opener)             # third-party store
"""

from __future__ import annotations

from urllib.parse import parse_qs, urlparse


def _open_local(path: str, **kw):
    from euler_tpu.graph.store import Graph

    return Graph.load(path, **kw)


def _open_remote(uri, **kw):
    from euler_tpu.distributed import connect

    q = {k: v[-1] for k, v in parse_qs(uri.query).items()}
    # the registry is a filesystem path, not host/path — accept both
    # remote:///abs/reg (empty netloc) and remote://rel/reg forms
    registry = (uri.netloc + uri.path) if uri.netloc else uri.path
    return connect(
        registry_path=registry,
        num_shards=int(q["shards"]),
        timeout=float(q.get("timeout", 30.0)),
        **kw,
    )


BACKENDS = {
    # join netloc like _open_remote: "local://data/g" means ./data/g
    "local": lambda uri, **kw: _open_local(
        (uri.netloc + uri.path) if uri.netloc else uri.path, **kw
    ),
    "remote": _open_remote,
}


def register_backend(scheme: str, opener) -> None:
    """opener(parsed_uri, **kw) → Graph-like object."""
    BACKENDS[scheme] = opener


def open_graph(uri: str, **kw):
    """Open a graph by path or <scheme>://… URI through the registry."""
    parsed = urlparse(uri)
    scheme = parsed.scheme or "local"
    if scheme not in BACKENDS:
        raise KeyError(
            f"no graph backend for scheme {scheme!r}; have {sorted(BACKENDS)}"
        )
    if scheme == "local" and not parsed.scheme:
        parsed = parsed._replace(path=uri)
    return BACKENDS[scheme](parsed, **kw)
