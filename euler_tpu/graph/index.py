"""Attribute sample-indexes + DNF condition algebra (conditioned sampling).

TPU-native counterpart of the reference index subsystem
(euler/core/index/sample_index.h:30-60, index_manager.h:35-58,
common_index_result.h): `HashIndex` answers eq/in over discrete attribute
values, `RangeIndex` answers lt/le/gt/ge/eq over ordered scalars with
prefix-sum weights for O(log n) weighted sampling, `HashRangeIndex` nests a
range index under each hash key. Search results are `IndexResult` row sets
supporting intersection/union so DNF filter conditions
(`has/hasKey/hasLabel`, gremlin.l:15-56) compose, then sample by weight or
materialize ids. Everything is vectorized numpy over the shard's columnar
arrays — no per-row trees.

A condition is DNF: a list of AND-clauses, each clause a list of atoms
`(field, op, value)`; the whole condition is the OR of its clauses.
Fields: any feature name, or the specials `id`, `type`, `weight`.
Ops: eq ne lt le gt ge in not_in haskey.
"""

from __future__ import annotations

import numpy as np

from euler_tpu.graph.meta import BINARY, DENSE, SPARSE

OPS = ("eq", "ne", "lt", "le", "gt", "ge", "in", "not_in", "haskey")


class IndexResult:
    """A set of local row indices with the shard's sampling weights.

    Mirrors the reference's lazy IndexResult set algebra
    (euler/core/index/common_index_result.h) eagerly: rows are kept sorted
    and unique so intersection/union are linear merges.
    """

    def __init__(self, rows: np.ndarray, weights: np.ndarray):
        self.rows = np.asarray(rows, dtype=np.int64)
        self._weights = weights  # full per-row weight column (shared)

    def intersect(self, other: "IndexResult") -> "IndexResult":
        return IndexResult(
            np.intersect1d(self.rows, other.rows, assume_unique=True),
            self._weights,
        )

    def union(self, other: "IndexResult") -> "IndexResult":
        return IndexResult(
            np.union1d(self.rows, other.rows), self._weights
        )

    def __len__(self) -> int:
        return len(self.rows)

    @property
    def total_weight(self) -> float:
        return float(self._weights[self.rows].sum()) if len(self.rows) else 0.0

    def sample(self, count: int, rng: np.random.Generator) -> np.ndarray:
        """Weighted sample (with replacement) of `count` rows; -1 if empty."""
        if len(self.rows) == 0:
            return np.full(count, -1, dtype=np.int64)
        w = np.asarray(self._weights[self.rows], dtype=np.float64)
        cum = np.cumsum(w)
        if cum[-1] <= 0:
            return np.full(count, -1, dtype=np.int64)
        u = rng.random(count) * cum[-1]
        return self.rows[np.searchsorted(cum, u, side="right")]

    def contains(self, rows: np.ndarray) -> np.ndarray:
        """Membership mask for arbitrary row indices (vectorized)."""
        rows = np.asarray(rows, dtype=np.int64)
        if len(self.rows) == 0:
            return np.zeros(rows.shape, dtype=bool)
        pos = np.searchsorted(self.rows, rows)
        pos = np.clip(pos, 0, len(self.rows) - 1)
        return (self.rows[pos] == rows) & (rows >= 0)


class HashIndex:
    """value → rows, for discrete (u64 / bytes / int) attributes.

    Parity: HashSampleIndex (euler/core/index/hash_sample_index.h). Rows may
    appear under several values (multi-valued sparse attributes).
    """

    def __init__(self, table: dict, num_rows: int, nonempty: np.ndarray):
        self._table = table  # value → sorted row array
        self._num_rows = num_rows
        self._nonempty = nonempty  # sorted rows that carry the attribute

    @classmethod
    def build(cls, rows: np.ndarray, values: np.ndarray, num_rows: int):
        order = np.argsort(values, kind="stable")
        rows, values = rows[order], values[order]
        table = {}
        if len(values):
            cuts = np.flatnonzero(np.r_[True, values[1:] != values[:-1]])
            bounds = np.r_[cuts, len(values)]
            for i, c in enumerate(cuts):
                v = values[c]
                table[v.item() if isinstance(v, np.generic) else v] = np.sort(
                    rows[c : bounds[i + 1]]
                )
        return cls(table, num_rows, np.unique(rows))

    def _all(self) -> np.ndarray:
        return np.arange(self._num_rows, dtype=np.int64)

    def search(self, op: str, value) -> np.ndarray:
        if op == "haskey":
            return self._nonempty
        if op == "eq":
            return self._table.get(_key(value), np.empty(0, np.int64))
        if op == "in":
            hits = [
                self._table.get(_key(v), np.empty(0, np.int64)) for v in value
            ]
            return _union_many(hits)
        if op == "ne":
            return np.setdiff1d(self._all(), self.search("eq", value))
        if op == "not_in":
            return np.setdiff1d(self._all(), self.search("in", value))
        raise ValueError(f"hash index does not support op {op!r}")


class RangeIndex:
    """Ordered scalar attribute → row ranges via binary search.

    Parity: RangeSampleIndex (euler/core/index/range_sample_index.h) —
    sorted (value, row) pairs; lt/le/gt/ge/eq become contiguous slices of
    the sort order, sampled through the shared weight column.
    """

    def __init__(self, sorted_vals: np.ndarray, order_rows: np.ndarray):
        self._vals = sorted_vals
        self._rows = order_rows

    @classmethod
    def build(cls, values: np.ndarray):
        values = np.asarray(values)
        # integers (incl. uint64 node ids) stay exact; everything else
        # compares as float64
        if not np.issubdtype(values.dtype, np.integer):
            values = values.astype(np.float64)
        order = np.argsort(values, kind="stable")
        return cls(values[order], order.astype(np.int64))

    def _coerce(self, value):
        """Search value → the index dtype; None = below an unsigned domain."""
        if not isinstance(value, (int, float, str, np.integer, np.floating)):
            # a list/tuple here means a malformed condition (e.g. a GQL
            # in_() list reaching a scalar comparator) — reject it as a
            # query error, not a raw float(list) TypeError
            raise ValueError(
                f"scalar comparison value expected, got {type(value).__name__}"
            )
        dt = self._vals.dtype
        integral = isinstance(value, (int, np.integer)) or (
            isinstance(value, float) and value.is_integer()
        )
        if np.issubdtype(dt, np.integer):
            if not integral:
                # fractional threshold over an integer column: compares as
                # float64 (exactness above 2**53 is not preserved here)
                return float(value)
            if int(value) < 0 and np.issubdtype(dt, np.unsignedinteger):
                return None
            return dt.type(int(value))
        return float(value)

    def search(self, op: str, value) -> np.ndarray:
        n = len(self._vals)
        if op == "in":
            return _union_many([self.search("eq", x) for x in value])
        if op == "not_in":
            return np.setdiff1d(np.sort(self._rows), self.search("in", value))
        if op == "haskey":
            return np.sort(self._rows)
        v = self._coerce(value)
        if v is None:  # negative value vs unsigned column
            if op in ("lt", "le", "eq"):
                return np.empty(0, np.int64)
            return np.sort(self._rows)  # gt/ge/ne match everything
        if op == "lt":
            sl = slice(0, np.searchsorted(self._vals, v, "left"))
        elif op == "le":
            sl = slice(0, np.searchsorted(self._vals, v, "right"))
        elif op == "gt":
            sl = slice(np.searchsorted(self._vals, v, "right"), n)
        elif op == "ge":
            sl = slice(np.searchsorted(self._vals, v, "left"), n)
        elif op == "eq":
            sl = slice(
                np.searchsorted(self._vals, v, "left"),
                np.searchsorted(self._vals, v, "right"),
            )
        elif op == "ne":
            return np.sort(
                np.r_[
                    self._rows[: np.searchsorted(self._vals, v, "left")],
                    self._rows[np.searchsorted(self._vals, v, "right") :],
                ]
            )
        else:
            raise ValueError(f"range index does not support op {op!r}")
        return np.sort(self._rows[sl])


class HashRangeIndex:
    """key → RangeIndex over (key, value) pair attributes.

    Parity: HashRangeSampleIndex — hash on the first component, range
    search within. Entries come as (row, key, value) triples.
    """

    def __init__(self, table: dict):
        self._table = table  # key → (RangeIndex over values, rows base)

    @classmethod
    def build(cls, rows: np.ndarray, keys: np.ndarray, values: np.ndarray):
        table = {}
        order = np.argsort(keys, kind="stable")
        rows, keys, values = rows[order], keys[order], values[order]
        if len(keys):
            cuts = np.flatnonzero(np.r_[True, keys[1:] != keys[:-1]])
            bounds = np.r_[cuts, len(keys)]
            for i, c in enumerate(cuts):
                seg = slice(c, bounds[i + 1])
                sub_vals = np.asarray(values[seg], dtype=np.float64)
                sub_order = np.argsort(sub_vals, kind="stable")
                k = keys[c]
                table[k.item() if isinstance(k, np.generic) else k] = RangeIndex(
                    sub_vals[sub_order], rows[seg][sub_order]
                )
        return cls(table)

    def search(self, key, op: str, value) -> np.ndarray:
        sub = self._table.get(_key(key))
        if sub is None:
            return np.empty(0, np.int64)
        return sub.search(op, value)


def _key(v):
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode()
    if isinstance(v, float) and v.is_integer():
        return int(v)
    return int(v) if isinstance(v, (int, np.integer)) else v


def _union_many(parts: list[np.ndarray]) -> np.ndarray:
    parts = [p for p in parts if len(p)]
    if not parts:
        return np.empty(0, np.int64)
    return np.unique(np.concatenate(parts))


class DnfEvaluator:
    """DNF walk over per-field indexes — the shape shared by the graph
    shard's `IndexManager` and the retrieval corpus's attribute index
    (retrieval/corpus.py). Subclasses provide `_index_for(field)` plus
    `_weights`/`_num_rows`; the condition algebra (AND = intersect
    within a clause, OR = union across clauses, empty DNF = everything)
    lives here exactly once so both surfaces stay semantically
    identical."""

    _weights: np.ndarray
    _num_rows: int

    def _index_for(self, field: str):  # pragma: no cover - abstract
        raise NotImplementedError

    def search(self, field: str, op: str, value=None) -> IndexResult:
        if op not in OPS:
            raise ValueError(f"unknown condition op {op!r}")
        return IndexResult(
            self._index_for(field).search(op, value), self._weights
        )

    def search_dnf(self, dnf) -> IndexResult:
        """dnf = [[(field, op, value), ...AND...], ...OR...]."""
        out: IndexResult | None = None
        for clause in dnf:
            cur: IndexResult | None = None
            for atom in clause:
                field, op, value = (tuple(atom) + (None,))[:3]
                res = self.search(field, op, value)
                cur = res if cur is None else cur.intersect(res)
            if cur is None:
                continue
            out = cur if out is None else out.union(cur)
        if out is None:
            out = IndexResult(
                np.arange(self._num_rows, dtype=np.int64), self._weights
            )
        return out


class IndexManager(DnfEvaluator):
    """Per-shard index registry + DNF evaluator.

    Parity: IndexManager::Instance() (index_manager.h:35-58) except indexes
    are (re)built from the memory-mapped columns at first use instead of
    being deserialized from an `Index/` directory — the columnar shard
    format already holds every value the offline index files would.
    Un-indexed fields fall back to a vectorized full-column scan with the
    same semantics.
    """

    def __init__(self, store, node: bool = True):
        self._store = store
        self._node = node
        self._cache: dict[tuple, object] = {}
        meta = store.meta
        n = store.num_nodes if node else len(store.edge_src)
        self._num_rows = n
        self._weights = store.node_weights if node else store.edge_weights

    # ---- column extraction ---------------------------------------------

    def _column(self, field: str):
        """(kind, data) for a field: scalar column or (rows, values) pairs."""
        st = self._store
        if field == "id":
            return "scalar", (
                st.node_ids
                if self._node
                else np.arange(self._num_rows, dtype=np.int64)
            )
        if field in ("type", "label", "__label__"):
            col = st.node_types if self._node else st.edge_types
            return "scalar", np.asarray(col, dtype=np.int64)
        if field == "weight":
            return "scalar", np.asarray(self._weights, dtype=np.float64)
        spec = st.meta.feature_spec(field, node=self._node)
        prefix = "nf" if self._node else "ef"
        if spec.kind == DENSE:
            vals = np.asarray(st._feat(prefix, DENSE, spec.fid))
            return "scalar", vals[:, 0].astype(np.float64)
        if spec.kind == SPARSE:
            indptr = st._feat(prefix, SPARSE, spec.fid, "_indptr")
            values = np.asarray(st._feat(prefix, SPARSE, spec.fid, "_values"))
            rows = np.repeat(
                np.arange(self._num_rows, dtype=np.int64), np.diff(indptr)
            )
            return "multi", (rows, values)
        if spec.kind == BINARY:
            indptr = st._feat(prefix, BINARY, spec.fid, "_indptr")
            blob = np.asarray(st._feat(prefix, BINARY, spec.fid, "_values"))
            vals = np.array(
                [
                    bytes(blob[indptr[r] : indptr[r + 1]])
                    for r in range(self._num_rows)
                ],
                dtype=object,
            )
            rows = np.arange(self._num_rows, dtype=np.int64)
            keep = np.array([len(v) > 0 for v in vals], dtype=bool)
            return "multi", (rows[keep], vals[keep])
        raise ValueError(f"cannot index feature kind {spec.kind!r}")

    def _index_for(self, field: str):
        """Scalar fields get a RangeIndex (covers eq/ne/in + ordering ops);
        multi-valued sparse/binary fields get a HashIndex."""
        if field in self._cache:
            return self._cache[field]
        kind, data = self._column(field)
        if kind == "scalar":
            idx = RangeIndex.build(data)
        else:
            rows, values = data
            idx = HashIndex.build(rows, values, self._num_rows)
        self._cache[field] = idx
        return idx

    # ---- selective carry across delta merges ----------------------------

    def _backing_keys(self, field: str) -> list[str] | None:
        """The array-dict keys whose bytes a field's index is built from
        (None = unknown field: never carried). `merge_delta` carries
        untouched arrays BY REFERENCE, so key-by-key identity between the
        old and new array dicts proves the index's inputs are unchanged."""
        st = self._store
        if field == "id":
            return ["node_ids"] if self._node else []
        if field in ("type", "label", "__label__"):
            return ["node_types"] if self._node else ["edge_types"]
        if field == "weight":
            return ["node_weights"] if self._node else ["edge_weights"]
        try:
            spec = st.meta.feature_spec(field, node=self._node)
        except (KeyError, ValueError):
            return None
        prefix = "nf" if self._node else "ef"
        if spec.kind == DENSE:
            return [f"{prefix}_dense_{spec.fid}"]
        if spec.kind == SPARSE:
            return [
                f"{prefix}_sparse_{spec.fid}_indptr",
                f"{prefix}_sparse_{spec.fid}_values",
            ]
        if spec.kind == BINARY:
            return [
                f"{prefix}_bin_{spec.fid}_indptr",
                f"{prefix}_bin_{spec.fid}_values",
            ]
        return None

    def carry_from(self, old: "IndexManager", old_arrays: dict,
                   new_arrays: dict) -> int:
        """Adopt the per-field index objects an epoch publish did NOT
        touch (merge_delta cost control — see GraphStore.merge_delta).

        A cached index of `old` is carried iff the row numbering is
        provably unchanged (same row count AND the id column rode through
        the merge by reference — any insert/delete rewrites it) and every
        backing array of the field is the SAME object in both array
        dicts. Indexes map values to row numbers, so both conditions
        together make the carried object bit-identical to a rebuild;
        everything else stays lazy and rebuilds on first use. Returns the
        number of carried fields (telemetry + test pin)."""
        if old._node != self._node or old._num_rows != self._num_rows:
            return 0
        anchor = "node_ids" if self._node else "edge_src"
        if new_arrays.get(anchor) is not old_arrays.get(anchor):
            return 0
        carried = 0
        for field, idx in old._cache.items():
            keys = self._backing_keys(field)
            if keys is None:
                continue
            if all(
                k in old_arrays and new_arrays.get(k) is old_arrays[k]
                for k in keys
            ):
                self._cache[field] = idx
                carried += 1
        return carried
