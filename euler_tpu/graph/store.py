"""In-memory columnar graph shard + multi-shard facade.

`GraphStore` is one shard: the role of the reference's `Graph` singleton +
`Node`/`Edge` objects (euler/core/graph/graph.h:41-209, node.h:59-198), but
columnar and vectorized — every query is a batch query over numpy arrays, so a
single Python call does the work of thousands of per-record C++ virtual calls.
Weighted sampling uses prefix-sum + searchsorted (the vectorized equivalent of
the reference's CompactWeightedCollection binary search, node.h:49-57); global
per-type samplers match Graph::BuildGlobalSampler (graph.h:133-135).

`Graph` stitches shards together: ids are scattered to their owner shard
(`id % P`), queried, and gathered back in input order — the batch-API
equivalent of the reference's SPLIT → REMOTE(shard) → MERGE compiled DAGs
(euler/parser/optimizer.h:49-86, euler/core/kernels/remote_op.cc:31-36).
Shard-weighted global sampling mirrors query_proxy.cc:91-144.

All query results are fixed-shape padded arrays (+ boolean masks) so they can
feed straight into jitted XLA programs.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from euler_tpu.graph import format as tformat
from euler_tpu.graph.meta import BINARY, DENSE, SPARSE, GraphMeta

DEFAULT_ID = np.uint64(0xFFFFFFFFFFFFFFFF)  # padding sentinel for node ids


def _fold_type(dnf, type_id: int):
    """AND a `type == type_id` atom into every DNF clause (no-op if < 0)."""
    if type_id < 0:
        return dnf
    return [list(clause) + [("type", "eq", type_id)] for clause in dnf] or [
        [("type", "eq", type_id)]
    ]


def split_hops(n_roots: int, counts, *arrays):
    """Split flat per-kind arrays (concatenated over hops) into per-hop
    lists: hop i holds n_roots * prod(counts[:i]) entries. Shared by the
    native engine binding and the RPC client so both sides of the fused
    fanout agree on the hop layout."""
    widths = [int(n_roots)]
    for c in counts:
        widths.append(widths[-1] * int(c))
    offs = np.r_[0, np.cumsum(widths)]
    return [
        [a[offs[i] : offs[i + 1]] for i in range(len(widths))]
        for a in arrays
    ]


def lean_wire_ok(roots, hop_w, hop_mask, hop_rows, require_unit_w=True) -> bool:
    """True when a fused-fanout batch satisfies the LEAN-wire invariants:
    unit edge weights (hop_w=None means weights were already proven unit
    cluster-wide, e.g. via unit_edge_weights), no valid root id truncating
    to int32 -1, and no sampler-valid neighbor resolving to a dangling
    (-1) feature row. Lean hydration (dataflow/base.py hydrate_blocks)
    rebuilds edge_w as 1.0 and derives validity from feature row > 0 /
    int32 root_idx — a batch violating any invariant would silently train
    on wrong values, so the ONE definition of the check is shared by the
    client flow and the serving coordinator.

    require_unit_w=False checks only the id/row invariants — the
    weighted-lean wire (VERDICT r3 #5) ships bf16 edge weights next to the
    int32 rows instead of downgrading weighted graphs to full wire."""
    roots = np.asarray(roots, dtype=np.uint64)
    unit_w = not require_unit_w or hop_w is None or all(
        np.all(w.reshape(-1)[m.reshape(-1)] == 1.0)
        for w, m in zip(hop_w[1:], hop_mask[1:])
    )
    root32 = roots.astype(np.int64).astype(np.int32)
    alias = bool(((root32 == -1) & (roots != DEFAULT_ID)).any())
    dangling = any(
        bool(((r.reshape(-1) < 0) & m.reshape(-1)).any())
        for r, m in zip(hop_rows[1:], hop_mask[1:])
    )
    return unit_w and not alias and not dangling


def lean_feats(hop_rows) -> np.ndarray:
    """Concatenated int32 lean feature slots over all hops: global row+1,
    0 for padding/missing — the exact encoding hydrate_blocks and
    DeviceFeatureCache.gather expect."""
    return np.concatenate(
        [
            np.where(np.asarray(r) >= 0, np.asarray(r) + 1, 0).astype(
                np.int32
            )
            for r in hop_rows
        ]
    )


def layerwise_from_full(nbr, w, mask, count: int, rng) -> tuple:
    """LADIES-style layer selection from a batch's full neighbor arrays.

    Candidates are weighted ∝ their TOTAL incident weight from the batch,
    sampled WITHOUT replacement via Gumbel top-k (with-replacement +
    unique would concentrate on the few heaviest candidates and shrink
    the effective layer far below `count`); when the whole frontier fits
    in `count` the layer is EXACT. Shared by GraphStore and the
    partitioned facade — the facade scatter-gathers get_full_neighbor
    first, so a candidate whose incident weight is split across shards
    is weighted by the true global sum (per-shard sampling + union would
    bias toward shard order).

    Returns (layer_ids u64[count], adj f32[n, count], mask bool[count]).
    """
    n = nbr.shape[0]
    flat_ids = nbr[mask]
    flat_w = w[mask].astype(np.float64)
    if len(flat_ids) == 0:
        return (
            np.full(count, DEFAULT_ID, dtype=np.uint64),
            np.zeros((n, count), dtype=np.float32),
            np.zeros(count, dtype=bool),
        )
    uniq, inv = np.unique(flat_ids, return_inverse=True)
    wsum = np.zeros(len(uniq))
    np.add.at(wsum, inv, flat_w)
    if len(uniq) <= count:
        chosen = np.arange(len(uniq))
    else:
        keys = np.log(np.maximum(wsum, 1e-30)) + rng.gumbel(size=len(uniq))
        chosen = np.sort(np.argpartition(-keys, count - 1)[:count])
    layer = np.full(count, DEFAULT_ID, dtype=np.uint64)
    layer[: len(chosen)] = uniq[chosen]
    lmask = layer != DEFAULT_ID
    # batch → layer adjacency
    pos = np.searchsorted(uniq[chosen], nbr.ravel())
    pos = np.clip(pos, 0, len(chosen) - 1)
    hit = mask.ravel() & (uniq[chosen][pos] == nbr.ravel())
    adj = np.zeros((n, count), dtype=np.float32)
    rr = np.repeat(np.arange(n), nbr.shape[1])
    np.add.at(adj, (rr[hit], pos[hit]), w.ravel()[hit])
    return layer, adj, lmask


def multi_hop_neighbor(graph, nodes, edge_types_per_hop):
    """Hop-by-hop unioned receptive field with inter-hop adjacency
    (get_multi_hop_neighbor parity,
    tf_euler/python/euler_ops/neighbor_ops.py:698-731).

    edge_types_per_hop: one edge-type filter (list or None) per hop.
    Returns (nodes_list, adj_list):
      nodes_list[h]  — u64 deduplicated (ascending) node set of hop h;
                       nodes_list[0] is the flattened roots as given.
      adj_list[h]    — weighted COO adjacency from hop-h to hop-(h+1)
                       nodes as (rows i64, cols i64, vals f32, shape),
                       rows/cols indexing into the two node sets.
    Works on any object with the get_full_neighbor surface (local store,
    partitioned facade, remote shard).
    """
    cur = np.asarray(nodes, dtype=np.uint64).reshape(-1)
    nodes_list = [cur]
    adj_list = []
    for et in edge_types_per_hop:
        if cur.size == 0:
            nodes_list.append(np.empty(0, np.uint64))
            adj_list.append(
                (
                    np.empty(0, np.int64),
                    np.empty(0, np.int64),
                    np.empty(0, np.float32),
                    (0, 0),
                )
            )
            continue
        nbr, w, _, mask, _ = graph.get_full_neighbor(cur, et)
        rows2d = np.broadcast_to(
            np.arange(len(cur), dtype=np.int64)[:, None], nbr.shape
        )
        vals = nbr[mask]
        uniq, inv = np.unique(vals, return_inverse=True)
        adj_list.append(
            (
                rows2d[mask],
                inv.astype(np.int64),
                w[mask].astype(np.float32),
                (len(cur), len(uniq)),
            )
        )
        nodes_list.append(uniq)
        cur = uniq
    return nodes_list, adj_list


def _rng(rng) -> np.random.Generator:
    return rng if rng is not None else np.random.default_rng()


class _WeightedSampler:
    """O(log n) vectorized weighted sampling via prefix sums.

    The 8 B/item prefix array is built lazily on first draw — a
    NativeGraphStore routes sampling to the C++ engine, so its Python
    twin must not pay cumsum RAM for tables it never samples.
    """

    def __init__(self, weights: np.ndarray):
        self._weights = np.asarray(weights)
        self.total = float(np.sum(self._weights, dtype=np.float64))
        self.n = len(self._weights)
        self._cum: np.ndarray | None = None

    @property
    def cum(self) -> np.ndarray:
        if self._cum is None:
            self._cum = np.concatenate(
                [[0.0], np.cumsum(self._weights, dtype=np.float64)]
            )
        return self._cum

    def sample(self, count: int, rng) -> np.ndarray:
        if self.n == 0 or self.total <= 0:
            return np.zeros(count, dtype=np.int64)
        target = _rng(rng).random(count) * self.total
        return np.clip(
            np.searchsorted(self.cum, target, side="right") - 1, 0, self.n - 1
        )


class _CSR:
    """Per-edge-type adjacency with cumulative weights for row sampling."""

    def __init__(self, indptr, dst, w, eidx):
        self.indptr = np.asarray(indptr)
        self.dst = np.asarray(dst)
        self.w = np.asarray(w)
        self.eidx = np.asarray(eidx)
        self._cum = None  # lazy (8 B/edge; native stores never touch it)
        self._dst_sorted = None  # lazy: within-row dst-sorted view for lookups

    @property
    def cum(self) -> np.ndarray:
        if self._cum is None:
            self._cum = np.concatenate(
                [[0.0], np.cumsum(self.w, dtype=np.float64)]
            )
        return self._cum

    def degrees(self, rows: np.ndarray) -> np.ndarray:
        return self.indptr[rows + 1] - self.indptr[rows]

    def row_weight(self, rows: np.ndarray) -> np.ndarray:
        return self.cum[self.indptr[rows + 1]] - self.cum[self.indptr[rows]]

    def sample_in_rows(self, rows: np.ndarray, rng) -> np.ndarray:
        """One weighted neighbor element index (global) per entry of `rows`."""
        s, e = self.indptr[rows], self.indptr[rows + 1]
        lo, hi = self.cum[s], self.cum[e]
        target = lo + _rng(rng).random(len(rows)) * (hi - lo)
        j = np.searchsorted(self.cum, target, side="right") - 1
        return np.clip(j, s, np.maximum(s, e - 1))

    def sorted_dst(self):
        """(perm, dst_sorted): within-row permutation sorting dst ascending."""
        if self._dst_sorted is None:
            rows = np.repeat(
                np.arange(len(self.indptr) - 1), np.diff(self.indptr)
            )
            perm = np.lexsort((self.dst, rows))
            self._dst_sorted = (perm, self.dst[perm])
        return self._dst_sorted

    def contains(self, rows: np.ndarray, targets: np.ndarray) -> np.ndarray:
        """Membership: is targets[i] a neighbor of row rows[i]?"""
        perm, dsts = self.sorted_dst()
        s, e = self.indptr[rows], self.indptr[rows + 1]
        out = np.zeros(len(rows), dtype=bool)
        # vectorized per-row binary search using global sorted-by-(row,dst) order
        left = s + _searchsorted_segments(dsts, s, e, targets)
        ok = left < e
        out[ok] = dsts[left[ok]] == targets[ok]
        return out


def _searchsorted_segments(sorted_vals, seg_start, seg_end, targets, side="left"):
    """For each i, insertion position of targets[i] within the sorted slice
    sorted_vals[seg_start[i]:seg_end[i]] (vectorized per-segment binary
    search; side as in np.searchsorted)."""
    n = len(targets)
    lo = np.asarray(seg_start).copy()
    hi = np.asarray(seg_end).copy()
    right = side == "right"
    while True:
        active = lo < hi
        if not active.any():
            break
        mid = (lo + hi) // 2
        less = np.zeros(n, dtype=bool)
        if right:
            less[active] = sorted_vals[mid[active]] <= targets[active]
        else:
            less[active] = sorted_vals[mid[active]] < targets[active]
        lo = np.where(active & less, mid + 1, lo)
        hi = np.where(active & ~less, mid, hi)
    return lo - np.asarray(seg_start)


class GraphStore:
    """One graph shard served from columnar arrays (see builder.py layout)."""

    def __init__(self, meta: GraphMeta, arrays: dict[str, np.ndarray], part: int = 0):
        self.meta = meta
        self.part = part
        self.node_ids = np.asarray(arrays["node_ids"])
        self.node_types = np.asarray(arrays["node_types"])
        self.node_weights = np.asarray(arrays["node_weights"])
        self.num_nodes = len(self.node_ids)
        self.arrays = arrays
        self.adj = [
            _CSR(
                arrays[f"adj_{t}_indptr"],
                arrays[f"adj_{t}_dst"],
                arrays[f"adj_{t}_w"],
                arrays[f"adj_{t}_eidx"],
            )
            for t in range(meta.num_edge_types)
        ]
        self.inadj = [
            _CSR(
                arrays[f"inadj_{t}_indptr"],
                arrays[f"inadj_{t}_dst"],
                arrays[f"inadj_{t}_w"],
                arrays[f"inadj_{t}_eidx"],
            )
            for t in range(meta.num_edge_types)
            if f"inadj_{t}_indptr" in arrays
        ]
        self.edge_src = np.asarray(arrays["edge_src"])
        self.edge_dst = np.asarray(arrays["edge_dst"])
        self.edge_types = np.asarray(arrays["edge_types"])
        self.edge_weights = np.asarray(arrays["edge_weights"])
        # global per-type samplers (Graph::BuildGlobalSampler parity),
        # built lazily: the masked-weight copies + prefix sums cost
        # O(bytes-per-edge) RAM that native-engine stores never need
        self._samplers_n: dict[int, _WeightedSampler] = {}
        self._samplers_e: dict[int, _WeightedSampler] = {}
        self._edge_key_index: tuple | None = None  # lexsorted (src,dst,type)
        self._index_mgr = None
        self._edge_index_mgr = None
        self._unit_w: dict[int, bool] = {}  # per-type all-weights-==-1.0
        # data version served over the wire (`stats.graph_epoch`): any
        # in-place mutation of this shard's arrays must bump_epoch() so
        # client read caches invalidate instead of serving stale bytes.
        # Guarded by _lock: epoch writes race with concurrent merges and
        # server stat reads, and the delta-merge path publishes through it.
        self._lock = threading.Lock()
        self.graph_epoch = 0

    def bump_epoch(self) -> int:
        """Advance the shard's data version after an in-place mutation;
        remote read caches flush on the next epoch observation."""
        with self._lock:
            self.graph_epoch += 1
            return self.graph_epoch

    def merge_delta(self, delta):
        """Publish a DeltaStore at an epoch boundary.

        Folds the staged mutations into this shard's arrays — rebuilding
        only the touched CSR rows / feature rows (untouched arrays are
        carried by reference) — and returns ``(new_store, rows, ids)``:
        a NEW GraphStore over the merged arrays with ``graph_epoch``
        bumped, the mutated LOCAL rows (new row space, including every
        row whose index shifted through an insert/delete), and the node
        ids whose cached blocks went stale. The receiving process swaps
        its store reference in one assignment, so in-flight reads finish
        on this (immutable) snapshot and hot-path readers can never see
        a torn mix of epochs — the same swap discipline as the serving
        hot reload. Samplers and edge-key indexes rebuild lazily on the
        new store (the "sampler alias" rebuild is confined to the merged
        shard); attribute indexes whose backing columns rode through the
        merge by reference are CARRIED (IndexManager.carry_from), so a
        publish only pays index rebuilds for the fields it touched.

        Bit-parity contract: the merged arrays equal a from-scratch
        ``build_from_json`` of the equivalently mutated graph.json —
        pinned by tests/test_delta.py.
        """
        from euler_tpu.graph.delta import merge_arrays
        from euler_tpu.graph.index import IndexManager

        with self._lock:
            new_arrays, rows, ids = merge_arrays(
                self.meta, self.arrays, self.part, delta
            )
            new_store = GraphStore(self.meta, new_arrays, self.part)
            new_store.graph_epoch = self.graph_epoch + 1
            # attribute-index carry: merge_arrays moves untouched columns
            # by reference, so any per-field index whose backing arrays
            # (and the row numbering) rode through unchanged is adopted
            # into the new store instead of rebuilt on first conditioned
            # query — parity vs a full rebuild pinned in tests/test_index.py
            for attr, node in (("_index_mgr", True),
                               ("_edge_index_mgr", False)):
                old_mgr = getattr(self, attr)
                if old_mgr is None or not old_mgr._cache:
                    continue
                mgr = IndexManager(new_store, node=node)
                mgr.carry_from(old_mgr, self.arrays, new_arrays)
                setattr(new_store, attr, mgr)
        return new_store, rows, ids

    # ---- id resolution -------------------------------------------------

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        """External u64 ids → local rows; -1 for missing (vectorized)."""
        ids = np.asarray(ids, dtype=np.uint64)
        if self.num_nodes == 0:
            return np.full(len(ids), -1, dtype=np.int64)
        pos = np.searchsorted(self.node_ids, ids)
        pos = np.clip(pos, 0, self.num_nodes - 1)
        ok = self.node_ids[pos] == ids
        return np.where(ok, pos, -1).astype(np.int64)

    # ---- global sampling (api.h:44-52 parity) --------------------------

    def _node_sampler(self, node_type: int) -> _WeightedSampler:
        key = -1 if node_type < 0 else int(node_type)
        if key >= self.meta.num_node_types:
            raise IndexError(f"node type {key} out of range")
        s = self._samplers_n.get(key)
        if s is None:
            # build outside the lock (masked-weight copy can be big),
            # publish under it — racing builders agree via setdefault
            w = (
                self.node_weights
                if key < 0
                else np.where(self.node_types == key, self.node_weights, 0.0)
            )
            with self._lock:
                s = self._samplers_n.setdefault(key, _WeightedSampler(w))
        return s

    def _edge_sampler(self, edge_type: int) -> _WeightedSampler:
        key = -1 if edge_type < 0 else int(edge_type)
        if key >= self.meta.num_edge_types:
            raise IndexError(f"edge type {key} out of range")
        s = self._samplers_e.get(key)
        if s is None:
            w = (
                self.edge_weights
                if key < 0
                else np.where(self.edge_types == key, self.edge_weights, 0.0)
            )
            with self._lock:
                s = self._samplers_e.setdefault(key, _WeightedSampler(w))
        return s

    def unit_edge_weights(self, edge_types=None) -> bool:
        """True when every (selected) out-edge weight is exactly 1.0 —
        the precondition for the distributed LEAN fanout to skip shipping
        weights entirely. Chunked scan with early exit (weighted graphs
        usually fail within the first chunk; uniform graphs stream the
        mmap once without a whole-array boolean temp), cached per type."""
        types = (
            range(self.meta.num_edge_types)
            if edge_types is None
            else edge_types
        )
        for t in types:
            key = int(t)
            if key not in self._unit_w:
                # scan outside the lock (mmap stream), publish under it
                ok = True
                if key < len(self.adj):
                    w = self.adj[key].w
                    for lo in range(0, len(w), 1 << 22):
                        if not np.all(w[lo : lo + (1 << 22)] == 1.0):
                            ok = False
                            break
                with self._lock:
                    self._unit_w.setdefault(key, ok)
            if not self._unit_w[key]:
                return False
        return True

    def sample_neighbor_rows(self, ids, edge_types=None, count=10, rng=None):
        """Lean neighbor draw: (nbr, mask, local_rows) — rows are this
        shard's local node rows of each picked dst, -1 when the dst is
        owned elsewhere. Pure-numpy twin of the engine's
        etpu_sample_neighbor_rows."""
        nbr, _, _, mask, _ = self.sample_neighbor(
            ids, edge_types, count, rng
        )
        rows = self.lookup(nbr.reshape(-1)).reshape(nbr.shape)
        return nbr, mask, rows

    def sample_node(self, count: int, node_type: int = -1, rng=None) -> np.ndarray:
        sampler = self._node_sampler(node_type)
        rowz = sampler.sample(count, rng)
        if sampler.total <= 0:
            return np.full(count, DEFAULT_ID, dtype=np.uint64)
        return self.node_ids[rowz]

    def sample_edge(self, count: int, edge_type: int = -1, rng=None) -> np.ndarray:
        """Returns [count, 3] uint64 rows of (src, dst, type)."""
        sampler = self._edge_sampler(edge_type)
        if sampler.total <= 0:
            return np.full((count, 3), DEFAULT_ID, dtype=np.uint64)
        rowz = sampler.sample(count, rng)
        return np.stack(
            [
                self.edge_src[rowz],
                self.edge_dst[rowz],
                self.edge_types[rowz].astype(np.uint64),
            ],
            axis=1,
        )

    def node_type(self, ids: np.ndarray) -> np.ndarray:
        rows = self.lookup(ids)
        out = np.full(len(rows), -1, dtype=np.int32)
        ok = rows >= 0
        out[ok] = self.node_types[rows[ok]]
        return out

    # ---- neighbor queries (node.h:82-112 parity) -----------------------

    def _csrs(self, edge_types, in_edges: bool = False) -> list[_CSR]:
        table = self.inadj if in_edges else self.adj
        types = (
            range(self.meta.num_edge_types)
            if edge_types is None
            else edge_types
        )
        return [(t, table[t]) for t in types]

    def sample_neighbor(
        self, ids, edge_types=None, count: int = 10, rng=None, in_edges=False
    ):
        """Weighted neighbor sampling with replacement.

        Returns (nbr_ids u64[n,count], weights f32[n,count], types i32[n,count],
        mask bool[n,count]).
        """
        rng = _rng(rng)
        ids = np.asarray(ids, dtype=np.uint64)
        rows = self.lookup(ids)
        n = len(rows)
        csrs = self._csrs(edge_types, in_edges)
        safe = np.maximum(rows, 0)
        # per (node, type) total weights → type choice per draw
        tot = np.stack([c.row_weight(safe) for _, c in csrs], axis=1)  # [n, T]
        tot[rows < 0] = 0.0
        row_total = tot.sum(axis=1)
        mask_any = row_total > 0
        cum_t = np.cumsum(tot, axis=1)
        u = rng.random((n, count)) * row_total[:, None]
        type_choice = (u[:, :, None] >= cum_t[:, None, :]).sum(axis=2)  # [n,count]
        type_choice = np.minimum(type_choice, len(csrs) - 1)

        nbr = np.full((n, count), DEFAULT_ID, dtype=np.uint64)
        w = np.zeros((n, count), dtype=np.float32)
        tt = np.full((n, count), -1, dtype=np.int32)
        eidx = np.full((n, count), -1, dtype=np.int64)
        for k, (t, c) in enumerate(csrs):
            sel = (type_choice == k) & mask_any[:, None] & (rows >= 0)[:, None]
            if not sel.any() or len(c.dst) == 0:
                continue
            r_sel = np.repeat(safe, count).reshape(n, count)[sel]
            has = c.degrees(r_sel) > 0
            j = c.sample_in_rows(r_sel[has], rng)
            flat = np.zeros(sel.sum(), dtype=np.int64)
            flat[has] = j
            vals = np.where(has, c.dst[flat], DEFAULT_ID)
            nbr[sel] = vals
            w[sel] = np.where(has, c.w[flat], 0.0).astype(np.float32)
            tt[sel] = np.where(has, t, -1)
            eidx[sel] = np.where(has, c.eidx[flat], -1)
        mask = nbr != DEFAULT_ID
        return nbr, w, tt, mask, eidx

    def get_full_neighbor(
        self, ids, edge_types=None, max_degree=None, in_edges=False, sort_by=None
    ):
        """Padded full adjacency.

        sort_by: None (storage order) | 'id' | 'weight' (descending, for top-k).
        Returns (nbr u64[n,D], w f32[n,D], types i32[n,D], mask bool[n,D],
        eidx i64[n,D]).
        """
        ids = np.asarray(ids, dtype=np.uint64)
        rows = self.lookup(ids)
        n = len(rows)
        safe = np.maximum(rows, 0)
        csrs = self._csrs(edge_types, in_edges)
        degs = np.stack(
            [c.degrees(safe) for _, c in csrs], axis=1
        )  # [n, T]
        degs[rows < 0] = 0
        total_deg = degs.sum(axis=1)
        cap = int(total_deg.max()) if max_degree is None else int(max_degree)
        cap = max(cap, 1)
        nbr = np.full((n, cap), DEFAULT_ID, dtype=np.uint64)
        w = np.zeros((n, cap), dtype=np.float32)
        tt = np.full((n, cap), -1, dtype=np.int32)
        eidx = np.full((n, cap), -1, dtype=np.int64)
        col = np.zeros(n, dtype=np.int64)
        for k, (t, c) in enumerate(csrs):
            d = degs[:, k]
            present = d > 0
            if not present.any():
                col += 0
                continue
            # element indices per row, flattened
            reps = d[present]
            r_idx = np.repeat(np.nonzero(present)[0], reps)
            starts = c.indptr[safe[present]]
            offs = np.arange(reps.sum()) - np.repeat(
                np.cumsum(reps) - reps, reps
            )
            src_el = np.repeat(starts, reps) + offs
            dest_col = np.repeat(col[present], reps) + offs
            keep = dest_col < cap
            nbr[r_idx[keep], dest_col[keep]] = c.dst[src_el[keep]]
            w[r_idx[keep], dest_col[keep]] = c.w[src_el[keep]]
            tt[r_idx[keep], dest_col[keep]] = t
            eidx[r_idx[keep], dest_col[keep]] = c.eidx[src_el[keep]]
            col += d
        mask = nbr != DEFAULT_ID
        if sort_by == "id":
            order = np.argsort(np.where(mask, nbr, DEFAULT_ID), axis=1, kind="stable")
        elif sort_by == "weight":
            order = np.argsort(np.where(mask, -w, np.inf), axis=1, kind="stable")
        else:
            order = None
        if order is not None:
            take = np.take_along_axis
            nbr = take(nbr, order, 1)
            w = take(w, order, 1)
            tt = take(tt, order, 1)
            eidx = take(eidx, order, 1)
            mask = take(mask, order, 1)
        return nbr, w, tt, mask, eidx

    def degree_sum(self, ids, edge_types=None, in_edges=False) -> np.ndarray:
        """Total degree per id across the requested edge types (0 if absent)."""
        rows = self.lookup(ids)
        safe = np.maximum(rows, 0)
        total = np.zeros(len(rows), dtype=np.int64)
        for _, c in self._csrs(edge_types, in_edges):
            total += c.degrees(safe)
        total[rows < 0] = 0
        return total

    def get_top_k_neighbor(self, ids, edge_types=None, k=10, in_edges=False):
        nbr, w, tt, mask, eidx = self.get_full_neighbor(
            ids, edge_types, in_edges=in_edges, sort_by="weight"
        )
        pad = max(k - nbr.shape[1], 0)
        if pad:
            nbr = np.pad(nbr, ((0, 0), (0, pad)), constant_values=DEFAULT_ID)
            w = np.pad(w, ((0, 0), (0, pad)))
            tt = np.pad(tt, ((0, 0), (0, pad)), constant_values=-1)
            mask = np.pad(mask, ((0, 0), (0, pad)))
            eidx = np.pad(eidx, ((0, 0), (0, pad)), constant_values=-1)
        return nbr[:, :k], w[:, :k], tt[:, :k], mask[:, :k], eidx[:, :k]

    def get_multi_hop_neighbor(self, nodes, edge_types_per_hop):
        return multi_hop_neighbor(self, nodes, edge_types_per_hop)

    # ---- layerwise sampling (API_SAMPLE_L, sample_layer_op.cc:83) ------

    def sample_neighbor_layerwise(
        self, batch_ids, edge_types=None, count: int = 128, rng=None
    ):
        """LADIES-style layer sampling: one candidate set for the whole batch.

        Samples `count` layer nodes ∝ total incident weight from the batch,
        then returns the batch→layer adjacency restricted to sampled nodes.
        Returns (layer_ids u64[count], adj f32[n, count], mask bool[count]).
        """
        rng = _rng(rng)
        batch_ids = np.asarray(batch_ids, dtype=np.uint64)
        nbr, w, _, mask, _ = self.get_full_neighbor(batch_ids, edge_types)
        return layerwise_from_full(nbr, w, mask, count, rng)

    # ---- features (node.h:120-145 / feature_ops parity) ----------------

    def _feat(self, prefix: str, kind: str, fid: int, suffix: str = ""):
        key = {
            DENSE: f"{prefix}_dense_{fid}",
            SPARSE: f"{prefix}_sparse_{fid}{suffix}",
            BINARY: f"{prefix}_bin_{fid}{suffix}",
        }[kind]
        return self.arrays[key]

    def get_dense_feature(self, ids, names: list[str]) -> np.ndarray:
        """[n, sum(dims)] f32; missing nodes → zeros."""
        rows = self.lookup(ids)
        return self._dense_by_rows(rows, names, node=True)

    def get_dense_feature_udf(self, ids, names, udfs):
        """Per (name, udf) pair: aggregate the feature block in place and
        return ([n, sum(k_i)], widths) — the server-side half of remote
        `values(udf_*)` (udf.h / API_GET_P semantics: ship the aggregate,
        not the block)."""
        from euler_tpu.query.gql import dense_feature_udf

        return dense_feature_udf(self, ids, names, udfs)

    def get_dense_by_rows(self, rows, names) -> np.ndarray:
        """Dense node features by pre-resolved local rows (-1 → zeros);
        skips the id lookup. Same contract as the native engine's."""
        return self._dense_by_rows(
            np.asarray(rows, dtype=np.int64), names, node=True
        )

    def _dense_by_rows(self, rows, names, node: bool) -> np.ndarray:
        prefix = "nf" if node else "ef"
        specs = [self.meta.feature_spec(nm, node=node) for nm in names]
        cols = []
        safe = np.maximum(rows, 0)
        for spec in specs:
            vals = self._feat(prefix, DENSE, spec.fid)
            out = np.asarray(vals[safe], dtype=np.float32)
            out[rows < 0] = 0.0
            cols.append(out)
        return np.concatenate(cols, axis=1) if cols else np.zeros((len(rows), 0), np.float32)

    def get_sparse_feature(self, ids, names: list[str], max_len: int | None = None):
        """Per name: (values u64[n, L], mask bool[n, L])."""
        rows = self.lookup(ids)
        return self._varlen_by_rows(rows, names, SPARSE, node=True, max_len=max_len)

    def get_binary_feature(self, ids, names: list[str]) -> list[list[bytes]]:
        rows = self.lookup(ids)
        out = []
        for nm in names:
            spec = self.meta.feature_spec(nm, node=True)
            indptr = self._feat("nf", BINARY, spec.fid, "_indptr")
            blob = self._feat("nf", BINARY, spec.fid, "_values")
            vals = []
            for r in rows:
                if r < 0:
                    vals.append(b"")
                else:
                    vals.append(bytes(blob[indptr[r] : indptr[r + 1]]))
            out.append(vals)
        return out

    def _varlen_by_rows(self, rows, names, kind, node: bool, max_len=None):
        prefix = "nf" if node else "ef"
        out = []
        for nm in names:
            spec = self.meta.feature_spec(nm, node=node)
            indptr = self._feat(prefix, kind, spec.fid, "_indptr")
            values = self._feat(prefix, kind, spec.fid, "_values")
            safe = np.maximum(rows, 0)
            lens = np.where(rows >= 0, indptr[safe + 1] - indptr[safe], 0)
            cap = int(max_len) if max_len else max(int(lens.max(initial=0)), 1)
            if len(values) == 0:  # feature declared but empty everywhere
                out.append(
                    (
                        np.zeros((len(rows), cap), dtype=values.dtype),
                        np.zeros((len(rows), cap), dtype=bool),
                    )
                )
                continue
            # vectorized ragged gather: slot j of row i reads
            # values[indptr[row]+j] while j < len(row)
            j = np.arange(cap)
            mask = j[None, :] < np.minimum(lens, cap)[:, None]
            idx = indptr[safe][:, None] + j[None, :]
            np.clip(idx, 0, len(values) - 1, out=idx)
            vals = np.where(
                mask, np.asarray(values)[idx], np.zeros((), values.dtype)
            )
            out.append((vals, mask))
        return out

    # ---- edge features -------------------------------------------------

    def _edge_rows(self, edge_ids: np.ndarray) -> np.ndarray:
        """(src,dst,type) triples [n,3] u64 → edge row indices, -1 missing.

        Backed by a lazily-built (src,dst,type)-lexsorted permutation +
        vectorized segmented binary search: O(E log E) numpy sort once,
        O(n log E) per query batch — no Python dict over every edge
        (node.h:49-57 keeps per-node sorted adjacency for the same reason;
        parallel duplicate triples resolve to one of their rows).
        """
        if self._edge_key_index is None:
            # O(E log E) sort outside the lock; publish under it with a
            # re-check so racing builders keep exactly one index
            order = np.lexsort(
                (self.edge_types, self.edge_dst, self.edge_src)
            ).astype(np.int64)
            built = (
                order,
                np.ascontiguousarray(self.edge_src[order]),
                np.ascontiguousarray(self.edge_dst[order]),
                np.ascontiguousarray(self.edge_types[order]),
            )
            with self._lock:
                if self._edge_key_index is None:
                    self._edge_key_index = built
        order, s_src, s_dst, s_typ = self._edge_key_index
        q = np.asarray(edge_ids, dtype=np.uint64).reshape(-1, 3)
        if len(order) == 0:  # edge-less shard: nothing can match
            return np.full(len(q), -1, dtype=np.int64)
        qs, qd = q[:, 0], q[:, 1]
        qt = q[:, 2].astype(s_typ.dtype)
        # narrow [lo, hi) three levels deep: src, then dst, then type
        lo = np.searchsorted(s_src, qs, side="left")
        hi = np.searchsorted(s_src, qs, side="right")
        lo2 = lo + _searchsorted_segments(s_dst, lo, hi, qd, side="left")
        hi2 = lo + _searchsorted_segments(s_dst, lo, hi, qd, side="right")
        pos = lo2 + _searchsorted_segments(s_typ, lo2, hi2, qt, side="left")
        safe = np.minimum(pos, max(len(order) - 1, 0))
        hit = (
            (pos < hi2)
            & (s_typ[safe] == qt)
            & (s_dst[safe] == qd)
            & (s_src[safe] == qs)
        )
        return np.where(hit, order[safe], -1)

    def get_edge_dense_feature(self, edge_ids, names: list[str]) -> np.ndarray:
        rows = self._edge_rows(edge_ids)
        return self._dense_by_rows(rows, names, node=False)

    def get_edge_sparse_feature(self, edge_ids, names, max_len=None):
        rows = self._edge_rows(edge_ids)
        return self._varlen_by_rows(rows, names, SPARSE, node=False, max_len=max_len)

    def get_edge_binary_feature(self, edge_ids, names: list[str]):
        rows = self._edge_rows(edge_ids)
        out = []
        for nm in names:
            spec = self.meta.feature_spec(nm, node=False)
            indptr = self._feat("ef", BINARY, spec.fid, "_indptr")
            blob = self._feat("ef", BINARY, spec.fid, "_values")
            out.append(
                [
                    bytes(blob[indptr[r] : indptr[r + 1]]) if r >= 0 else b""
                    for r in rows
                ]
            )
        return out

    # ---- attribute indexes / conditioned sampling ----------------------
    # (euler/core/index parity: IndexManager + SampleIndex::Search feeding
    #  conditioned sample_node and the API_GET_NB_FILTER path)

    @property
    def index_manager(self):
        if self._index_mgr is None:
            from euler_tpu.graph.index import IndexManager

            built = IndexManager(self, node=True)
            with self._lock:
                if self._index_mgr is None:
                    self._index_mgr = built
        return self._index_mgr

    @property
    def edge_index_manager(self):
        if self._edge_index_mgr is None:
            from euler_tpu.graph.index import IndexManager

            built = IndexManager(self, node=False)
            with self._lock:
                if self._edge_index_mgr is None:
                    self._edge_index_mgr = built
        return self._edge_index_mgr

    def search_condition(self, dnf, node: bool = True):
        mgr = self.index_manager if node else self.edge_index_manager
        return mgr.search_dnf(dnf)

    def sample_node_with_condition(
        self, count: int, dnf, node_type: int = -1, rng=None
    ) -> np.ndarray:
        """Weighted node sampling restricted to rows matching a DNF condition."""
        res = self.search_condition(_fold_type(dnf, node_type))
        return self.sample_from_result(res, count, rng)

    def sample_from_result(self, res, count: int, rng=None) -> np.ndarray:
        """Sample node ids from an already-computed IndexResult."""
        rng = _rng(rng)
        rowz = res.sample(count, rng)
        out = np.full(count, DEFAULT_ID, dtype=np.uint64)
        ok = rowz >= 0
        out[ok] = self.node_ids[rowz[ok]]
        return out

    def sample_edge_with_condition(
        self, count: int, dnf, edge_type: int = -1, rng=None
    ) -> np.ndarray:
        """Exact-count conditioned edge sampling → [count, 3] (src,dst,type)."""
        res = self.search_condition(_fold_type(dnf, edge_type), node=False)
        return self.sample_edges_from_result(res, count, rng)

    def sample_edges_from_result(self, res, count: int, rng=None) -> np.ndarray:
        rng = _rng(rng)
        rowz = res.sample(count, rng)
        out = np.full((count, 3), DEFAULT_ID, dtype=np.uint64)
        ok = rowz >= 0
        safe = np.maximum(rowz, 0)
        for j, col in enumerate(
            (self.edge_src, self.edge_dst, self.edge_types.astype(np.uint64))
        ):
            out[ok, j] = col[safe][ok]
        return out

    def condition_mask(self, ids, dnf, node: bool = True) -> np.ndarray:
        """Bool mask: does each id satisfy the DNF condition?"""
        rows = (
            self.lookup(np.asarray(ids, dtype=np.uint64))
            if node
            else self._edge_rows(ids)
        )
        return self.search_condition(dnf, node=node).contains(rows)

    def get_node_ids_by_condition(self, dnf) -> np.ndarray:
        res = self.search_condition(dnf)
        return np.asarray(self.node_ids[res.rows], dtype=np.uint64)

    # ---- graph-label path (whole-graph batches) ------------------------

    def get_graph_by_label(self, label_ids: np.ndarray) -> list[np.ndarray]:
        indptr = self.arrays["glabel_indptr"]
        nodes = self.arrays["glabel_nodes"]
        out = []
        for li in np.asarray(label_ids, dtype=np.int64):
            if 0 <= li < len(indptr) - 1:
                out.append(np.asarray(nodes[indptr[li] : indptr[li + 1]]))
            else:
                out.append(np.zeros(0, dtype=np.uint64))
        return out

    # ---- random walks (random_walk_op.cc:27-90 parity) -----------------

    def random_walk(
        self,
        ids,
        edge_types=None,
        walk_len: int = 3,
        p: float = 1.0,
        q: float = 1.0,
        rng=None,
    ) -> np.ndarray:
        """node2vec walk. Returns u64 [n, walk_len+1]; DEFAULT_ID once stuck."""
        rng = _rng(rng)
        ids = np.asarray(ids, dtype=np.uint64)
        n = len(ids)
        walks = np.full((n, walk_len + 1), DEFAULT_ID, dtype=np.uint64)
        walks[:, 0] = ids
        cur = ids.copy()
        prev = np.full(n, DEFAULT_ID, dtype=np.uint64)
        for step in range(1, walk_len + 1):
            if p == 1.0 and q == 1.0:
                nbr, _, _, mask, _ = self.sample_neighbor(cur, edge_types, 1, rng)
                nxt = np.where(mask[:, 0], nbr[:, 0], DEFAULT_ID)
            else:
                nxt = self._node2vec_step(cur, prev, edge_types, p, q, rng)
            dead = cur == DEFAULT_ID
            nxt[dead] = DEFAULT_ID
            walks[:, step] = nxt
            prev, cur = cur, nxt
        return walks

    def _node2vec_step(self, cur, prev, edge_types, p, q, rng):
        """One node2vec transition. `prev` may be off-shard: the 1/p return
        bias works from ids alone; the "distance-1" membership bias needs
        prev's adjacency and degrades to 1/q when prev is not local."""
        nbr, w, _, mask, _ = self.get_full_neighbor(cur, edge_types)
        n, cap = nbr.shape
        rows = self.lookup(cur)
        # bias: 1/p back to prev, 1 if nbr adjacent to prev, 1/q else
        adj_w = w.astype(np.float64).copy()
        prev = np.asarray(prev, dtype=np.uint64)
        prev_rows = self.lookup(prev)
        has_prev = prev != DEFAULT_ID
        prev_local = prev_rows >= 0
        flat_prev = np.repeat(np.maximum(prev_rows, 0), cap)
        flat_nbr = nbr.ravel()
        is_back = flat_nbr == np.repeat(prev, cap)
        near = np.zeros(n * cap, dtype=bool)
        for t, c in self._csrs(edge_types):
            near |= c.contains(flat_prev, flat_nbr)
        near &= np.repeat(prev_local, cap)
        bias = np.where(is_back, 1.0 / p, np.where(near, 1.0, 1.0 / q))
        bias = np.where(np.repeat(has_prev, cap), bias, 1.0).reshape(n, cap)
        adj_w *= bias
        adj_w[~mask] = 0.0
        tot = adj_w.sum(axis=1)
        ok = tot > 0
        r = _rng(rng).random(n) * np.maximum(tot, 1e-30)
        choice = (r[:, None] >= np.cumsum(adj_w, axis=1)).sum(axis=1)
        choice = np.minimum(choice, cap - 1)
        out = np.where(
            ok & (rows >= 0), nbr[np.arange(n), choice], DEFAULT_ID
        )
        return out


class Graph:
    """Multi-shard facade: in-process shards today, RPC shards later.

    This is the single entry point trainers use — the `QueryProxy` of the TPU
    build (euler/client/query_proxy.h:39-93). All methods accept/return padded
    numpy batches.
    """

    def __init__(self, meta: GraphMeta, shards: list[GraphStore]):
        self.meta = meta
        self.shards = shards
        self.num_shards = len(shards)
        # elastic resharding (PR 19): bumped by swap_topology so writers
        # and device staging know the shard LAYOUT changed (row spaces
        # moved), independently of per-shard graph_epoch data versions
        self.topology_epoch = 0
        # shard-weighted root sampling (query_proxy.cc:91-144)
        self._node_shard_w = np.asarray(meta.node_weight_sums, dtype=np.float64)
        self._edge_shard_w = np.asarray(meta.edge_weight_sums, dtype=np.float64)
        # overlap per-shard dispatch when any shard is remote: while this
        # process waits on a peer's RPC, its own (GIL-releasing) native
        # sampling proceeds — the coordinator's per-hop rounds then cost
        # max(local, peer) instead of their sum. Single-core hosts stay
        # sequential: there the pool only adds handoff overhead (measured
        # ~7% on the 1-core bench box).
        self._parallel_dispatch = (
            self.num_shards > 1
            and any(hasattr(s, "call") for s in shards)
            and (os.cpu_count() or 1) > 1
        )
        # created eagerly: _scatter_gather runs on several server worker
        # threads at once, and a lazy unsynchronized init would let two
        # first-callers each build (and one leak) an executor
        if self._parallel_dispatch:
            from concurrent.futures import ThreadPoolExecutor

            self._dispatch_pool = ThreadPoolExecutor(
                max_workers=min(self.num_shards, 8)
            )
        else:
            self._dispatch_pool = None

    def refresh_shard_weights(self) -> None:
        """Re-read the per-shard weight sums from the meta — the facade
        copies them at construction, and a published delta merge updates
        the meta's lists in place, so root-sampling shard weights must
        re-sync after every publish (GraphWriter.publish calls this)."""
        self._node_shard_w = np.asarray(
            self.meta.node_weight_sums, dtype=np.float64
        )
        self._edge_shard_w = np.asarray(
            self.meta.edge_weight_sums, dtype=np.float64
        )

    def swap_topology(self, meta: GraphMeta, shards: list) -> int:
        """Re-point this facade at a resharded cluster P→P′ in place
        (PR 19): `connect()`'s topology watch calls this so every handle
        the trainer/writer/server already holds re-routes without a
        reconnect. Returns the bumped topology_epoch.

        Lock-free against in-flight readers by assignment ordering:
        `_scatter_gather` derives the shard count from ONE snapshot of
        the shards list, and the root-sampling paths (which read the
        weight tables and the shards list separately) are ordered so any
        interleaving indexes in bounds — a grow publishes the longer
        shards list first, a shrink publishes the shorter weight tables
        first. A reader racing the swap instant may route one request to
        a shard that no longer owns the id and get the standard
        missing-row defaults; the next call is consistent. The old
        dispatch pool is intentionally NOT shut down — an in-flight
        scatter may still hold it, and reshards are rare enough that an
        idle executor is cheaper than racing a shutdown."""
        growing = len(shards) >= len(self.shards)
        parallel = (
            len(shards) > 1
            and any(hasattr(s, "call") for s in shards)
            and (os.cpu_count() or 1) > 1
        )
        pool = None
        if parallel:
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(max_workers=min(len(shards), 8))
        node_w = np.asarray(meta.node_weight_sums, dtype=np.float64)
        edge_w = np.asarray(meta.edge_weight_sums, dtype=np.float64)
        self.meta = meta
        if growing:
            self.shards = list(shards)
            self.num_shards = len(shards)
            self._node_shard_w = node_w
            self._edge_shard_w = edge_w
        else:
            self._node_shard_w = node_w
            self._edge_shard_w = edge_w
            self.num_shards = len(shards)
            self.shards = list(shards)
        self._dispatch_pool = pool
        self._parallel_dispatch = parallel
        self.topology_epoch += 1
        return self.topology_epoch

    # -- construction ----------------------------------------------------

    @classmethod
    def load(
        cls, directory: str, mmap: bool = True, native: bool | None = None
    ) -> "Graph":
        """native=True → C++ engine hot paths; None → auto (use if it builds)."""
        meta = GraphMeta.load(directory)
        store_cls = GraphStore
        if native is None or native:
            try:
                from euler_tpu.graph.native import (
                    NativeGraphStore,
                    engine_available,
                )

                if engine_available():
                    store_cls = NativeGraphStore
                elif native:
                    raise RuntimeError("native engine unavailable")
            except Exception:
                if native:
                    raise
        shards = []
        for p in range(meta.num_partitions):
            part_dir = os.path.join(directory, f"part_{p}")
            arrays = tformat.read_arrays(part_dir, mmap)
            if store_cls is GraphStore:
                shards.append(GraphStore(meta, arrays, part=p))
            else:
                shards.append(store_cls(meta, arrays, p, part_dir))
        return cls(meta, shards)

    @classmethod
    def from_json(cls, graph_json, num_partitions: int = 1) -> "Graph":
        from euler_tpu.graph.builder import build_from_json

        meta, arrays = build_from_json(graph_json, num_partitions)
        return cls(meta, [GraphStore(meta, a, p) for p, a in enumerate(arrays)])

    # -- scatter/gather helper (SPLIT → REMOTE → MERGE equivalent) -------

    def _owner(self, ids: np.ndarray) -> np.ndarray:
        return (np.asarray(ids, dtype=np.uint64) % np.uint64(self.num_shards)).astype(
            np.int64
        )

    def _scatter_gather(self, ids, fn, extras=()):
        """fn(shard, sub_ids, *sub_extras) → tuple/array, gathered to input order.

        `extras` are arrays aligned with `ids`, scattered the same way.
        """
        ids = np.asarray(ids, dtype=np.uint64)
        # ONE snapshot of the shards list per call: count, routing, and
        # dispatch all derive from it, so a concurrent swap_topology can
        # never tear this scatter across two topologies
        shards = self.shards
        num = len(shards)
        pool = self._dispatch_pool
        if num == 1 or len(ids) == 0:
            return fn(shards[0], ids, *extras)
        owner = (ids % np.uint64(num)).astype(np.int64)
        index = [np.nonzero(owner == s)[0] for s in range(num)]
        if pool is not None:
            futs = [
                pool.submit(
                    fn, shards[s], ids[sel], *[e[sel] for e in extras]
                )
                if len(sel)
                else None
                for s, sel in enumerate(index)
            ]
            parts = [f.result() if f is not None else None for f in futs]
        else:
            parts = [
                fn(shards[s], ids[sel], *[e[sel] for e in extras])
                if len(sel)
                else None
                for s, sel in enumerate(index)
            ]
        # find a template result to size outputs
        template = next(p for p in parts if p is not None)
        single = not isinstance(template, tuple)
        outs = []
        n = len(ids)
        arrs = (template,) if single else template
        for a in arrs:
            out = np.zeros((n,) + a.shape[1:], dtype=a.dtype)
            if a.dtype == np.uint64:
                out[:] = DEFAULT_ID
            elif a.dtype in (np.int32, np.int64):
                out[:] = -1
            outs.append(out)
        for s, sel in enumerate(index):
            if parts[s] is None:
                continue
            res = (parts[s],) if single else parts[s]
            for o, a in zip(outs, res):
                o[sel] = a
        return outs[0] if single else tuple(outs)

    # -- API surface -----------------------------------------------------

    def sample_node(self, count: int, node_type: int = -1, rng=None) -> np.ndarray:
        rng = _rng(rng)
        node_type = self.meta.node_type_id(node_type) if isinstance(node_type, str) else node_type
        # snapshot the shards list once (swap_topology race discipline):
        # count, weights, and dispatch all derive from this one read
        shards = self.shards
        if len(shards) == 1:
            return shards[0].sample_node(count, node_type, rng)
        w = (
            self._node_shard_w.sum(axis=1)
            if node_type < 0
            else self._node_shard_w[:, node_type]
        )
        if len(w) != len(shards):  # mid-swap: weights lag one assignment
            w = np.ones(len(shards), dtype=np.float64)
        picks = _WeightedSampler(w).sample(count, rng)
        out = np.empty(count, dtype=np.uint64)
        for s, sh in enumerate(shards):
            sel = picks == s
            if sel.any():
                out[sel] = sh.sample_node(int(sel.sum()), node_type, rng)
        return out

    def sample_edge(self, count: int, edge_type: int = -1, rng=None) -> np.ndarray:
        rng = _rng(rng)
        shards = self.shards
        if len(shards) == 1:
            return shards[0].sample_edge(count, edge_type, rng)
        w = (
            self._edge_shard_w.sum(axis=1)
            if edge_type < 0
            else self._edge_shard_w[:, edge_type]
        )
        if len(w) != len(shards):  # mid-swap: weights lag one assignment
            w = np.ones(len(shards), dtype=np.float64)
        picks = _WeightedSampler(w).sample(count, rng)
        out = np.empty((count, 3), dtype=np.uint64)
        for s, sh in enumerate(shards):
            sel = picks == s
            if sel.any():
                out[sel] = sh.sample_edge(int(sel.sum()), edge_type, rng)
        return out

    def node_type(self, ids) -> np.ndarray:
        return self._scatter_gather(ids, lambda sh, i: sh.node_type(i))

    # -- conditioned sampling / filters (index subsystem, euler/core/index) --

    def sample_node_with_condition(
        self, count: int, dnf, node_type: int = -1, rng=None
    ) -> np.ndarray:
        """Sample nodes matching a DNF condition, weighted across shards by
        each shard's matched weight (index-aware root sampling)."""
        rng = _rng(rng)
        if isinstance(node_type, str):
            node_type = self.meta.node_type_id(node_type)
        dnf = _fold_type(dnf, node_type)
        shards = self.shards
        if len(shards) == 1:
            return shards[0].sample_node_with_condition(count, dnf, -1, rng)
        # one DNF search per shard, reused for both the shard-weight draw and
        # the within-shard sample
        results = [sh.search_condition(dnf) for sh in shards]
        w = np.asarray([r.total_weight for r in results])
        if w.sum() <= 0:
            return np.full(count, DEFAULT_ID, dtype=np.uint64)
        picks = _WeightedSampler(w).sample(count, rng)
        out = np.full(count, DEFAULT_ID, dtype=np.uint64)
        for s, sh in enumerate(shards):
            sel = picks == s
            if sel.any():
                out[sel] = sh.sample_from_result(
                    results[s], int(sel.sum()), rng
                )
        return out

    def sample_edge_with_condition(
        self, count: int, dnf, edge_type: int = -1, rng=None
    ) -> np.ndarray:
        """Exact-count conditioned edge sampling across shards → [count, 3]."""
        rng = _rng(rng)
        if isinstance(edge_type, str):
            edge_type = self.meta.edge_type_id(edge_type)
        dnf = _fold_type(dnf, edge_type)
        shards = self.shards
        if len(shards) == 1:
            return shards[0].sample_edge_with_condition(count, dnf, -1, rng)
        results = [sh.search_condition(dnf, node=False) for sh in shards]
        w = np.asarray([r.total_weight for r in results])
        if w.sum() <= 0:
            return np.full((count, 3), DEFAULT_ID, dtype=np.uint64)
        picks = _WeightedSampler(w).sample(count, rng)
        out = np.full((count, 3), DEFAULT_ID, dtype=np.uint64)
        for s, sh in enumerate(shards):
            sel = picks == s
            if sel.any():
                out[sel] = sh.sample_edges_from_result(
                    results[s], int(sel.sum()), rng
                )
        return out

    def condition_mask(self, ids, dnf, node: bool = True) -> np.ndarray:
        if not node:
            ids = np.asarray(ids, dtype=np.uint64)
            shards = self.shards
            owner = (ids[:, 0] % np.uint64(len(shards))).astype(np.int64)
            out = np.zeros(len(ids), dtype=bool)
            for s, sh in enumerate(shards):
                sel = owner == s
                if sel.any():
                    out[sel] = sh.condition_mask(
                        ids[sel], dnf, node=False
                    )
            return out
        return self._scatter_gather(
            ids, lambda sh, i: sh.condition_mask(i, dnf)
        )

    def get_node_ids_by_condition(self, dnf) -> np.ndarray:
        parts = [sh.get_node_ids_by_condition(dnf) for sh in self.shards]
        return np.sort(np.concatenate(parts)) if parts else np.empty(0, np.uint64)

    def get_nb_filter(
        self, ids, dnf, edge_types=None, max_degree=None, in_edges=False
    ):
        """Full neighbors with non-matching neighbors masked out
        (API_GET_NB_FILTER parity, euler/core/kernels)."""
        nbr, w, tt, mask, eidx = self.get_full_neighbor(
            ids, edge_types, max_degree, in_edges
        )
        keep = self.condition_mask(nbr.reshape(-1), dnf).reshape(nbr.shape)
        keep &= mask
        return (
            np.where(keep, nbr, DEFAULT_ID),
            np.where(keep, w, 0.0).astype(np.float32),
            np.where(keep, tt, -1),
            keep,
            np.where(keep, eidx, -1),
        )

    def _shard_rngs(self, rng) -> list:
        """One independent child generator per shard, split up-front —
        per-shard dispatch may run concurrently (parallel _scatter_gather)
        and a shared Generator is neither thread-safe nor bias-free when
        two shards race to the same draw."""
        seeds = _rng(rng).integers(0, 2**63 - 1, size=self.num_shards)
        return [np.random.default_rng(int(s)) for s in seeds]

    def sample_neighbor(self, ids, edge_types=None, count=10, rng=None, in_edges=False):
        rngs = self._shard_rngs(rng)
        return self._scatter_gather(
            ids,
            lambda sh, i: sh.sample_neighbor(
                i, edge_types, count, rngs[sh.part], in_edges
            ),
        )

    def get_full_neighbor(
        self, ids, edge_types=None, max_degree=None, in_edges=False, sort_by=None
    ):
        if max_degree is None:
            max_degree = int(self.max_degree(ids, edge_types, in_edges))
        return self._scatter_gather(
            ids,
            lambda sh, i: sh.get_full_neighbor(
                i, edge_types, max_degree, in_edges, sort_by
            ),
        )

    def degree_sum(self, ids, edge_types=None, in_edges=False) -> np.ndarray:
        return self._scatter_gather(
            ids, lambda sh, i: sh.degree_sum(i, edge_types, in_edges)
        )

    def max_degree(self, ids, edge_types=None, in_edges=False) -> int:
        degs = self.degree_sum(ids, edge_types, in_edges)
        return max(int(np.max(degs, initial=0)), 1)

    def get_top_k_neighbor(self, ids, edge_types=None, k=10, in_edges=False):
        return self._scatter_gather(
            ids, lambda sh, i: sh.get_top_k_neighbor(i, edge_types, k, in_edges)
        )

    def sample_fanout(self, ids, edge_types, counts: list[int], rng=None):
        """Multi-hop fanout (sample_fanout_op.cc semantics, padded).

        Returns list of per-hop (ids, weights, types, mask); hop 0 is the
        roots with all-True mask. Hop i has shape [len(ids) * prod(counts[:i])].
        """
        rng = _rng(rng)
        ids = np.asarray(ids, dtype=np.uint64)
        hops = [(ids, np.ones(len(ids), np.float32), self.node_type(ids), np.ones(len(ids), bool))]
        cur = ids
        for c in counts:
            nbr, w, tt, mask, _ = self.sample_neighbor(cur, edge_types, c, rng)
            cur = nbr.reshape(-1)
            hops.append((cur, w.reshape(-1), tt.reshape(-1), mask.reshape(-1)))
        return hops

    def sparse_get_adj(self, ids, edge_types=None, max_degree=None):
        """Induced adjacency among `ids` (sparse_get_adj kernel parity,
        tf_euler kernels sparse_get_adj_op): COO (src_pos, dst_pos, w)
        where positions index into `ids`; edges whose destination is not in
        `ids` are dropped. Duplicate ids map to their first occurrence."""
        ids = np.asarray(ids, dtype=np.uint64)
        nbr, w, _, mask, _ = self.get_full_neighbor(
            ids, edge_types, max_degree=max_degree
        )
        order = np.argsort(ids, kind="stable")
        sorted_ids = ids[order]
        pos = np.searchsorted(sorted_ids, nbr)
        pos = np.clip(pos, 0, len(ids) - 1)
        hit = (sorted_ids[pos] == nbr) & mask
        dst_pos = order[pos]
        src_pos = np.broadcast_to(
            np.arange(len(ids))[:, None], nbr.shape
        )
        return (
            src_pos[hit].astype(np.int64),
            dst_pos[hit].astype(np.int64),
            w[hit].astype(np.float32),
        )

    def get_multi_hop_neighbor(self, nodes, edge_types_per_hop):
        return multi_hop_neighbor(self, nodes, edge_types_per_hop)

    def fanout_with_rows(self, ids, edge_types, counts, rng=None):
        """Fused multi-hop fanout incl. feature-cache rows — the hot path
        for sampled training. Returns (hop_ids, hop_w, hop_tt, hop_mask,
        hop_rows) lists over hops 0..len(counts), or None when unsupported.

        Three routes, mirroring the reference's shard-fanout optimizer
        (optimizer.h:49-86, remote_op.cc:31-36 — keep multi-shard queries
        one round per hop, and remote queries one client round trip):
        - single local shard: one fused native-engine call;
        - remote shards: ONE RPC to a coordinating server, which runs the
          hop rounds next to the data (worker-to-worker scatter);
        - multiple local shards: one owner-scattered sampling round per
          hop, then a single batched row-resolve round over every hop's
          ids, rows globalized with per-shard offsets (shard-major row
          space) — len(counts)+2 scatter rounds total per batch.
        Per-node sampling only reads that node's own out-edges (they live
        wholly on its owner shard), so every route draws from the same
        distribution.
        """
        rng = _rng(rng)
        if self.num_shards == 1 and hasattr(self.shards[0], "fanout_with_rows"):
            return self.shards[0].fanout_with_rows(ids, edge_types, counts, rng)
        if all(hasattr(s, "call") for s in self.shards):
            # remote cluster: the planner SPLITs roots by owner and issues
            # ONE exec_plan RPC per shard — each server runs every hop
            # next to the data, so the batch costs P parallel coordinator
            # RPCs instead of one serialized coordinator or L×P per-op
            # rounds (optimizer.h:49-86 parity). EULER_TPU_FUSED_PLAN=0
            # drives the same sub-plans per-op from here (seed-compatible
            # A/B); "off" keeps the legacy single-coordinator RPC.
            from euler_tpu.query.plan import fanout_plan, plan_mode, run_plan

            mode = plan_mode()
            if mode != "off":
                seed = int(rng.integers(0, 2**63 - 1))
                try:
                    res = run_plan(
                        self, fanout_plan(edge_types, counts),
                        np.asarray(ids, np.uint64), seed,
                        fused=mode == "fused",
                    )
                    return res["__hops"]
                except RuntimeError as e:
                    # capability gap only (old server missing both
                    # exec_plan and the per-op lookup surface): drop to
                    # the legacy coordinator RPC below
                    msg = str(e)
                    if "unknown op" not in msg and "num_nodes" not in msg:
                        raise
            # legacy: forward the whole query to one shard server
            # (spread coordinator load across shards)
            shards = self.shards
            pick = int(rng.integers(len(shards)))
            try:
                return shards[pick].fanout_with_rows(
                    ids, edge_types, counts, rng
                )
            except RuntimeError as e:
                if "unknown op" in str(e):
                    # older server without the sample_fanout op — keep the
                    # documented None-when-unsupported contract so callers
                    # fall back to the per-hop path
                    return None
                raise  # genuine server/network failure: surface it
        try:
            self._shard_row_offsets()  # capability check: rows resolvable?
        except RuntimeError:
            return None
        ids = np.asarray(ids, dtype=np.uint64)
        hop_ids = [ids]
        hop_w = [np.ones(len(ids), np.float32)]
        hop_tt = [np.asarray(self.node_type(ids), np.int32)]
        hop_mask = [ids != DEFAULT_ID]
        cur = ids
        for c in counts:
            nbr, w, tt, mask, _ = self.sample_neighbor(
                cur, edge_types, int(c), rng=rng
            )
            cur = nbr.reshape(-1)
            hop_ids.append(cur)
            hop_w.append(w.reshape(-1).astype(np.float32))
            hop_tt.append(tt.reshape(-1).astype(np.int32))
            hop_mask.append(mask.reshape(-1))
        # one batched row-resolve round for ALL hops (each hop's rows live
        # on the id's owner shard, not the sampling shard, so they can't
        # ride the sampling round — but they can share one scatter)
        all_rows = np.asarray(
            self.lookup_rows(np.concatenate(hop_ids)), np.int64
        )
        offs = np.r_[0, np.cumsum([len(h) for h in hop_ids])]
        hop_rows = [
            all_rows[offs[i] : offs[i + 1]] for i in range(len(hop_ids))
        ]
        return hop_ids, hop_w, hop_tt, hop_mask, hop_rows

    def unit_edge_weights(self, edge_types=None) -> bool:
        return all(
            hasattr(s, "unit_edge_weights") and s.unit_edge_weights(edge_types)
            for s in self.shards
        )

    def fanout_rows_lean(self, ids, edge_types, counts, rng=None):
        """Multi-shard fused fanout shipping ONLY ids+mask+rows per hop —
        the distributed lean hot path. Per hop, the owner-scattered leaf
        draw returns each pick's row when the dst happens to live on the
        sampling shard (the engine's dst_row cache makes that free); one
        final batched lookup round resolves the rest, roots included.
        Returns (hop_ids, hop_mask, hop_rows[global]) or None when a
        shard lacks the lean leaf surface.
        """
        if not all(hasattr(s, "sample_neighbor_rows") for s in self.shards):
            return None
        try:
            return self._fanout_rows_lean(ids, edge_types, counts, rng)
        except RuntimeError as e:
            if "unknown op" in str(e):
                # remote shards always expose the client method; a server
                # predating the lean leaf ops surfaces here instead
                return None
            raise

    def _fanout_rows_lean(self, ids, edge_types, counts, rng=None):
        rng = _rng(rng)
        offsets = self._shard_row_offsets()
        ids = np.asarray(ids, dtype=np.uint64)
        hop_ids = [ids]
        hop_mask = [ids != DEFAULT_ID]
        hop_rows = [np.full(len(ids), -1, dtype=np.int64)]
        cur = ids
        for c in counts:
            rngs = self._shard_rngs(rng)

            def fn(shard, sub, c=int(c), rngs=rngs):
                nbr, mask, rows = shard.sample_neighbor_rows(
                    sub, edge_types, c, rngs[shard.part]
                )
                rows = np.asarray(rows, np.int64)
                rows = np.where(rows >= 0, rows + offsets[shard.part], -1)
                return nbr, mask.astype(bool), rows
            nbr, mask, rows = self._scatter_gather(cur, fn)
            cur = nbr.reshape(-1)
            hop_ids.append(cur)
            hop_mask.append(mask.reshape(-1))
            hop_rows.append(rows.reshape(-1))
        # one batched resolve for every still-unknown row (roots + the
        # picks whose dst lives off its sampling shard)
        all_rows = np.concatenate(hop_rows)
        all_mask = np.concatenate(hop_mask)
        need = (all_rows < 0) & all_mask
        if need.any():
            all_ids = np.concatenate(hop_ids)
            all_rows[need] = self.lookup_rows(all_ids[need])
        offs = np.r_[0, np.cumsum([len(h) for h in hop_ids])]
        hop_rows = [
            all_rows[offs[i] : offs[i + 1]] for i in range(len(hop_ids))
        ]
        return hop_ids, hop_mask, hop_rows

    def sage_minibatch(
        self,
        batch_size,
        edge_types,
        counts,
        label=None,
        node_type=-1,
        rng=None,
        lean=True,
    ):
        """One-RPC training minibatch on a remote cluster (root sampling +
        fused fanout + labels, coordinated server-side next to the data).
        Returns None on in-process graphs — callers fall back to
        sample_node + fanout_with_rows, which is already zero-copy there.
        """
        if not all(hasattr(s, "call") for s in self.shards):
            return None
        rng = _rng(rng)
        pick = int(rng.integers(self.num_shards))
        try:
            return self.shards[pick].sage_minibatch(
                batch_size, edge_types, counts, label, node_type, rng, lean
            )
        except RuntimeError as e:
            if "unknown op" in str(e):
                # older server without the fused op: honor the documented
                # None-when-unsupported contract (same compat stance as
                # fanout_with_rows above) so callers fall back to
                # sample_node + per-op queries
                return None
            raise

    def sage_minibatch_async(
        self,
        batch_size,
        edge_types,
        counts,
        label=None,
        node_type=-1,
        rng=None,
        lean=True,
    ):
        """Pipelined sage_minibatch: a Future of the result dict, with up
        to EULER_TPU_INFLIGHT requests overlapped per shard (the
        reference's async completion-queue client, query_proxy.cc:235-256).
        None on in-process graphs or servers without the async surface —
        callers fall back to the sync path."""
        if not all(hasattr(s, "sage_minibatch_async") for s in self.shards):
            return None
        rng = _rng(rng)
        pick = int(rng.integers(self.num_shards))
        return self.shards[pick].sage_minibatch_async(
            batch_size, edge_types, counts, label, node_type, rng, lean
        )

    def get_dense_by_rows(self, rows, names) -> np.ndarray:
        """Dense features by pre-resolved global rows (-1 → zeros).

        Rows are shard-major (lookup_rows space); multi-shard splits them
        back to per-shard local rows, so the fused-fanout dense path works
        on partitioned graphs too.
        """
        rows = np.asarray(rows, dtype=np.int64)
        if self.num_shards == 1:
            return self.shards[0].get_dense_by_rows(rows, names)
        offsets = self._shard_row_offsets()
        owner = np.searchsorted(offsets, rows, side="right") - 1  # -1 → -1
        dims = sum(
            self.meta.feature_spec(nm, node=True).dim for nm in names
        )
        out = np.zeros((len(rows), dims), np.float32)
        for s, sh in enumerate(self.shards):
            sel = np.nonzero(owner == s)[0]
            if not len(sel):
                continue
            local = rows[sel] - offsets[s]
            out[sel] = sh.get_dense_by_rows(local, names)
        return out

    def sample_neighbor_layerwise(self, batch_ids, edge_types=None, count=128, rng=None):
        """Exact on any shard count: scatter-gather the batch's full
        neighbor arrays (each node's out-adjacency lives whole on its
        owner shard), then run the ONE candidate selection over the
        merged result — a candidate cited by batch nodes on different
        shards is weighted by its true global incident sum. (The earlier
        per-shard sample + truncating union kept shard 0's candidates
        preferentially and split candidate weights.)"""
        rng = _rng(rng)
        if self.num_shards == 1:
            return self.shards[0].sample_neighbor_layerwise(
                batch_ids, edge_types, count, rng
            )
        batch_ids = np.asarray(batch_ids, dtype=np.uint64)
        nbr, w, _, mask, _ = self.get_full_neighbor(batch_ids, edge_types)
        return layerwise_from_full(nbr, w, mask, count, rng)

    def get_dense_feature(self, ids, names) -> np.ndarray:
        return self._scatter_gather(ids, lambda sh, i: sh.get_dense_feature(i, names))

    def get_dense_feature_udf(self, ids, names, udfs):
        """Rows are aggregated independently (axis=1), so each owner shard
        runs the UDF on its own rows and only the aggregates are gathered
        — for remote shards this is the server-side UDF pushdown."""
        from euler_tpu.query.gql import dense_feature_udf

        # every shard reports identical widths (differing column counts
        # would already fail _scatter_gather's template-shaped scatter);
        # capture any one result's
        widths_box: list = []

        def fn(sh, i):
            pushdown = getattr(sh, "get_dense_feature_udf", None)
            out, w = (
                pushdown(i, names, udfs)
                if pushdown is not None
                else dense_feature_udf(sh, i, names, udfs)
            )
            if not widths_box:
                widths_box.append(np.asarray(w, np.int64))
            return out

        gathered = self._scatter_gather(ids, fn)
        return gathered, widths_box[0]

    def _shard_row_offsets(self) -> np.ndarray:
        if not all(hasattr(s, "num_nodes") for s in self.shards):
            raise RuntimeError(
                "feature-cache row lookup needs shards exposing num_nodes "
                "(local stores, or remote shards served by a version with "
                "the num_nodes op)"
            )
        return np.cumsum([0] + [s.num_nodes for s in self.shards])

    def lookup_rows(self, ids) -> np.ndarray:
        """u64 ids → global dense rows (shard-major order); -1 for missing.

        The row space enumerates every node across shards (shard 0's rows
        first), letting device-resident feature tables replace per-batch
        dense-feature transfers: ship int32 rows, gather [rows] on device.
        """
        offsets = self._shard_row_offsets()

        def fn(shard, sub):
            r = shard.lookup(sub)
            return np.where(r >= 0, r + offsets[shard.part], -1)

        return np.asarray(self._scatter_gather(ids, fn), dtype=np.int64)

    def dense_feature_table(self, names) -> np.ndarray:
        """f32 [total_nodes, F] dense features for all nodes, shard-major —
        the host-side source for a device feature cache (rows from
        lookup_rows index into it)."""
        dims = max(
            1,
            sum(self.meta.feature_spec(nm, node=True).dim for nm in names),
        )
        # bound each fetch well under the wire frame cap so remote shards
        # with big tables stream in chunks instead of one giant frame
        chunk = max(1, (64 << 20) // (4 * dims))
        parts = []
        for sh in self.shards:
            for lo in range(0, max(sh.num_nodes, 1), chunk):
                rows = np.arange(
                    lo, min(lo + chunk, sh.num_nodes), dtype=np.int64
                )
                if not len(rows):
                    continue
                parts.append(sh.get_dense_by_rows(rows, names))
        return (
            np.concatenate(parts, axis=0)
            if parts
            else np.zeros((0, 0), np.float32)
        )

    def get_sparse_feature(self, ids, names, max_len=None):
        if max_len is None:
            max_len = max(
                self.meta.feature_spec(nm, node=True).dim for nm in names
            )
        results = self._scatter_gather(
            ids,
            lambda sh, i: tuple(
                x
                for pair in sh.get_sparse_feature(i, names, max_len)
                for x in pair
            ),
        )
        if not isinstance(results, tuple):
            results = (results,)
        return [(results[2 * i], results[2 * i + 1]) for i in range(len(names))]

    def get_binary_feature(self, ids, names):
        ids = np.asarray(ids, dtype=np.uint64)
        out = [[b""] * len(ids) for _ in names]
        owner = self._owner(ids)
        for s in range(self.num_shards):
            sel = np.nonzero(owner == s)[0]
            if not len(sel):
                continue
            res = self.shards[s].get_binary_feature(ids[sel], names)
            for fi, vals in enumerate(res):
                for j, v in zip(sel, vals):
                    out[fi][j] = v
        return out

    def get_edge_dense_feature(self, edge_ids, names) -> np.ndarray:
        edge_ids = np.asarray(edge_ids, dtype=np.uint64)
        owner = (edge_ids[:, 0] % np.uint64(self.num_shards)).astype(np.int64)
        n = len(edge_ids)
        dim = sum(self.meta.feature_spec(nm, node=False).dim for nm in names)
        out = np.zeros((n, dim), dtype=np.float32)
        for s in range(self.num_shards):
            sel = np.nonzero(owner == s)[0]
            if len(sel):
                out[sel] = self.shards[s].get_edge_dense_feature(edge_ids[sel], names)
        return out

    def get_edge_sparse_feature(self, edge_ids, names, max_len=None):
        """Per-name (values, mask) pairs for edge sparse features, routed
        to each edge's owner (src % P) shard — the edge twin of the node
        get_sparse_feature facade (feature_ops.py:152-168 parity)."""
        edge_ids = np.asarray(edge_ids, dtype=np.uint64)
        if max_len is None:
            max_len = max(
                self.meta.feature_spec(nm, node=False).dim for nm in names
            )
        owner = (edge_ids[:, 0] % np.uint64(self.num_shards)).astype(np.int64)
        n = len(edge_ids)
        outs = None
        for s in range(self.num_shards):
            sel = np.nonzero(owner == s)[0]
            if not len(sel):
                continue
            pairs = self.shards[s].get_edge_sparse_feature(
                edge_ids[sel], names, max_len
            )
            if outs is None:
                outs = [
                    (
                        np.zeros((n, max_len), pairs[0][0].dtype),
                        np.zeros((n, max_len), bool),
                    )
                    for _ in names
                ]
            for fi, (vals, mask) in enumerate(pairs):
                outs[fi][0][sel] = vals
                outs[fi][1][sel] = mask
        if outs is None:
            outs = [
                (np.zeros((n, max_len), np.int64), np.zeros((n, max_len), bool))
                for _ in names
            ]
        return outs

    def get_edge_binary_feature(self, edge_ids, names):
        edge_ids = np.asarray(edge_ids, dtype=np.uint64)
        owner = (edge_ids[:, 0] % np.uint64(self.num_shards)).astype(np.int64)
        n = len(edge_ids)
        out = [[b""] * n for _ in names]
        for s in range(self.num_shards):
            sel = np.nonzero(owner == s)[0]
            if not len(sel):
                continue
            res = self.shards[s].get_edge_binary_feature(edge_ids[sel], names)
            for fi, vals in enumerate(res):
                for j, v in zip(sel, vals):
                    out[fi][j] = v
        return out

    def sample_graph_label(self, count: int, rng=None) -> np.ndarray:
        """Uniform sample over graph labels; returns label indices i64."""
        rng = _rng(rng)
        n = len(self.meta.graph_labels)
        return rng.integers(0, max(n, 1), size=count)

    def get_graph_by_label(self, label_ids) -> list[np.ndarray]:
        per_shard = [sh.get_graph_by_label(label_ids) for sh in self.shards]
        return [
            np.sort(np.concatenate([ps[i] for ps in per_shard]))
            for i in range(len(np.asarray(label_ids)))
        ]

    def random_walk(self, ids, edge_types=None, walk_len=3, p=1.0, q=1.0, rng=None):
        rng = _rng(rng)
        if self.num_shards == 1:
            return self.shards[0].random_walk(ids, edge_types, walk_len, p, q, rng)
        ids = np.asarray(ids, dtype=np.uint64)
        n = len(ids)
        walks = np.full((n, walk_len + 1), DEFAULT_ID, dtype=np.uint64)
        walks[:, 0] = ids
        cur = ids.copy()
        prev = np.full(n, DEFAULT_ID, dtype=np.uint64)
        for step in range(1, walk_len + 1):
            if p == 1.0 and q == 1.0:
                nbr, _, _, mask, _ = self.sample_neighbor(cur, edge_types, 1, rng)
                nxt = np.where(mask[:, 0], nbr[:, 0], DEFAULT_ID)
            else:
                # cross-shard node2vec: step owned by cur's shard; prev id
                # travels along so the 1/p return bias is exact, while the
                # distance-1 bias degrades to 1/q when prev is off-shard.
                rngs = self._shard_rngs(rng)  # dispatch may be concurrent
                nxt = self._scatter_gather(
                    cur,
                    lambda sh, i, pv: sh._node2vec_step(
                        i, pv, edge_types, p, q, rngs[sh.part]
                    ),
                    extras=(prev,),
                )
            nxt = np.asarray(nxt, dtype=np.uint64)
            nxt[cur == DEFAULT_ID] = DEFAULT_ID
            walks[:, step] = nxt
            prev, cur = cur, nxt
        return walks
