"""Streaming graph mutation: per-shard delta buffers + epoch publish.

Euler 2.0's `GraphBuilder` supports a graph that is built and REBUILT
while trainers read it; this module is that write path for the TPU
build's columnar shards. The shape is write-ahead + epoch publish:

- `DeltaStore` is a per-shard append-only buffer of typed mutation
  batches (`upsert_nodes` / `upsert_edges` / `delete_nodes` /
  `delete_edges`), mirroring the builder's partition-array schema
  (graph/builder.py). Staged writes are INVISIBLE to readers — the base
  `GraphStore` arrays are never touched, so every read keeps serving the
  epoch-consistent base snapshot while a writer streams batches in.
- `merge_arrays` folds a DeltaStore into a shard's arrays at an epoch
  boundary, rebuilding only the TOUCHED structures: patched feature /
  node rows, spliced CSR rows of mutated sources, remapped edge ids.
  Untouched arrays are carried by reference (copy-on-first-write), so a
  small delta costs O(touched + per-type indptr), not a partition
  rebuild. The output is BIT-IDENTICAL to building the mutated graph
  from scratch (builder.py on the post-mutation JSON) — the property the
  tier-1 parity tests pin, and what keeps every execution lane (host,
  fused, cached, device dense, device paged) consistent per epoch.
- `GraphStore.merge_delta` (store.py) wraps the merge in the publish
  discipline: new arrays become a NEW store object with `graph_epoch`
  bumped, so serving processes swap one reference and in-flight reads
  finish on the old immutable snapshot — no torn reads by construction
  (the same immutable-engine swap the serving hot reload uses).

Mutation semantics (the from-scratch reference is "apply the same edit
to graph.json, rebuild"):

- upsert_nodes: existing id → type/weight replaced, provided dense
  features replaced (others kept); new id → inserted in sorted order
  with zero features for anything not provided. Sparse/binary feature
  mutation is not supported (raise) — their schemas are build-time.
- upsert_edges: existing (src, dst, type) → weight replaced in place
  (flat row and CSR slots keep their positions); new key → appended to
  the flat edge arrays and spliced onto the END of its source row's CSR
  segment, exactly where a from-scratch build of "record appended to
  the JSON" puts it. Edge features of streamed edges are empty.
- delete_edges: the flat edge row is removed (every CSR `eidx` is
  remapped) and the adjacency/in-adjacency slots drop.
- delete_nodes: the node row is removed (features and CSR rows go with
  it); edge RECORDS referencing it survive in the flat arrays but drop
  out of the adjacency, which is precisely what the builder emits for a
  JSON with the node record gone.

Bounds: a DeltaStore refuses rows past `EULER_TPU_DELTA_MAX_ROWS`
(default 2_000_000) with a typed `OverloadError` — the wire maps it to
the standard admission-control verdict, which clients never retry.
"""

from __future__ import annotations

import dataclasses
import os
import threading

import numpy as np

from euler_tpu.distributed.errors import OverloadError
from euler_tpu.graph.meta import DENSE, GraphMeta


def delta_max_rows() -> int:
    return int(os.environ.get("EULER_TPU_DELTA_MAX_ROWS", 2_000_000))


def _u64(x) -> np.ndarray:
    return np.asarray(x, dtype=np.uint64).reshape(-1)


def _i32(x) -> np.ndarray:
    return np.asarray(x, dtype=np.int32).reshape(-1)


def _f32(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float32).reshape(-1)


@dataclasses.dataclass
class _NodeBatch:
    ids: np.ndarray  # u64
    types: np.ndarray  # i32
    weights: np.ndarray  # f32
    names: list  # dense feature names carried by this batch
    dense: np.ndarray | None  # f32 [n, sum(dims of names)]


@dataclasses.dataclass
class _EdgeBatch:
    # out-edges (this shard owns src) and in-edges (this shard owns dst)
    osrc: np.ndarray
    odst: np.ndarray
    ott: np.ndarray
    ow: np.ndarray
    isrc: np.ndarray
    idst: np.ndarray
    itt: np.ndarray
    iw: np.ndarray


@dataclasses.dataclass
class _EdgeDeleteBatch:
    osrc: np.ndarray
    odst: np.ndarray
    ott: np.ndarray
    isrc: np.ndarray
    idst: np.ndarray
    itt: np.ndarray


@dataclasses.dataclass
class _NodeDeleteBatch:
    ids: np.ndarray


class DeltaStore:
    """Per-shard append-only mutation buffer (pre-routed to this shard).

    Thread-safe: every buffer append happens under `self._lock` (server
    worker threads stage concurrently), and the byte/row bound is
    enforced there too — overflow raises a typed `OverloadError` BEFORE
    buffering, so a rejected batch leaves no partial state behind.
    Readers never see staged content: the overlay is append-only and
    only `merge_arrays` (at publish) folds it into a NEW array set.
    """

    def __init__(self, part: int, num_partitions: int, max_rows: int | None = None):
        self.part = int(part)
        self.num_partitions = int(num_partitions)
        self.max_rows = int(max_rows) if max_rows is not None else delta_max_rows()
        self._lock = threading.Lock()
        self._nodes: list[_NodeBatch] = []
        self._edges: list[_EdgeBatch] = []
        self._edge_dels: list[_EdgeDeleteBatch] = []
        self._node_dels: list[_NodeDeleteBatch] = []
        self._rows = 0

    # -- staging ---------------------------------------------------------

    def _admit(self, n: int) -> None:
        # caller holds self._lock (every stage_* method takes it before
        # calling here — the write below is never lock-free)
        if self._rows + n > self.max_rows:
            raise OverloadError(
                f"delta buffer full on shard {self.part} "
                f"({self._rows} staged + {n} > EULER_TPU_DELTA_MAX_ROWS="
                f"{self.max_rows}); publish the pending epoch first"
            )
        self._rows += n  # graftlint: disable=lock-mixed-write -- every stage_* caller holds self._lock around this call

    def stage_nodes(self, ids, types, weights, names=(), dense=None) -> int:
        ids = _u64(ids)
        types = _i32(types)
        weights = _f32(weights)
        names = list(names or ())
        if not (len(ids) == len(types) == len(weights)):
            raise ValueError("upsert_nodes: ids/types/weights length mismatch")
        if names:
            dense = np.asarray(dense, np.float32).reshape(len(ids), -1)
        else:
            dense = None
        with self._lock:
            self._admit(len(ids))
            self._nodes.append(_NodeBatch(ids, types, weights, names, dense))
        return len(ids)

    def stage_edges(self, osrc, odst, ott, ow, isrc, idst, itt, iw) -> int:
        b = _EdgeBatch(
            _u64(osrc), _u64(odst), _i32(ott), _f32(ow),
            _u64(isrc), _u64(idst), _i32(itt), _f32(iw),
        )
        if not (len(b.osrc) == len(b.odst) == len(b.ott) == len(b.ow)):
            raise ValueError("upsert_edges: out column length mismatch")
        if not (len(b.isrc) == len(b.idst) == len(b.itt) == len(b.iw)):
            raise ValueError("upsert_edges: in column length mismatch")
        n = len(b.osrc) + len(b.isrc)
        with self._lock:
            self._admit(n)
            self._edges.append(b)
        return n

    def stage_edge_deletes(self, osrc, odst, ott, isrc, idst, itt) -> int:
        b = _EdgeDeleteBatch(
            _u64(osrc), _u64(odst), _i32(ott),
            _u64(isrc), _u64(idst), _i32(itt),
        )
        n = len(b.osrc) + len(b.isrc)
        with self._lock:
            self._admit(n)
            self._edge_dels.append(b)
        return n

    def stage_node_deletes(self, ids) -> int:
        ids = _u64(ids)
        with self._lock:
            self._admit(len(ids))
            self._node_dels.append(_NodeDeleteBatch(ids))
        return len(ids)

    # -- introspection (the read-overlay view) ---------------------------

    @property
    def empty(self) -> bool:
        with self._lock:
            return self._rows == 0

    def pending(self) -> dict:
        """Staged-row counts by kind — the diagnostic overlay view
        (readers of the STORE never see these rows; they exist only
        here until publish)."""
        with self._lock:
            return {
                "rows": self._rows,
                "node_upserts": sum(len(b.ids) for b in self._nodes),
                "edge_upserts": sum(
                    len(b.osrc) + len(b.isrc) for b in self._edges
                ),
                "edge_deletes": sum(
                    len(b.osrc) + len(b.isrc) for b in self._edge_dels
                ),
                "node_deletes": sum(len(b.ids) for b in self._node_dels),
                "max_rows": self.max_rows,
            }

    def snapshot(self) -> "DeltaStore":
        """Detach the staged batches for merging: returns a frozen copy
        holding the current buffers and resets this store to empty, all
        under the lock — a concurrent stage lands either wholly before
        the publish (merged now) or wholly after (next epoch)."""
        with self._lock:
            out = DeltaStore(self.part, self.num_partitions, self.max_rows)
            out._nodes = self._nodes
            out._edges = self._edges
            out._edge_dels = self._edge_dels
            out._node_dels = self._node_dels
            out._rows = self._rows
            self._nodes = []
            self._edges = []
            self._edge_dels = []
            self._node_dels = []
            self._rows = 0
        return out


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------


def _segment_arange(counts: np.ndarray) -> np.ndarray:
    counts = np.asarray(counts, np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, np.int64)
    ends = np.cumsum(counts)
    return np.arange(total) - np.repeat(ends - counts, counts)


def _dedupe_triples(src, dst, tt, vals=None):
    """Unique (src, dst, type) keys keeping FIRST position and LAST
    value — the stream semantics of re-upserting the same edge: its
    JSON record is appended once (first occurrence) and then updated in
    place. Returns (src, dst, tt[, vals]) ordered by first occurrence."""
    if len(src) == 0:
        return (src, dst, tt) + ((vals,) if vals is not None else ())
    trip = np.stack([src, dst, tt.astype(np.uint64)], axis=1)
    _, first_idx = np.unique(trip, axis=0, return_index=True)
    order = np.sort(first_idx)
    if vals is None:
        return src[order], dst[order], tt[order]
    _, last_rev = np.unique(trip[::-1], axis=0, return_index=True)
    last_idx = len(trip) - 1 - last_rev
    # np.unique sorts rows the same way for both passes, so last_idx[k]
    # is the last occurrence of the SAME key first_idx[k] found
    by_first = np.argsort(first_idx, kind="stable")
    return (
        src[first_idx[by_first]],
        dst[first_idx[by_first]],
        tt[first_idx[by_first]],
        vals[last_idx[by_first]],
    )


class _Merge:
    """One merge pass: delta folded into a copy-on-write array dict."""

    def __init__(self, meta: GraphMeta, arrays: dict, part: int):
        self.meta = meta
        self.part = part
        self.A = {k: np.asarray(v) for k, v in arrays.items()}
        self._written: set[str] = set()
        self.mutated: list[np.ndarray] = []  # local rows, NEW space
        self.touched_ids: list[np.ndarray] = []
        self.shift_start: int | None = None  # first row whose identity shifted

    def W(self, key: str) -> np.ndarray:
        """Copy-on-first-write: the base store's arrays are live read
        snapshots and must never be mutated in place."""
        if key not in self._written:
            self.A[key] = np.array(self.A[key], copy=True)
            self._written.add(key)
        return self.A[key]

    def put(self, key: str, arr: np.ndarray) -> None:
        self.A[key] = arr
        self._written.add(key)

    def _tmp_store(self):
        from euler_tpu.graph.store import GraphStore

        return GraphStore(self.meta, self.A, self.part)

    def _note_shift(self, row: int) -> None:
        self.shift_start = (
            int(row)
            if self.shift_start is None
            else min(self.shift_start, int(row))
        )

    # -- phase 1: node upserts -------------------------------------------

    def node_upserts(self, batches: list[_NodeBatch]) -> None:
        if not batches:
            return
        all_ids = np.unique(np.concatenate([b.ids for b in batches]))
        for b in batches:
            bad = (b.types < 0) | (b.types >= self.meta.num_node_types)
            if bad.any():
                raise ValueError(
                    f"upsert_nodes: type out of range (num_node_types="
                    f"{self.meta.num_node_types}) — type schemas are "
                    "build-time, stream within them"
                )
        node_ids = self.A["node_ids"]
        if len(node_ids):
            pos = np.minimum(
                np.searchsorted(node_ids, all_ids), len(node_ids) - 1
            )
            exists = node_ids[pos] == all_ids
        else:
            exists = np.zeros(len(all_ids), bool)
        new_ids = all_ids[~exists]
        if len(new_ids):
            ins = np.searchsorted(node_ids, new_ids)
            self.put("node_ids", np.insert(node_ids, ins, new_ids))
            self.put(
                "node_types",
                np.insert(self.A["node_types"], ins, 0).astype(np.int32),
            )
            self.put(
                "node_weights",
                np.insert(self.A["node_weights"], ins, 0.0).astype(
                    np.float32
                ),
            )
            for t in range(self.meta.num_edge_types):
                for tag in ("adj", "inadj"):
                    k = f"{tag}_{t}_indptr"
                    if k in self.A:
                        ip = self.A[k]
                        self.put(k, np.insert(ip, ins, ip[ins]))
            for spec in self.meta.node_features.values():
                if spec.kind == DENSE:
                    k = f"nf_dense_{spec.fid}"
                    self.put(k, np.insert(self.A[k], ins, 0.0, axis=0))
                else:
                    prefix = "sparse" if spec.kind == "sparse" else "bin"
                    k = f"nf_{prefix}_{spec.fid}_indptr"
                    ip = self.A[k]
                    self.put(k, np.insert(ip, ins, ip[ins]))
            self._note_shift(int(ins[0]))
        # replay batches in order (later batches win) as pure row patches
        tmp = self._tmp_store()
        for b in batches:
            rows = tmp.lookup(b.ids)
            if (rows < 0).any():  # cannot happen after the insert above
                raise RuntimeError("node upsert rows unresolved post-insert")
            self.W("node_types")[rows] = b.types
            self.W("node_weights")[rows] = b.weights
            off = 0
            for nm in b.names:
                spec = self.meta.feature_spec(nm, node=True)
                if spec.kind != DENSE:
                    raise ValueError(
                        f"upsert_nodes: feature {nm!r} is {spec.kind}; only "
                        "dense features are mutable over the stream"
                    )
                if b.dense is None or b.dense.shape[1] < off + spec.dim:
                    raise ValueError(
                        "upsert_nodes: dense block narrower than the "
                        "declared names"
                    )
                self.W(f"nf_dense_{spec.fid}")[rows] = b.dense[
                    :, off : off + spec.dim
                ]
                off += spec.dim
            self.mutated.append(np.asarray(rows, np.int64))
            self.touched_ids.append(b.ids)

    # -- phase 2: edge upserts -------------------------------------------

    def edge_upserts(self, batches: list[_EdgeBatch]) -> dict:
        """Returns {(src, dst, type): flat eidx} for edges appended here
        (the in-adjacency phase needs it for locally-owned edges)."""
        appended: dict = {}
        if not batches:
            return appended
        osrc = np.concatenate([b.osrc for b in batches])
        odst = np.concatenate([b.odst for b in batches])
        ott = np.concatenate([b.ott for b in batches])
        ow = np.concatenate([b.ow for b in batches])
        bad = (ott < 0) | (ott >= self.meta.num_edge_types)
        if len(ott) and bad.any():
            raise ValueError(
                f"upsert_edges: edge type out of range (num_edge_types="
                f"{self.meta.num_edge_types})"
            )
        if len(osrc):
            appended = self._edge_upserts_out(osrc, odst, ott, ow)
        isrc = np.concatenate([b.isrc for b in batches])
        idst = np.concatenate([b.idst for b in batches])
        itt = np.concatenate([b.itt for b in batches])
        iw = np.concatenate([b.iw for b in batches])
        if len(isrc):
            self._edge_upserts_in(isrc, idst, itt, iw, appended)
        return appended

    def _edge_upserts_out(self, src, dst, tt, w) -> dict:
        src, dst, tt, w = _dedupe_triples(src, dst, tt, w)
        tmp = self._tmp_store()
        trip = np.stack([src, dst, tt.astype(np.uint64)], axis=1)
        eidx = tmp._edge_rows(trip)
        exist = eidx >= 0
        # (a) weight replacement in place
        if exist.any():
            upd = eidx[exist]
            ew = self.W("edge_weights")
            ew[upd] = w[exist]
            for t in np.unique(tt[exist]):
                self._patch_csr_weights("adj", int(t), upd, ew)
                self._patch_csr_weights("inadj", int(t), upd, ew)
            rows = tmp.lookup(src[exist])
            self.mutated.append(rows[rows >= 0].astype(np.int64))
            self.touched_ids.append(src[exist])
            self.touched_ids.append(dst[exist])
        # (b) append the rest
        ns, nd, nt, nw = src[~exist], dst[~exist], tt[~exist], w[~exist]
        if not len(ns):
            return {}
        base_e = len(self.A["edge_src"])
        self.put("edge_src", np.concatenate([self.A["edge_src"], ns]))
        self.put("edge_dst", np.concatenate([self.A["edge_dst"], nd]))
        self.put(
            "edge_types",
            np.concatenate([self.A["edge_types"], nt]).astype(np.int32),
        )
        self.put(
            "edge_weights",
            np.concatenate([self.A["edge_weights"], nw]).astype(np.float32),
        )
        for spec in self.meta.edge_features.values():
            if spec.kind == DENSE:
                k = f"ef_dense_{spec.fid}"
                pad = np.zeros((len(ns), self.A[k].shape[1]), np.float32)
                self.put(k, np.concatenate([self.A[k], pad], axis=0))
            else:
                prefix = "sparse" if spec.kind == "sparse" else "bin"
                k = f"ef_{prefix}_{spec.fid}_indptr"
                ip = self.A[k]
                self.put(
                    k,
                    np.concatenate(
                        [ip, np.full(len(ns), ip[-1], dtype=ip.dtype)]
                    ),
                )
        new_eidx = base_e + np.arange(len(ns), dtype=np.int64)
        appended = {
            (int(s), int(d), int(t)): int(e)
            for s, d, t, e in zip(ns, nd, nt, new_eidx)
        }
        rows = tmp.lookup(ns)
        keep = rows >= 0  # non-resident src: flat arrays only (builder parity)
        for t in np.unique(nt[keep]) if keep.any() else ():
            sel = keep & (nt == t)
            self._splice_csr(
                "adj", int(t), rows[sel], nd[sel], nw[sel], new_eidx[sel]
            )
        self.mutated.append(rows[keep].astype(np.int64))
        self.touched_ids.append(ns)
        self.touched_ids.append(nd)
        return appended

    def _edge_upserts_in(self, src, dst, tt, w, appended: dict) -> None:
        src, dst, tt, w = _dedupe_triples(src, dst, tt, w)
        tmp = self._tmp_store()
        rows = tmp.lookup(dst)
        keep = rows >= 0
        add_rows, add_src, add_w, add_eidx, add_tt = [], [], [], [], []
        for s, d, t, wt, r, ok in zip(src, dst, tt, w, rows, keep):
            if not ok:
                continue
            t = int(t)
            k = f"inadj_{t}_indptr"
            if k not in self.A:
                continue
            ip = self.A[k]
            lo, hi = int(ip[r]), int(ip[r + 1])
            seg = self.A[f"inadj_{t}_dst"][lo:hi]
            hit = np.nonzero(seg == s)[0]
            if len(hit):
                self.W(f"inadj_{t}_w")[lo + int(hit[0])] = wt
            else:
                add_rows.append(int(r))
                add_src.append(int(s))
                add_w.append(float(wt))
                add_tt.append(t)
                # locally-owned edge rows carry their flat eidx; edges
                # whose src lives on a peer shard stay -1 (builder parity)
                add_eidx.append(appended.get((int(s), int(d), t), -1))
        for t in sorted(set(add_tt)):
            sel = [i for i, x in enumerate(add_tt) if x == t]
            self._splice_csr(
                "inadj",
                t,
                np.asarray([add_rows[i] for i in sel], np.int64),
                np.asarray([add_src[i] for i in sel], np.uint64),
                np.asarray([add_w[i] for i in sel], np.float32),
                np.asarray([add_eidx[i] for i in sel], np.int64),
            )
        self.mutated.append(rows[keep].astype(np.int64))
        self.touched_ids.append(dst)
        self.touched_ids.append(src)

    def _patch_csr_weights(self, tag: str, t: int, upd_eidx, ew) -> None:
        k = f"{tag}_{t}_eidx"
        if k not in self.A:
            return
        ce = self.A[k]
        sel = (ce >= 0) & np.isin(ce, upd_eidx)
        if sel.any():
            self.W(f"{tag}_{t}_w")[sel] = ew[ce[sel]].astype(np.float32)

    def _splice_csr(self, tag, t, rows, other, w, eidx) -> None:
        """Append entries at the END of each row's segment (where the
        builder's stable (type, row) lexsort puts late JSON records)."""
        if not len(rows):
            return
        order = np.argsort(rows, kind="stable")
        rows, other, w, eidx = rows[order], other[order], w[order], eidx[order]
        ip = self.A[f"{tag}_{t}_indptr"]
        n = len(ip) - 1
        add_cnt = np.bincount(rows, minlength=n)
        excl = np.concatenate([[0], np.cumsum(add_cnt)])
        old_cnt = np.diff(ip)
        new_ip = ip + excl
        old_dst = self.A[f"{tag}_{t}_dst"]
        old_w = self.A[f"{tag}_{t}_w"]
        old_e = self.A[f"{tag}_{t}_eidx"]
        nnz = len(old_dst)
        dst2 = np.empty(nnz + len(rows), old_dst.dtype)
        w2 = np.empty(nnz + len(rows), old_w.dtype)
        e2 = np.empty(nnz + len(rows), old_e.dtype)
        dest_old = np.arange(nnz) + np.repeat(excl[:-1], old_cnt)
        dst2[dest_old] = old_dst
        w2[dest_old] = old_w
        e2[dest_old] = old_e
        dest_new = np.repeat(
            new_ip[:-1] + old_cnt, add_cnt
        ) + _segment_arange(add_cnt)
        dst2[dest_new] = other
        w2[dest_new] = w
        e2[dest_new] = eidx
        self.put(f"{tag}_{t}_indptr", new_ip)
        self.put(f"{tag}_{t}_dst", dst2)
        self.put(f"{tag}_{t}_w", w2)
        self.put(f"{tag}_{t}_eidx", e2)

    # -- phase 3: edge deletes -------------------------------------------

    def edge_deletes(self, batches: list[_EdgeDeleteBatch]) -> None:
        if not batches:
            return
        osrc = np.concatenate([b.osrc for b in batches])
        odst = np.concatenate([b.odst for b in batches])
        ott = np.concatenate([b.ott for b in batches])
        isrc = np.concatenate([b.isrc for b in batches])
        idst = np.concatenate([b.idst for b in batches])
        itt = np.concatenate([b.itt for b in batches])
        tmp = self._tmp_store()
        del_eidx = np.empty(0, np.int64)
        if len(osrc):
            osrc, odst, ott = _dedupe_triples(osrc, odst, ott)
            trip = np.stack([osrc, odst, ott.astype(np.uint64)], axis=1)
            eidx = tmp._edge_rows(trip)
            del_eidx = np.unique(eidx[eidx >= 0])
            rows = tmp.lookup(osrc)
            self.mutated.append(rows[rows >= 0].astype(np.int64))
            self.touched_ids.append(osrc)
            self.touched_ids.append(odst)
        e_total = len(self.A["edge_src"])
        keep = np.ones(e_total, bool)
        keep[del_eidx] = False
        remap = np.cumsum(keep, dtype=np.int64) - 1
        if len(del_eidx):
            self.put("edge_src", self.A["edge_src"][keep])
            self.put("edge_dst", self.A["edge_dst"][keep])
            self.put("edge_types", self.A["edge_types"][keep])
            self.put("edge_weights", self.A["edge_weights"][keep])
            for spec in self.meta.edge_features.values():
                if spec.kind == DENSE:
                    k = f"ef_dense_{spec.fid}"
                    self.put(k, self.A[k][keep])
                else:
                    prefix = "sparse" if spec.kind == "sparse" else "bin"
                    kip = f"ef_{prefix}_{spec.fid}_indptr"
                    kv = f"ef_{prefix}_{spec.fid}_values"
                    ip = self.A[kip]
                    lens = np.diff(ip)
                    self.put(
                        kip,
                        np.concatenate(
                            [[0], np.cumsum(lens[keep])]
                        ).astype(ip.dtype),
                    )
                    self.put(kv, self.A[kv][np.repeat(keep, lens)])
        # in-side matches for cross-shard deletes: (dst row, src, type)
        in_hits: dict[int, list[int]] = {}
        if len(isrc):
            isrc, idst, itt = _dedupe_triples(isrc, idst, itt)
            rows_d = tmp.lookup(idst)
            for s, d, t, r in zip(isrc, idst, itt, rows_d):
                if r < 0:
                    continue
                t = int(t)
                k = f"inadj_{t}_indptr"
                if k not in self.A:
                    continue
                ip = self.A[k]
                lo, hi = int(ip[r]), int(ip[r + 1])
                seg = self.A[f"inadj_{t}_dst"][lo:hi]
                for off in np.nonzero(seg == s)[0]:
                    in_hits.setdefault(t, []).append(lo + int(off))
            self.mutated.append(rows_d[rows_d >= 0].astype(np.int64))
            self.touched_ids.append(idst)
            self.touched_ids.append(isrc)
        if not len(del_eidx) and not in_hits:
            return
        for t in range(self.meta.num_edge_types):
            self._drop_csr_entries(
                "adj", t, del_eidx, remap, extra_positions=()
            )
            self._drop_csr_entries(
                "inadj", t, del_eidx, remap,
                extra_positions=in_hits.get(t, ()),
            )

    def _drop_csr_entries(self, tag, t, del_eidx, remap, extra_positions):
        k = f"{tag}_{t}_indptr"
        if k not in self.A:
            return
        ip = self.A[k]
        ce = self.A[f"{tag}_{t}_eidx"]
        drop = np.zeros(len(ce), bool)
        if len(del_eidx):
            drop |= (ce >= 0) & np.isin(ce, del_eidx)
        if len(extra_positions):
            drop[np.asarray(extra_positions, np.int64)] = True
        if not drop.any() and not len(del_eidx):
            return  # nothing dropped here and no eidx shift to remap
        if drop.any():
            rows_of = np.repeat(
                np.arange(len(ip) - 1, dtype=np.int64), np.diff(ip)
            )
            kept_counts = np.bincount(
                rows_of[~drop], minlength=len(ip) - 1
            )
            self.put(
                k, np.concatenate([[0], np.cumsum(kept_counts)]).astype(
                    ip.dtype
                )
            )
            self.put(f"{tag}_{t}_dst", self.A[f"{tag}_{t}_dst"][~drop])
            self.put(f"{tag}_{t}_w", self.A[f"{tag}_{t}_w"][~drop])
            ce = ce[~drop]
        new_e = np.where(ce >= 0, remap[np.maximum(ce, 0)], -1)
        self.put(f"{tag}_{t}_eidx", new_e.astype(np.int64))

    # -- phase 4: node deletes -------------------------------------------

    def node_deletes(self, batches: list[_NodeDeleteBatch]) -> None:
        if not batches:
            return
        ids = np.unique(np.concatenate([b.ids for b in batches]))
        tmp = self._tmp_store()
        rows = tmp.lookup(ids)
        drop_rows = np.sort(rows[rows >= 0]).astype(np.int64)
        if not len(drop_rows):
            return
        n = len(self.A["node_ids"])
        keep = np.ones(n, bool)
        keep[drop_rows] = False
        self.put("node_ids", self.A["node_ids"][keep])
        self.put("node_types", self.A["node_types"][keep])
        self.put("node_weights", self.A["node_weights"][keep])
        for spec in self.meta.node_features.values():
            if spec.kind == DENSE:
                k = f"nf_dense_{spec.fid}"
                self.put(k, self.A[k][keep])
            else:
                prefix = "sparse" if spec.kind == "sparse" else "bin"
                kip = f"nf_{prefix}_{spec.fid}_indptr"
                kv = f"nf_{prefix}_{spec.fid}_values"
                ip = self.A[kip]
                lens = np.diff(ip)
                self.put(
                    kip,
                    np.concatenate([[0], np.cumsum(lens[keep])]).astype(
                        ip.dtype
                    ),
                )
                self.put(kv, self.A[kv][np.repeat(keep, lens)])
        for t in range(self.meta.num_edge_types):
            for tag in ("adj", "inadj"):
                k = f"{tag}_{t}_indptr"
                if k not in self.A:
                    continue
                ip = self.A[k]
                entry_keep = np.repeat(keep, np.diff(ip))
                self.put(
                    k,
                    np.concatenate(
                        [[0], np.cumsum(np.diff(ip)[keep])]
                    ).astype(ip.dtype),
                )
                self.put(f"{tag}_{t}_dst", self.A[f"{tag}_{t}_dst"][entry_keep])
                self.put(f"{tag}_{t}_w", self.A[f"{tag}_{t}_w"][entry_keep])
                self.put(
                    f"{tag}_{t}_eidx", self.A[f"{tag}_{t}_eidx"][entry_keep]
                )
        # graph-label groups reference node IDS: a deleted node's record
        # (and with it its graph_label feature) is gone from-scratch, so
        # its id drops out of the label grouping too
        gn = self.A.get("glabel_nodes")
        if gn is not None and len(gn):
            gkeep = ~np.isin(gn, ids)
            if not gkeep.all():
                gip = self.A["glabel_indptr"]
                lens = np.diff(gip)
                rows_of = np.repeat(np.arange(len(lens)), lens)
                self.put(
                    "glabel_indptr",
                    np.concatenate(
                        [[0], np.cumsum(
                            np.bincount(rows_of[gkeep], minlength=len(lens))
                        )]
                    ).astype(gip.dtype),
                )
                self.put("glabel_nodes", gn[gkeep])
        self._note_shift(int(drop_rows[0]))
        self.touched_ids.append(ids)

    # -- finish ----------------------------------------------------------

    def finish(self) -> tuple[dict, np.ndarray, np.ndarray]:
        nt = np.asarray(self.A["node_types"])
        nw = np.zeros(self.meta.num_node_types, np.float64)
        if len(nt):
            np.add.at(
                nw, nt, np.asarray(self.A["node_weights"], np.float64)
            )
        et = np.asarray(self.A["edge_types"])
        ew = np.zeros(self.meta.num_edge_types, np.float64)
        if len(et):
            np.add.at(
                ew, et, np.asarray(self.A["edge_weights"], np.float64)
            )
        self.meta.node_weight_sums[self.part] = nw.tolist()
        self.meta.edge_weight_sums[self.part] = ew.tolist()
        n_new = len(self.A["node_ids"])
        parts = [
            m[(m >= 0) & (m < n_new)] for m in self.mutated if len(m)
        ]
        if self.shift_start is not None:
            parts.append(np.arange(self.shift_start, n_new, dtype=np.int64))
        rows = (
            np.unique(np.concatenate(parts))
            if parts
            else np.empty(0, np.int64)
        )
        ids = (
            np.unique(np.concatenate(self.touched_ids))
            if self.touched_ids
            else np.empty(0, np.uint64)
        )
        return self.A, rows.astype(np.int64), ids.astype(np.uint64)


def merge_arrays(
    meta: GraphMeta, arrays: dict, part: int, delta: DeltaStore
) -> tuple[dict, np.ndarray, np.ndarray]:
    """Fold `delta` into a COPY of `arrays` (untouched keys carried by
    reference). Returns (new_arrays, mutated_local_rows, touched_ids):
    rows are in the NEW row space and include every row whose identity
    shifted through an insert/delete; ids are the node ids whose
    blocks (features, neighborhoods, degrees) changed semantically —
    exactly what a client read cache must drop on publish."""
    m = _Merge(meta, arrays, part)
    m.node_upserts(delta._nodes)
    m.edge_upserts(delta._edges)
    m.edge_deletes(delta._edge_dels)
    m.node_deletes(delta._node_dels)
    return m.finish()
